"""Sharded bulk scoring — BASELINE config 4 (1M rows across a v5e-8 slice).

The reference has no batch-scoring path at all (serving is request-at-a-time
FastAPI, `app/main.py:42-86`; the closest artifact is an 80-row
`databricks/data/inference.csv` for ad-hoc tests). This module is the
TPU-native capability the baseline calls for: score an arbitrarily large
encoded dataset by streaming fixed-size chunks through ONE compiled
data-parallel program.

Mechanics (scaling-book recipe):
- a chunk is padded to a fixed shape and jit'd with `in_shardings` that lay
  rows out over the mesh's 'data' axis; params replicate. XLA inserts the
  (trivially few) collectives; every chunk reuses the same executable.
- classifier probabilities and outlier flags are exact per row.
- batch drift is a *dataset-level* statistic: K-S/chi² over millions of rows
  saturates (any tiny shift -> p≈0), so it is computed once over a bounded
  uniform row sample — same semantics as the serving monitor, bounded cost.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from mlops_tpu.bundle.bundle import Bundle
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.monitor.state import drift_scores, outlier_flags
from mlops_tpu.parallel.sharding import batch_sharding, replicated
from mlops_tpu.schema import SCHEMA


@dataclasses.dataclass
class BulkScoreResult:
    predictions: np.ndarray  # float32 [N]
    outliers: np.ndarray  # float32 [N]
    feature_drift: dict[str, float]  # per-feature 1 - p_val on the sample
    rows: int
    elapsed_s: float  # device scoring time (excludes data generation/IO)
    path: str = "exact"  # "exact" | "distilled" — which params scored

    @property
    def rows_per_s(self) -> float:
        return self.rows / max(self.elapsed_s, 1e-9)

    def summary(self) -> dict[str, Any]:
        return {
            "rows": self.rows,
            "path": self.path,
            "elapsed_s": round(self.elapsed_s, 4),
            "rows_per_s": round(self.rows_per_s, 1),
            "default_rate": (
                round(float((self.predictions >= 0.5).mean()), 6) if self.rows else 0.0
            ),
            "outlier_rate": (
                round(float(self.outliers.mean()), 6) if self.rows else 0.0
            ),
            "feature_drift_batch": {
                k: round(v, 6) for k, v in self.feature_drift.items()
            },
        }


def use_distilled_bulk(bundle: Bundle, exact: bool | None = None) -> bool:
    """Routing decision for bulk sweeps: the distilled student
    (`train/distill.py`) scores when the bundle carries one and either the
    caller asked for it (``exact=False``) or — the auto default — the
    backend is a CPU, where the K-member ensemble's FLOPs lose to the
    reference's sklearn floor (BASELINE.md config 1). On a TPU the exact
    ensemble is already fast, so auto keeps it."""
    if exact is True or not bundle.has_bulk:
        return False
    if exact is False:
        return True
    return jax.default_backend() == "cpu"


def make_chunk_scorer(bundle: Bundle, mesh: Mesh | None, exact: bool | None = None):
    """One compiled program: (cat[chunk,C], num[chunk,M], mask[chunk]) ->
    (probs, outlier_flags), fixed-shape per call site (the caller feeds
    equal-sized chunks so a single compile serves the whole sweep).
    Sharded over 'data' when a mesh is given. ``exact`` controls
    distilled-student routing (see ``use_distilled_bulk``)."""
    monitor = bundle.monitor
    temperature = bundle.temperature  # calibration (train/calibrate.py):
    # bulk scores must match what the serving engine would return; the
    # distilled student matched the teacher's LOGITS, so the same
    # temperature applies on either path

    if bundle.flavor == "sklearn":
        estimator = bundle.estimator

        @jax.jit
        def outliers_only(num, mask):
            return outlier_flags(monitor, num, mask)

        from mlops_tpu.train.calibrate import apply_temperature

        def score_chunk(cat, num, mask):
            probs = np.zeros(mask.shape[0], np.float32)
            p = estimator.predict_proba(cat[mask], num[mask])
            probs[mask] = apply_temperature(p, temperature)
            return probs, np.asarray(outliers_only(num, mask))

        return score_chunk

    if use_distilled_bulk(bundle, exact):
        model, variables = bundle.bulk_model, bundle.bulk_variables
    else:
        model, variables = bundle.model, bundle.variables

    def fused(variables, cat, num, mask):
        # cat ids travel as int8 (max vocab cardinality is 12; lossless)
        # and widen on device: host->device bandwidth is the bulk
        # bottleneck on remote-attached chips (~20 MB/s measured), and
        # int8 cuts the categorical block's bytes 4x.
        logits = model.apply(variables, cat.astype(jnp.int32), num, train=False)
        return jax.nn.sigmoid(logits / temperature), outlier_flags(monitor, num, mask)

    if mesh is None:
        return _bind_vars(jax.jit(fused), variables)
    data_in = batch_sharding(mesh)
    mask_in = batch_sharding(mesh, ndim=1)
    fn = jax.jit(
        fused,
        in_shardings=(replicated(mesh), data_in, data_in, mask_in),
        out_shardings=(batch_sharding(mesh, ndim=1), batch_sharding(mesh, ndim=1)),
    )
    return _bind_vars(fn, variables)


def _bind_vars(fn, variables):
    def score_chunk(cat, num, mask):
        probs, flags = fn(variables, cat, num, mask)
        return probs, flags

    return score_chunk


def score_dataset(
    bundle: Bundle,
    ds: EncodedDataset,
    mesh: Mesh | None = None,
    chunk_rows: int = 131_072,
    drift_sample: int = 65_536,
    seed: int = 0,
    exact: bool | None = None,
) -> BulkScoreResult:
    """Stream ``ds`` through the chunk scorer; aggregate monitors.

    ``exact=None`` auto-routes through the distilled bulk student on CPU
    backends when the bundle carries one (``use_distilled_bulk``);
    ``exact=True`` forces the serving-identical ensemble."""
    path = "distilled" if use_distilled_bulk(bundle, exact) else "exact"
    n = ds.n
    if n == 0:
        # Same guard as the serving engine: an empty dataset has no drift
        # signal and must not emit NaN rates into the JSON summary.
        return BulkScoreResult(
            predictions=np.empty(0, np.float32),
            outliers=np.empty(0, np.float32),
            feature_drift=dict.fromkeys(SCHEMA.feature_names, 0.0),
            rows=0,
            elapsed_s=0.0,
        )
    axis = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    chunk = max(axis, (chunk_rows // axis) * axis)
    scorer = make_chunk_scorer(bundle, mesh, exact)

    predictions = np.empty(n, np.float32)
    outliers = np.empty(n, np.float32)

    # Warm the executable before the timed run. The host tree ensemble has
    # nothing to compile, so sklearn-flavor warmup scores a single row.
    warm_rows = 1 if bundle.flavor == "sklearn" else chunk
    warm_dtype = np.int8 if bundle.flavor != "sklearn" else np.int32
    cat0 = np.zeros((chunk, SCHEMA.num_categorical), warm_dtype)
    num0 = np.zeros((chunk, SCHEMA.num_numeric), np.float32)
    jax.block_until_ready(
        scorer(cat0, num0, np.arange(chunk) < warm_rows)[0]
    )

    # Pipeline the sweep in bounded waves: dispatch up to ``wave`` chunks
    # (JAX queues the host->device copies and kernels asynchronously),
    # then fetch the wave's results in one batched device_get. Blocking
    # per chunk would pay a full transport round trip each (~70 ms on a
    # tunnel-attached chip); batching fetches amortizes that to one round
    # trip per wave, while the bound keeps in-flight input buffers from
    # growing with dataset size (unbounded dispatch of a 10M-row sweep
    # would hold every chunk's buffers live on the device at once).
    wave = 32
    t0 = time.perf_counter()
    spans: list[tuple[int, int]] = []
    device_outs = []

    def drain() -> None:
        for (start, stop), (probs, flags) in zip(
            spans, jax.device_get(device_outs)
        ):
            size = stop - start
            predictions[start:stop] = probs[:size]
            outliers[start:stop] = flags[:size]
        spans.clear()
        device_outs.clear()

    narrow = (
        np.int8 if bundle.flavor != "sklearn" else ds.cat_ids.dtype
    )  # host trees index with the original ids; device path widens in-jit
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        size = stop - start
        cat = ds.cat_ids[start:stop].astype(narrow)
        num = ds.numeric[start:stop]
        if size < chunk:
            cat = np.pad(cat, ((0, chunk - size), (0, 0)))
            num = np.pad(num, ((0, chunk - size), (0, 0)))
        mask = np.arange(chunk) < size
        spans.append((start, stop))
        device_outs.append(scorer(cat, num, mask))
        if len(device_outs) >= wave:
            drain()
    drain()
    elapsed = time.perf_counter() - t0

    # Dataset-level drift on a bounded uniform sample (see module docstring).
    take = min(n, drift_sample)
    idx = (
        np.random.default_rng(seed).choice(n, take, replace=False)
        if take < n
        else np.arange(n)
    )
    drift = np.asarray(
        drift_scores(
            bundle.monitor, ds.cat_ids[idx], ds.numeric[idx], np.ones(take, bool)
        )
    )
    return BulkScoreResult(
        predictions=predictions,
        outliers=outliers,
        feature_drift=dict(
            zip(SCHEMA.feature_names, drift.astype(float).tolist())
        ),
        rows=n,
        elapsed_s=elapsed,
        path=path,
    )
