"""Sharded bulk scoring — BASELINE config 4 (1M rows across a v5e-8 slice).

The reference has no batch-scoring path at all (serving is request-at-a-time
FastAPI, `app/main.py:42-86`; the closest artifact is an 80-row
`databricks/data/inference.csv` for ad-hoc tests). This module is the
TPU-native capability the baseline calls for: score an arbitrarily large
encoded dataset by streaming fixed-size chunks through ONE compiled
data-parallel program.

Mechanics (scaling-book recipe):
- a chunk is padded to a fixed shape and jit'd with `in_shardings` that lay
  rows out over the mesh's 'data' axis; params replicate. XLA inserts the
  (trivially few) collectives; every chunk reuses the same executable.
- classifier probabilities and outlier flags are exact per row.
- batch drift is a *dataset-level* statistic: K-S/chi² over millions of rows
  saturates (any tiny shift -> p≈0), so it is computed once over a bounded
  uniform row sample — same semantics as the serving monitor, bounded cost.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from mlops_tpu.bundle.bundle import Bundle
from mlops_tpu.data.encode import EncodedDataset
from mlops_tpu.monitor.state import drift_scores, outlier_flags
from mlops_tpu.parallel.sharding import batch_sharding, replicated
from mlops_tpu.schema import SCHEMA

# Chunks a batched fetch stage may drain in one device_get (and how far
# the compute stage may dispatch ahead of it) — the wave bound that
# amortizes the per-fetch transport round trip on remote-attached chips
# while capping in-flight device buffers.
FETCH_WAVE = 32


def mesh_chunk_rows(chunk_rows: int, mesh: Mesh | None) -> int:
    """THE one chunk-size rounding rule over a data mesh (round UP to the
    'data' axis, floor one row per shard). score_dataset, the streaming
    scorer (data/stream.py), and the compile-cache warmer
    (compilecache/warmup.py) must all agree, or a pre-warmed
    ``bulk-score-chunk`` artifact's signature never matches the shape the
    run actually dispatches (silent cache miss, full recompile)."""
    if mesh is None:
        return max(1, chunk_rows)
    axis = int(mesh.shape["data"])
    return max(axis, ((chunk_rows + axis - 1) // axis) * axis)


@dataclasses.dataclass
class BulkScoreResult:
    predictions: np.ndarray  # float32 [N]
    outliers: np.ndarray  # float32 [N]
    feature_drift: dict[str, float]  # per-feature 1 - p_val on the sample
    rows: int
    elapsed_s: float  # device scoring time (excludes data generation/IO)
    path: str = "exact"  # "exact" | "distilled" | "quant" — which params scored
    pipeline: dict[str, Any] | None = None  # per-stage busy/occupancy
    # timings from the streaming executor (None for the empty dataset)
    compile_cache: dict[str, Any] | None = None  # hit/miss/bypass counts +
    # per-program compile vs deserialize wall time (compilecache/cache.py)
    # when the sweep ran against a persistent executable cache

    @property
    def rows_per_s(self) -> float:
        return self.rows / max(self.elapsed_s, 1e-9)

    def summary(self) -> dict[str, Any]:
        return {
            "rows": self.rows,
            "path": self.path,
            "elapsed_s": round(self.elapsed_s, 4),
            "rows_per_s": round(self.rows_per_s, 1),
            "default_rate": (
                round(float((self.predictions >= 0.5).mean()), 6) if self.rows else 0.0
            ),
            "outlier_rate": (
                round(float(self.outliers.mean()), 6) if self.rows else 0.0
            ),
            "feature_drift_batch": {
                k: round(v, 6) for k, v in self.feature_drift.items()
            },
            **(
                {"pipeline": self.pipeline} if self.pipeline is not None else {}
            ),
            **(
                {"compile_cache": self.compile_cache}
                if self.compile_cache is not None
                else {}
            ),
        }


def use_distilled_bulk(bundle: Bundle, exact: bool | None = None) -> bool:
    """Routing decision for bulk sweeps: the distilled student
    (`train/distill.py`) scores when the bundle carries one and either the
    caller asked for it (``exact=False``) or — the auto default — the
    backend is a CPU, where the K-member ensemble's FLOPs lose to the
    reference's sklearn floor (BASELINE.md config 1). On a TPU the exact
    ensemble is already fast, so auto keeps it."""
    if exact is True or not bundle.has_bulk:
        return False
    if exact is False:
        return True
    return jax.default_backend() == "cpu"


def use_quant_bulk(bundle: Bundle, tier: str = "exact") -> bool:
    """Quant-tier routing for bulk sweeps — the same demand-vs-preference
    semantics as `serve/engine.py _resolve_tier`: ``tier="quant"`` is a
    DEMAND (raises when the bundle has no gate-passed quant tree — an
    explicit ask is never silently downgraded), ``"auto"`` takes quant
    when it is there and gated, ``"exact"`` never routes here. Unlike the
    serve tier there is no shard restriction: bulk quant is data-parallel
    (params replicate over the 'data' axis like every other bulk path)."""
    if tier not in ("exact", "quant", "auto"):
        raise ValueError(f"tier must be exact|quant|auto, got {tier!r}")
    if tier == "exact":
        return False
    eligible = (
        bundle.flavor != "sklearn"
        and bundle.has_quant
        and bundle.quant_gates_passed
    )
    if tier == "quant" and not eligible:
        raise ValueError(
            "tier='quant' refused: bundle carries no gate-passed quant "
            "params (train with train.distill_quant=true)"
        )
    return eligible


def make_chunk_scorer(
    bundle: Bundle,
    mesh: Mesh | None,
    exact: bool | None = None,
    compile_cache=None,
    chunk_rows: int | None = None,
    tier: str = "exact",
):
    """One compiled program: (cat[chunk,C], num[chunk,M], mask[chunk]) ->
    (probs, outlier_flags), fixed-shape per call site (the caller feeds
    equal-sized chunks so a single compile serves the whole sweep).
    Sharded over 'data' when a mesh is given. ``exact`` controls
    distilled-student routing (see ``use_distilled_bulk``); ``tier``
    routes the int8/bf16 quant student (``use_quant_bulk``) and, when it
    routes, takes precedence over the exact/distilled pair.

    With ``compile_cache`` + ``chunk_rows``, the chunk program is AOT
    loaded through the persistent executable cache (`compilecache/` entry
    ``bulk-score-chunk``: deserialize on hit, compile+persist on miss);
    chunks at any OTHER shape fall back to the jitted program, so the
    cached executable can never be fed a signature it was not built for.
    """
    monitor = bundle.monitor
    temperature = bundle.temperature  # calibration (train/calibrate.py):
    # bulk scores must match what the serving engine would return; the
    # distilled student matched the teacher's LOGITS, so the same
    # temperature applies on either path

    if bundle.flavor == "sklearn":
        estimator = bundle.estimator

        @jax.jit
        def outliers_only(num, mask):
            return outlier_flags(monitor, num, mask)

        from mlops_tpu.train.calibrate import apply_temperature

        def score_chunk(cat, num, mask):
            probs = np.zeros(mask.shape[0], np.float32)
            p = estimator.predict_proba(cat[mask], num[mask])
            probs[mask] = apply_temperature(p, temperature)
            return probs, np.asarray(outliers_only(num, mask))

        return score_chunk

    if use_quant_bulk(bundle, tier):
        path = "quant"
        model, variables = None, bundle.quant_params
        temperature = bundle.quant_temperature  # the quant tier carries
        # its OWN post-distillation refit (train/calibrate.py) — the
        # student's logit scale is not the teacher's
        fn = make_bulk_quant_jit(mesh)
    elif use_distilled_bulk(bundle, exact):
        path = "distilled"
        model, variables = bundle.bulk_model, bundle.bulk_variables
        fn = make_bulk_jit(model, mesh)
    else:
        path = "exact"
        model, variables = bundle.model, bundle.variables
        fn = make_bulk_jit(model, mesh)
    # device_put the per-call program state ONCE (replicated over the mesh
    # when sharded): params/monitor travel as arguments now, and host
    # arrays would re-pay the transfer every chunk.
    rep = replicated(mesh) if mesh is not None else None
    place = (lambda x: jax.device_put(x, rep)) if rep else jax.device_put
    variables = place(variables)
    monitor = place(monitor)
    t = place(np.float32(temperature))
    aot = None
    if compile_cache is not None and chunk_rows:
        if path == "quant":
            from mlops_tpu.compilecache.warmup import bulk_quant_chunk_job

            job = bulk_quant_chunk_job(
                variables, monitor, chunk_rows, mesh, jitted=fn
            )
        else:
            from mlops_tpu.compilecache.warmup import bulk_chunk_job

            job = bulk_chunk_job(
                model,
                bundle.model_config,
                variables,
                monitor,
                chunk_rows,
                mesh,
                path_label=path,
                jitted=fn,
            )
        aot = compile_cache.load_or_compile(job)

    def score_chunk(cat, num, mask):
        run = aot if (aot is not None and cat.shape[0] == chunk_rows) else fn
        probs, flags = run(variables, monitor, t, cat, num, mask)
        return probs, flags

    return score_chunk


def make_bulk_jit(model, mesh: Mesh | None):
    """The jitted (and, with a mesh, data-sharded) bulk chunk program —
    the ONE jit site the compile cache warms (`compilecache/warmup.py
    bulk_chunk_job`) and ``make_chunk_scorer`` dispatches."""
    fused = make_bulk_fused(model)
    if mesh is None:
        return jax.jit(fused)
    data_in = batch_sharding(mesh)
    mask_in = batch_sharding(mesh, ndim=1)
    rep = replicated(mesh)
    return jax.jit(
        fused,
        in_shardings=(rep, rep, rep, data_in, data_in, mask_in),
        out_shardings=(batch_sharding(mesh, ndim=1), batch_sharding(mesh, ndim=1)),
    )


def make_bulk_fused(model):
    """The ONE fused bulk program — classifier probabilities + outlier
    flags in a single dispatch — shared by ``make_chunk_scorer``, the
    compile cache, and the tpulint Layer-2 registry
    (`analysis/entrypoints.py bulk-score-chunk`), so the jaxpr the
    analyzer gates is the program production compiles. Params, monitor
    state, and temperature are ARGUMENTS (cacheable form: a closed-over
    array would be baked into the serialized executable — see
    `ops/predict.py make_padded_predict_base`)."""

    def fused(variables, monitor, temperature, cat, num, mask):
        # cat ids travel as int8 (max vocab cardinality is 12; lossless)
        # and widen on device: host->device bandwidth is the bulk
        # bottleneck on remote-attached chips (~20 MB/s measured), and
        # int8 cuts the categorical block's bytes 4x.
        logits = model.apply(variables, cat.astype(jnp.int32), num, train=False)
        return jax.nn.sigmoid(logits / temperature), outlier_flags(monitor, num, mask)

    return fused


def make_bulk_quant_fused():
    """Quant-tier bulk chunk body: the int8/bf16 student
    (`ops/quant.py quant_student_logits` — dequantized in-jit, f32
    compute) in place of the flax ensemble, same ``(probs, flags)``
    contract and the same cacheable argument discipline as
    `make_bulk_fused`. ``variables`` is the quant param DICT; the chunk
    program stays tier-keyed in the compile cache via
    ``path_label="quant"`` plus the quant geometry fingerprint
    (`compilecache/warmup.py bulk_quant_chunk_job`)."""
    from mlops_tpu.ops.quant import quant_student_logits

    def fused(variables, monitor, temperature, cat, num, mask):
        logits = quant_student_logits(variables, cat.astype(jnp.int32), num)
        return jax.nn.sigmoid(logits / temperature), outlier_flags(monitor, num, mask)

    return fused


def make_bulk_quant_jit(mesh: Mesh | None):
    """Quant twin of `make_bulk_jit` — the ONE jit site for the quant bulk
    chunk program (whitelisted in `compilecache/registry.py
    CACHED_JIT_BUILDERS`). Data-parallel like the exact path: rows shard
    over 'data', the quant tree replicates (its int8/bf16 leaves are a few
    KB — replication is free; there is no model axis in this tier)."""
    fused = make_bulk_quant_fused()
    if mesh is None:
        return jax.jit(fused)
    data_in = batch_sharding(mesh)
    mask_in = batch_sharding(mesh, ndim=1)
    rep = replicated(mesh)
    return jax.jit(
        fused,
        in_shardings=(rep, rep, rep, data_in, data_in, mask_in),
        out_shardings=(batch_sharding(mesh, ndim=1), batch_sharding(mesh, ndim=1)),
    )


def make_chunk_transfer(bundle: Bundle, mesh: Mesh | None):
    """Stage-3 device placement for the pipelined executors
    (`data/pipeline_exec.py`): ``jax.device_put`` the NEXT chunk's host
    arrays — with the mesh's data-parallel shardings when given, so the
    jitted scorer consumes them zero-copy — while the current chunk
    computes (double buffering). The sklearn flavor scores on host; its
    transfer is the identity."""
    if bundle.flavor == "sklearn":
        return lambda cat, num, mask: (cat, num, mask)
    if mesh is None:
        def place(cat, num, mask):
            return jax.device_put(cat), jax.device_put(num), jax.device_put(mask)

        return place
    data_in = batch_sharding(mesh)
    mask_in = batch_sharding(mesh, ndim=1)

    def place_sharded(cat, num, mask):
        return (
            jax.device_put(cat, data_in),
            jax.device_put(num, data_in),
            jax.device_put(mask, mask_in),
        )

    return place_sharded


def score_dataset(
    bundle: Bundle,
    ds: EncodedDataset,
    mesh: Mesh | None = None,
    chunk_rows: int = 131_072,
    drift_sample: int = 65_536,
    seed: int = 0,
    exact: bool | None = None,
    pipeline_depth: int = 2,
    compile_cache=None,
    tier: str = "exact",
) -> BulkScoreResult:
    """Stream ``ds`` through the chunk scorer; aggregate monitors.

    The sweep runs on the pipelined streaming executor
    (`data/pipeline_exec.py`): chunk slicing/padding, host->device
    transfer, device dispatch, and batched result fetch each occupy their
    own stage, so chunk N+1 transfers while chunk N computes and chunk
    N-1's results fetch — with bounded queues keeping in-flight buffers
    at a few chunks regardless of dataset size. ``pipeline_depth=1``
    degrades to the strict serial loop (bit-identical results; the
    executor preserves chunk order at any depth).

    ``exact=None`` auto-routes through the distilled bulk student on CPU
    backends when the bundle carries one (``use_distilled_bulk``);
    ``exact=True`` forces the serving-identical ensemble. ``tier``
    ("exact"|"quant"|"auto") routes the int8/bf16 quant student
    (``use_quant_bulk``) ahead of both."""
    from mlops_tpu.data.pipeline_exec import Stage, run_pipeline

    if use_quant_bulk(bundle, tier):
        path = "quant"
    elif use_distilled_bulk(bundle, exact):
        path = "distilled"
    else:
        path = "exact"
    n = ds.n
    if n == 0:
        # Same guard as the serving engine: an empty dataset has no drift
        # signal and must not emit NaN rates into the JSON summary.
        return BulkScoreResult(
            predictions=np.empty(0, np.float32),
            outliers=np.empty(0, np.float32),
            feature_drift=dict.fromkeys(SCHEMA.feature_names, 0.0),
            rows=0,
            elapsed_s=0.0,
        )
    chunk = mesh_chunk_rows(chunk_rows, mesh)
    scorer = make_chunk_scorer(
        bundle, mesh, exact, compile_cache=compile_cache, chunk_rows=chunk,
        tier=tier,
    )
    transfer = make_chunk_transfer(bundle, mesh)

    predictions = np.empty(n, np.float32)
    outliers = np.empty(n, np.float32)

    # Warm the executable before the timed run. The host tree ensemble has
    # nothing to compile, so sklearn-flavor warmup scores a single row.
    warm_rows = 1 if bundle.flavor == "sklearn" else chunk
    warm_dtype = np.int8 if bundle.flavor != "sklearn" else np.int32
    cat0 = np.zeros((chunk, SCHEMA.num_categorical), warm_dtype)
    num0 = np.zeros((chunk, SCHEMA.num_numeric), np.float32)
    jax.block_until_ready(
        scorer(cat0, num0, np.arange(chunk) < warm_rows)[0]
    )

    narrow = (
        np.int8 if bundle.flavor != "sklearn" else ds.cat_ids.dtype
    )  # host trees index with the original ids; device path widens in-jit
    base_index = np.arange(chunk)
    full_mask = np.ones(chunk, bool)

    def slice_chunk(span):
        start, stop = span
        size = stop - start
        cat = ds.cat_ids[start:stop].astype(narrow)
        num = ds.numeric[start:stop]
        if size < chunk:
            cat = np.pad(cat, ((0, chunk - size), (0, 0)))
            num = np.pad(num, ((0, chunk - size), (0, 0)))
            mask = base_index < size
        else:
            mask = full_mask
        return start, stop, cat, num, mask

    def transfer_chunk(item):
        start, stop, cat, num, mask = item
        return (start, stop, *transfer(cat, num, mask))

    def compute_chunk(item):
        start, stop, cat, num, mask = item
        return (start, stop, *scorer(cat, num, mask))

    def fetch_chunks(items):
        # Batched fetch: one device_get round trip for everything already
        # dispatched (~70 ms each on a tunnel-attached chip if paid per
        # chunk). The executor bounds the gather at the queue depth, so
        # in-flight device buffers stay fixed regardless of dataset size.
        fetched = jax.device_get(
            [(probs, flags) for _, _, probs, flags in items]
        )
        return [
            (start, stop, probs, flags)
            for (start, stop, _, _), (probs, flags) in zip(items, fetched)
        ]

    def store_chunk(item):
        start, stop, probs, flags = item
        size = stop - start
        predictions[start:stop] = probs[:size]
        outliers[start:stop] = flags[:size]

    spans = (
        (start, min(start + chunk, n)) for start in range(0, n, chunk)
    )
    pipe = run_pipeline(
        spans,
        [
            Stage("slice", slice_chunk),
            Stage("transfer", transfer_chunk),
            Stage("compute", compute_chunk),
            # The fetch stage keeps the old wave semantics: its deep input
            # queue lets the compute stage dispatch up to FETCH_WAVE chunks
            # ahead (JAX queues the copies/kernels asynchronously) and one
            # batched device_get drains them — one transport round trip
            # per wave instead of per chunk (~70 ms each on a
            # tunnel-attached chip), independent of pipeline_depth.
            # batch_max >= 2 also keeps fetch in list-in/list-out mode at
            # depth 1 (the gather is still at most one item there).
            Stage(
                "fetch",
                fetch_chunks,
                batch_max=FETCH_WAVE,
                queue_depth=FETCH_WAVE,
            ),
        ],
        store_chunk,
        depth=pipeline_depth,
        source_name="span",
        sink_name="store",
    )
    elapsed = pipe.wall_s

    # Dataset-level drift on a bounded uniform sample (see module docstring).
    take = min(n, drift_sample)
    idx = (
        np.random.default_rng(seed).choice(n, take, replace=False)
        if take < n
        else np.arange(n)
    )
    drift = np.asarray(
        drift_scores(
            bundle.monitor, ds.cat_ids[idx], ds.numeric[idx], np.ones(take, bool)
        )
    )
    return BulkScoreResult(
        predictions=predictions,
        outliers=outliers,
        feature_drift=dict(
            zip(SCHEMA.feature_names, drift.astype(float).tolist())
        ),
        rows=n,
        elapsed_s=elapsed,
        path=path,
        pipeline=pipe.as_dict(),
        compile_cache=(
            compile_cache.stats() if compile_cache is not None else None
        ),
    )
