"""Sharding specs: batch layouts + regex partition rules for param trees.

Megatron-style tensor parallelism for the dense trunks: the first matmul of
each block is column-split (output features over 'model'), the second is
row-split (input features over 'model'); XLA inserts the psum on the row-cut
output. Embeddings and norms replicate (tiny). The same rules serve MLP and
FT-Transformer because both name their projections accordingly.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins; default replicate.
PARAM_RULES: tuple[tuple[str, P], ...] = (
    # MLP residual blocks: a = column-parallel, b = row-parallel.
    (r"dense_\d+a/kernel", P(None, "model")),
    (r"dense_\d+b/kernel", P("model", None)),
    (r"stem/kernel", P(None, None)),
    # Transformer attention (MultiHeadSelfAttention: qkv kernel
    # [embed, 3, heads, head_dim], out kernel [heads, head_dim, embed]):
    # shard the heads axis.
    (r"Attention_\d+/qkv/kernel", P(None, None, "model", None)),
    (r"Attention_\d+/out/kernel", P("model", None, None)),
    # FT-Transformer MLP: Dense_0 widens (column), Dense_1 narrows (row).
    (r"block_\d+/Dense_0/kernel", P(None, "model")),
    (r"block_\d+/Dense_1/kernel", P("model", None)),
    # MoE: stacked expert weights [E, ...] — EXPERT parallelism: each
    # device holds E/ep experts (spec right-truncates for the 2-d biases).
    (r"experts_", P("model", None, None)),
)


def serve_mesh(shards: int, offset: int = 0) -> Mesh:
    """A ('model',)-only mesh over ``shards`` devices starting at
    ``offset`` — the serving-side tensor/expert-parallel layout
    (ISSUE 13 ``serve.model_shards``): params shard by `PARAM_RULES`,
    activations and the monitor accumulator replicate, and XLA inserts
    the psums the Megatron column/row cuts imply. No 'data' axis:
    request fan-out is the ENGINE REPLICA SET's job (process-level DP)
    — ``offset`` is how replica r takes ITS device slice
    (``devices[r*S : (r+1)*S]``) when one process's visibility spans
    the whole fleet's devices."""
    import numpy as np

    devices = jax.devices()
    if offset + shards > len(devices):
        raise ValueError(
            f"serve.model_shards={shards} at device offset {offset} "
            f"exceeds the {len(devices)} visible devices in this engine "
            "process"
        )
    return Mesh(np.asarray(devices[offset : offset + shards]), ("model",))


def sharded_avals(tree: Any) -> Any:
    """Concrete COMMITTED pytree -> ShapeDtypeStruct pytree carrying each
    leaf's live sharding: AOT warmup lowers against these so the cached
    executable bakes the same layout the engine's resident state has —
    a sharded engine deserializing an unsharded artifact (or vice versa)
    is excluded by the cache key's mesh_shape axis before it could even
    mismatch here."""

    def aval(leaf):
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=leaf.sharding
        )

    return jax.tree_util.tree_map(aval, tree)


def replicated_avals(tree: Any, mesh: Mesh) -> Any:
    """Abstract pytree -> the same avals pinned to full replication over
    ``mesh`` (batch inputs, the temperature scalar, the accumulator)."""
    sharding = replicated(mesh)

    def aval(leaf):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sharding)

    return jax.tree_util.tree_map(aval, tree)


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) axis over 'data'; trailing axes replicated."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def param_shardings(
    mesh: Mesh,
    params: Any,
    rules: tuple[tuple[str, P], ...] = PARAM_RULES,
) -> Any:
    """Map a param pytree to NamedShardings via regex rules (default:
    replicate). Specs with more axes than the leaf are right-truncated."""

    def assign(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, path_s):
                if leaf.ndim > len(spec):
                    # Extra LEADING axes (deep-ensemble member axis, vmapped
                    # HPO trial axis) replicate; the rule's axes stay aligned
                    # to the kernel's own trailing dims.
                    spec = P(*([None] * (leaf.ndim - len(spec)) + list(spec)))
                trimmed = P(*spec[: leaf.ndim])
                # Drop 'model' axes that don't divide the dim (tiny leaves).
                sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
                cleaned = []
                for dim, axis in zip(leaf.shape, trimmed):
                    if axis is not None and dim % sizes.get(axis, 1):
                        cleaned.append(None)
                    else:
                        cleaned.append(axis)
                return NamedSharding(mesh, P(*cleaned))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, params)
