"""mlops_tpu — a TPU-native MLOps framework.

Brand-new implementation (JAX/XLA/Flax/optax/Pallas) of the capabilities of
the reference MLOps proof-of-concept (``nfmoore/databricks-kubernetes-mlops-poc``):
train a credit-card-default classifier with hyperparameter search and tracked
metrics, package it as a versioned bundle pairing the model with drift and
outlier detectors, serve it over HTTP ``POST /predict`` with structured
per-request JSON logging, and promote it through a containerized
staging -> smoke-test -> gated-production pipeline.

Layer map (mirrors SURVEY.md SS1, re-based on TPU):

- ``schema``   single source of truth for the 23-feature contract
  (reference duplicates it three times: notebooks 01/02 cell 4 and
  ``app/model.py:8-34``).
- ``data``     CSV/Parquet ingest + synthetic generator + stats fit +
  fixed-shape device encoding (replaces Spark external table,
  ``databricks/src/00-create-external-table.ipynb``).
- ``models``   Flax model zoo (MLP, FT-Transformer, linear) — replaces the
  sklearn RandomForest pipeline (``01-train-model.ipynb:195-227``).
- ``ops``      pure-JAX / Pallas numerics: drift tests, outlier scores,
  fused predict.
- ``monitor``  drift + outlier detector fit/state (replaces alibi-detect
  TabularDrift + IForest, ``02-register-model.ipynb:225-233``).
- ``train``    optax loop under jit/pjit, vmapped+sharded HPO (replaces
  hyperopt fmin, ``01-train-model.ipynb:333-360``).
- ``bundle``   versioned model bundle + registry (replaces the MLflow pyfunc
  CustomModel + model registry, ``02-register-model.ipynb:305-353,461-470``).
- ``parallel`` device mesh / sharding / collectives helpers (the reference has
  no distributed compute at all — SURVEY.md SS2.7).
- ``serve``    asyncio HTTP server + micro-batching engine (replaces
  FastAPI/uvicorn + mlflow pyfunc serving, ``app/main.py``).
"""

from mlops_tpu.version import __version__

__all__ = ["__version__"]
