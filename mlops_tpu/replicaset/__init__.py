"""Engine replica set (ISSUE 13): data-parallel serve fleet on a mesh.

One shm ring, E engine REPLICA processes: the router half lives here
(`ReplicaRouter`, consulted by every front end at submit time); the
transport half is the per-replica queue/doorbell/stats axes grown onto
`serve/ipc.py`; the process half is the supervisor forking E engine
children in `serve/frontend.py`. `replicaset.sim` builds an in-process
E-replica plane over simulated-device engines for the bench's scaling
stage and the unit tests (imported explicitly — it pulls serve.ipc,
which this package's import-light half must not).

Jax-free: front ends import the router; nothing here touches a device.
"""

from mlops_tpu.replicaset.router import ReplicaRouter

__all__ = ["ReplicaRouter"]
