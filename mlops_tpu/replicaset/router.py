"""ReplicaRouter: per-submit engine-replica choice for the ring plane.

Policy (ISSUE 13): least-loaded by LIVE ring depth — the per-(worker,
replica) ``rep_inflight`` gauge cells, summed per replica — with a
deterministic lowest-index tie-break, plus PER-(TENANT, CLASS) AFFINITY
on the coalescable small class: grouped coalescing only pays when
concurrent batch-1 requests of one tenant land on the SAME replica's
collector inside one pop window, so the small class sticks to its last
choice until that replica dies, un-readies, or falls more than
``affinity_slack`` slots behind the least-loaded candidate. The large
(solo-dispatch) class has nothing to coalesce and always takes the
least-loaded live replica.

Dead replicas are routed AROUND (readiness words in shm, cleared by the
supervisor at death): their busy slots replay on the respawned
incarnation while fresh admissions spread over the survivors — a kill
-9 of one replica is a brownout of 1/E capacity, never a wedge. When NO
replica is ready (full outage, or first boot), the router still returns
the least-loaded index so admissions PARK on a concrete queue and the
first replica to attach replays/answers its share.

Event-loop confined per front-end worker (one router per RingClient):
the sticky map is plain worker-local state, and the only shared reads
are single-cell gauge loads — no locks, declared below.
"""

from __future__ import annotations

from typing import Any

# tpulint Layer-3 manifest: lock-free by design — worker-local sticky
# state plus torn-read-tolerant shm gauge loads (a stale depth read
# costs one suboptimal routing choice, never correctness: every replica
# answers every descriptor it is handed).
TPULINT_LOCK_ORDER: dict[str, tuple[str, ...]] = {"ReplicaRouter": ()}

# Slot classes (serve/wire.py geometry; serve/ipc.py SMALL/LARGE): class
# 0 is the coalescable small class the affinity policy targets. Kept as
# a local constant — this module must stay importable without serve.ipc
# (which imports it back).
_SMALL = 0


class ReplicaRouter:
    def __init__(self, ring: Any, affinity_slack: int = 4) -> None:
        self._ring = ring
        self._replicas = int(ring.replicas)
        self._slack = max(0, int(affinity_slack))
        # (tenant, class) -> sticky replica for the coalescable class.
        self._sticky: dict[tuple[int, int], int] = {}

    # ------------------------------------------------------------- signals
    def depth(self, replica: int) -> int:
        """Live ring depth of one replica: slots routed to it and not yet
        released, summed over every front-end worker's gauge cell."""
        return int(self._ring.rep_inflight[:, replica].sum())

    def candidates(self) -> list[int]:
        """Replicas eligible for fresh work: the READY set, or — full
        outage / first boot, when nothing is ready — every replica (the
        submit then parks on a concrete queue and the first replica to
        attach answers it)."""
        ready = [
            r for r in range(self._replicas) if self._ring.rep_ready[r]
        ]
        return ready if ready else list(range(self._replicas))

    # -------------------------------------------------------------- policy
    def route(self, tenant: int, slot_class: int) -> int:
        """The replica index for one submit. Deterministic given the
        gauge state: equal depths break toward the LOWEST index, so unit
        tests (and two workers observing the same state) agree."""
        if self._replicas == 1:
            return 0
        candidates = self.candidates()
        depths = {r: self.depth(r) for r in candidates}
        least = min(candidates, key=lambda r: (depths[r], r))
        if slot_class != _SMALL:
            return least
        key = (int(tenant), int(slot_class))
        sticky = self._sticky.get(key)
        if (
            sticky is not None
            and sticky in depths
            and depths[sticky] <= depths[least] + self._slack
        ):
            return sticky
        self._sticky[key] = least
        return least
