"""In-process E-replica plane over simulated devices (bench + tests).

The replica set's scaling claim is about DEVICE-TIME-bound serving: on
the TPU path every dispatch pays a flat device/transport round trip
(measured ~70-90 ms through the remote-chip tunnel this repo benches
against), and data-parallel replicas hide exactly that wait behind each
other. A CPU CI box cannot demonstrate it with real compute — one core
runs one matmul at a time no matter how many processes ask — so the
bench's replica stage (and the unit tests) drive the REAL ring, router,
and E REAL `RingService` consumers over engines whose device time is a
simulated constant-latency round trip. Host-side work (descriptor
queues, coalescing, scatter, slab writes, doorbells) is all real and
all measured; only the XLA execution is replaced by the latency it
models. ``XLA_FLAGS=--xla_force_host_platform_device_count=E`` is the
companion knob for runs that want E visible jax devices too; this
module itself is jax-free.

Everything here is test/bench harness, not serving code — the
production fleet is `serve_multi_worker` with ``serve.engine_replicas``.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from mlops_tpu.schema import SCHEMA

# Harness-only module: the engines below hold no locks (per-handle state
# only) and the plane builder wires the production classes, whose own
# manifests govern them.
TPULINT_LOCK_ORDER: dict[str, tuple[str, ...]] = {
    "SimulatedDeviceEngine": ()
}


class _Handle:
    __slots__ = ("parts", "sizes", "n")

    def __init__(self, parts=None, sizes=None, n=0):
        self.parts = parts
        self.sizes = sizes
        self.n = n

    def start_copy(self) -> None:
        pass


class SimulatedDeviceEngine:
    """Engine-API stand-in whose device time is a constant-latency sleep.

    Deterministic, input-dependent outputs (predictions are the numeric
    row sums) so routing/parity tests can detect a cross-wired slab; the
    sleep sits in the FETCH — exactly where the real engine blocks on
    the device — so E RingService pool threads overlap E simulated
    round trips the way E replicas overlap E real ones."""

    ready = True
    max_bucket = 64
    supports_grouping = True
    monitor_accumulating = False

    def __init__(self, device_ms: float = 5.0, replica: int = 0) -> None:
        self.device_ms = float(device_ms)
        self.replica = int(replica)
        self._d = SCHEMA.num_categorical + SCHEMA.num_numeric

    # ------------------------------------------------------------- solo
    def dispatch_arrays(self, cat: np.ndarray, num: np.ndarray) -> _Handle:
        return _Handle(parts=[(cat, num)], sizes=[cat.shape[0]],
                       n=cat.shape[0])

    def fetch_arrays_raw(self, handle: _Handle):
        time.sleep(self.device_ms / 1e3)
        cat, num = handle.parts[0]
        pred = num.sum(axis=1).astype(float)
        return pred, np.zeros(handle.n, float), np.zeros(self._d, float)

    # ---------------------------------------------------------- grouped
    def dispatch_group_arrays(
        self, parts: list[tuple[np.ndarray, np.ndarray]]
    ) -> _Handle:
        return _Handle(parts=parts, sizes=[cat.shape[0] for cat, _ in parts])

    def fetch_group_raw(self, handle: _Handle):
        # ONE simulated round trip for the whole coalesced group — the
        # grouping economics the real plane has (requests-per-dispatch
        # is what amortizes the flat transport cost).
        time.sleep(self.device_ms / 1e3)
        rows = max(handle.sizes)
        preds = np.zeros((len(handle.parts), rows), float)
        outs = np.zeros_like(preds)
        drifts = np.zeros((len(handle.parts), self._d), float)
        for i, (cat, num) in enumerate(handle.parts):
            preds[i, : num.shape[0]] = num.sum(axis=1)
        return handle.sizes, preds, outs, drifts


@dataclasses.dataclass
class SimPlane:
    ring: Any
    services: list[Any]
    engines: list[SimulatedDeviceEngine]

    def stop(self) -> None:
        for service in self.services:
            service.stop()
        self.ring.close()


def build_sim_plane(
    replicas: int,
    workers: int = 1,
    slots_small: int = 64,
    slots_large: int = 2,
    device_ms: float = 5.0,
    max_group: int = 16,
    max_inflight: int = 2,
    threads: int = 4,
    start: bool = True,
) -> SimPlane:
    """The production ring + E production `RingService` consumers over
    simulated-device engines, all in this process (no forks — the bench
    measures fan-out mechanics and device-time overlap, not HTTP)."""
    from mlops_tpu.serve.ipc import RequestRing, RingService

    ring = RequestRing(
        workers=workers,
        slots_small=slots_small,
        slots_large=slots_large,
        large_rows=64,
        replicas=replicas,
    )
    engines = [
        SimulatedDeviceEngine(device_ms=device_ms, replica=r)
        for r in range(replicas)
    ]
    services = [
        RingService(
            engines[r],
            ring,
            max_group=max_group,
            max_inflight=max_inflight,
            threads=threads,
            monitor_fetch_every_s=0,
            replica=r,
        )
        for r in range(replicas)
    ]
    if start:
        for r, service in enumerate(services):
            service.reattach()
            service.start()
            ring.set_ready(True, r)
    return SimPlane(ring=ring, services=services, engines=engines)


async def drive_grouped_load(
    plane: SimPlane,
    duration_s: float,
    concurrency: int = 64,
    worker: int = 0,
) -> dict[str, Any]:
    """Hammer batch-1 submissions through one worker's RingClient for
    ``duration_s`` and return grouped-path throughput plus the
    per-replica served split. Call inside a fresh event loop (the client
    is loop-confined); doorbell readers are registered per replica, the
    production topology."""
    import asyncio

    from mlops_tpu.serve.ipc import RingClient
    from mlops_tpu.serve.wire import RESP_OK

    ring = plane.ring
    loop = asyncio.get_running_loop()
    client = RingClient(ring, worker)
    for r in range(ring.replicas):
        loop.add_reader(
            ring.worker_doorbell(worker, r).fileno(),
            client.on_doorbell,
            r,
        )
    cat = np.zeros((1, SCHEMA.num_categorical), np.int32)
    num = np.random.default_rng(7).random(
        (1, SCHEMA.num_numeric)
    ).astype(np.float32)
    expected = float(num.sum())
    served = [0]
    wrong = [0]
    deadline = loop.time() + duration_s
    peak_depth = [0] * ring.replicas
    from mlops_tpu.serve.metrics import MON_ROWS

    # Call-local goodput split: mon rows are cumulative across calls on
    # one plane (a warm pass would otherwise inflate the measured
    # window's per-replica split), so snapshot and difference.
    rows_base = [
        int(ring.mon_vals[r, :, MON_ROWS].sum())
        for r in range(ring.replicas)
    ]

    async def sample_depths() -> None:
        # Mid-run router-observable sample: peak live depth per replica
        # (end-of-run depths are trivially zero).
        while loop.time() < deadline:
            for r in range(ring.replicas):
                depth = int(ring.rep_inflight[:, r].sum())
                if depth > peak_depth[r]:
                    peak_depth[r] = depth
            await asyncio.sleep(0.01)

    async def one_lane() -> None:
        while loop.time() < deadline:
            slot = client.claim(1)
            if slot is None:
                await asyncio.sleep(0)  # shed pressure: yield and retry
                continue
            future = client.submit(slot, cat, num)
            status = await future
            if status == RESP_OK:
                pred, _, _ = client.response_arrays(slot)
                if abs(float(pred[0]) - expected) > 1e-5:
                    wrong[0] += 1
                else:
                    served[0] += 1
            client.release(slot)

    t0 = time.perf_counter()
    await asyncio.gather(
        sample_depths(), *(one_lane() for _ in range(concurrency))
    )
    wall = time.perf_counter() - t0
    for r in range(ring.replicas):
        loop.remove_reader(ring.worker_doorbell(worker, r).fileno())
    per_replica_rows = [
        int(ring.mon_vals[r, :, MON_ROWS].sum()) - rows_base[r]
        for r in range(ring.replicas)
    ]
    return {
        "req_per_s": round(served[0] / wall, 1),
        "served": served[0],
        "wrong": wrong[0],
        "wall_s": round(wall, 3),
        "per_replica_rows": per_replica_rows,
        "per_replica_peak_depth": peak_depth,
    }
