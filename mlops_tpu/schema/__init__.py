"""Feature schema — the single source of truth for the 23-feature contract.

The reference repo duplicates the feature lists three times (training
notebook `databricks/src/01-train-model.ipynb` cell 4, registration notebook
`02-register-model.ipynb` cell 4, and `app/model.py:8-34`). Here the schema is
defined once and everything else — encoders, pydantic I/O models, drift
layout, embedding tables — is generated from it.
"""

from mlops_tpu.schema.features import (
    CATEGORICAL_FEATURES,
    FEATURE_NAMES,
    NUM_CATEGORICAL,
    NUM_FEATURES,
    NUM_NUMERIC,
    NUMERIC_FEATURES,
    TARGET,
    CategoricalFeature,
    FeatureSchema,
    NumericFeature,
    SCHEMA,
)
from mlops_tpu.schema.io_models import (
    FeatureBatchDrift,
    LoanApplicant,
    ModelOutput,
    records_to_columns,
)

__all__ = [
    "CATEGORICAL_FEATURES",
    "FEATURE_NAMES",
    "NUM_CATEGORICAL",
    "NUM_FEATURES",
    "NUM_NUMERIC",
    "NUMERIC_FEATURES",
    "TARGET",
    "CategoricalFeature",
    "FeatureSchema",
    "NumericFeature",
    "SCHEMA",
    "FeatureBatchDrift",
    "LoanApplicant",
    "ModelOutput",
    "records_to_columns",
]
