"""Canonical feature schema for the credit-default task.

Feature names, ordering, and categorical/numeric split match the reference's
serving contract (`app/model.py:8-34`: 9 categorical string features followed
by 14 numeric features). Categorical vocabularies cover the adapted UCI
Credit Card Default dataset values observed in
`databricks/data/inference.csv` plus the full UCI repayment-delay range, with
out-of-vocabulary handling equivalent to the reference's
`OneHotEncoder(handle_unknown="ignore")` (`01-train-model.ipynb:204-209`):
unseen categories map to a dedicated OOV id instead of failing.

Everything downstream is derived from ``SCHEMA``:

- pydantic request/response models (``schema.io_models``)
- the integer/float encoder layout (``data.encode``)
- embedding-table sizes (``models``)
- per-feature drift layout (``monitor.drift``)
"""

from __future__ import annotations

import dataclasses
import hashlib
import json


@dataclasses.dataclass(frozen=True)
class CategoricalFeature:
    """A string-valued feature with a fixed vocabulary.

    Encoded as an int32 id in ``[0, card)``; id ``card - 1`` is the reserved
    out-of-vocabulary bucket (parity with ``handle_unknown="ignore"``).
    """

    name: str
    vocab: tuple[str, ...]
    default: str

    @property
    def card(self) -> int:
        """Cardinality including the OOV bucket."""
        return len(self.vocab) + 1

    @property
    def oov_id(self) -> int:
        return len(self.vocab)

    def encode(self, value: str) -> int:
        try:
            return self.vocab.index(value)
        except ValueError:
            return self.oov_id


@dataclasses.dataclass(frozen=True)
class NumericFeature:
    """A float-valued feature, standardized with train-time mean/std.

    Missing values are imputed with the train-time median (parity with the
    reference's ``SimpleImputer(strategy="median")``,
    `01-train-model.ipynb:195-227`).
    """

    name: str
    default: float


_REPAYMENT_VOCAB: tuple[str, ...] = (
    "duly_paid",
    "no_delay",
    "delay_1_month",
    "delay_2_months",
    "delay_3_months",
    "delay_4_months",
    "delay_5_months",
    "delay_6_months",
    "delay_7_months",
    "delay_8_months",
    "delay_9_months",
)


CATEGORICAL_FEATURES: tuple[CategoricalFeature, ...] = (
    CategoricalFeature("sex", ("male", "female"), "male"),
    CategoricalFeature(
        "education",
        ("graduate_school", "university", "high_school", "others"),
        "university",
    ),
    CategoricalFeature("marriage", ("married", "single", "others"), "married"),
    *(
        CategoricalFeature(
            f"repayment_status_{i}",
            _REPAYMENT_VOCAB,
            "duly_paid" if i <= 4 else "no_delay",
        )
        for i in range(1, 7)
    ),
)

# Numeric defaults follow the reference's LoanApplicant defaults
# (`app/model.py:21-34`) except `age`, whose reference default of 18000.0 is a
# documented copy-paste bug (SURVEY.md SS7 "bugs to not replicate").
NUMERIC_FEATURES: tuple[NumericFeature, ...] = (
    NumericFeature("credit_limit", 18000.0),
    NumericFeature("age", 35.0),
    NumericFeature("bill_amount_1", 764.95),
    NumericFeature("bill_amount_2", 2221.95),
    NumericFeature("bill_amount_3", 1131.85),
    NumericFeature("bill_amount_4", 5074.85),
    NumericFeature("bill_amount_5", 18000.0),
    NumericFeature("bill_amount_6", 1419.95),
    NumericFeature("payment_amount_1", 2236.5),
    NumericFeature("payment_amount_2", 1137.55),
    NumericFeature("payment_amount_3", 5084.55),
    NumericFeature("payment_amount_4", 111.65),
    NumericFeature("payment_amount_5", 306.9),
    NumericFeature("payment_amount_6", 805.65),
)

TARGET = "default_payment_next_month"


@dataclasses.dataclass(frozen=True)
class FeatureSchema:
    """The full feature contract: ordered categorical + numeric features."""

    categorical: tuple[CategoricalFeature, ...]
    numeric: tuple[NumericFeature, ...]
    target: str

    @property
    def feature_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.categorical) + tuple(
            f.name for f in self.numeric
        )

    @property
    def num_categorical(self) -> int:
        return len(self.categorical)

    @property
    def num_numeric(self) -> int:
        return len(self.numeric)

    @property
    def num_features(self) -> int:
        return self.num_categorical + self.num_numeric

    @property
    def cards(self) -> tuple[int, ...]:
        """Embedding-table cardinalities (incl. OOV bucket) per categorical."""
        return tuple(f.card for f in self.categorical)

    def fingerprint(self) -> str:
        """Stable content hash used in bundle manifests for compat checks."""
        payload = json.dumps(
            {
                "categorical": [[f.name, list(f.vocab)] for f in self.categorical],
                "numeric": [f.name for f in self.numeric],
                "target": self.target,
            },
            sort_keys=True,
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:16]


SCHEMA = FeatureSchema(
    categorical=CATEGORICAL_FEATURES,
    numeric=NUMERIC_FEATURES,
    target=TARGET,
)

FEATURE_NAMES = SCHEMA.feature_names
NUM_CATEGORICAL = SCHEMA.num_categorical
NUM_NUMERIC = SCHEMA.num_numeric
NUM_FEATURES = SCHEMA.num_features
