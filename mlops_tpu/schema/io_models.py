"""Pydantic I/O models, generated from the canonical schema.

Wire-compatible with the reference serving contract:

- request body = ``list[LoanApplicant]`` (`app/main.py:43`, `app/model.py:8-34`)
- response = ``ModelOutput{predictions, outliers, feature_drift_batch}``
  (`app/model.py:64-70`), where ``feature_drift_batch`` carries one drift
  score per feature (`app/model.py:37-61`).

Unlike the reference, these classes are *generated* from
``mlops_tpu.schema.features.SCHEMA`` via ``pydantic.create_model`` — no
hand-maintained duplicate field lists — and they do not replicate the
reference's ``@dataclasses.dataclass``-on-``BaseModel`` bug
(`app/model.py:8-9`).
"""

from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict, create_model

from mlops_tpu.schema.features import SCHEMA

_applicant_fields: dict[str, Any] = {}
for _cat in SCHEMA.categorical:
    _applicant_fields[_cat.name] = (str, _cat.default)
for _num in SCHEMA.numeric:
    _applicant_fields[_num.name] = (float, _num.default)

LoanApplicant = create_model(
    "LoanApplicant",
    __config__=ConfigDict(extra="ignore"),
    **_applicant_fields,
)
LoanApplicant.__doc__ = "Loan applicant record (23 features, schema-generated)."

FeatureBatchDrift = create_model(
    "FeatureBatchDrift",
    **{name: (float, ...) for name in SCHEMA.feature_names},
)
FeatureBatchDrift.__doc__ = (
    "Per-feature batch drift score (1 - p_value), one field per feature."
)


class ModelOutput(BaseModel):
    """Response of ``POST /predict`` (parity: `app/model.py:64-70`)."""

    predictions: list[float]
    outliers: list[float]
    feature_drift_batch: FeatureBatchDrift  # type: ignore[valid-type]


def records_to_columns(records: list[Any]) -> dict[str, list]:
    """Pivot a list of LoanApplicant-like records into columnar lists.

    Accepts pydantic models or plain dicts; missing keys take schema defaults.
    """
    columns: dict[str, list] = {name: [] for name in SCHEMA.feature_names}
    for record in records:
        data = record if isinstance(record, dict) else record.__dict__
        for cat in SCHEMA.categorical:
            columns[cat.name].append(str(data.get(cat.name, cat.default)))
        for num in SCHEMA.numeric:
            value = data.get(num.name, num.default)
            columns[num.name].append(float(value) if value is not None else num.default)
    return columns
