"""Zero-copy shared-memory IPC: N HTTP front ends -> E engine replicas.

The multi-worker server plane's transport (ROADMAP item 1): front-end
processes validate + encode requests and place the feature arrays
directly into fixed-slot shared-memory slabs; an engine replica scores
them (coalescing concurrent small requests into one grouped device
dispatch, exactly like the in-process micro-batcher) and writes the raw
response arrays back into the same slot. Only 8-byte descriptors cross a
queue — the arrays never serialize, never copy through a pipe, and never
touch a pickle.

ENGINE REPLICA SET (ISSUE 13, mlops_tpu/replicaset/): the ring fans
descriptors out across E engine REPLICA processes instead of exactly
one. Every queue/doorbell/lock that an engine consumes or produces is
PER REPLICA — replica r owns submission queue r (its own doorbell, its
own credit), pushes completions into its own per-worker completion
queues under its own completion lock, and mirrors its telemetry into
its own row of every engine-written stats block. The front ends'
`ReplicaRouter` (replicaset/router.py) picks the replica per submit:
least-loaded by live ring depth, sticky per (tenant, class) for the
coalescable small class so grouped batching keeps finding same-replica
company. A kill -9 of replica k is therefore a brownout of 1/E
capacity: only k's queues stall, the router routes new admissions
around the hole, and k's respawned incarnation replays exactly the
busy slots tagged ``slot_replica == k`` — no other replica ever blocks
on k's locks, because no replica ever takes another replica's locks.
``replicas=1`` (every pre-replica caller) is the degenerate fleet with
identical layout semantics.

Topology and ownership:

- One anonymous ``mmap`` (MAP_SHARED) created by the parent BEFORE
  forking the front ends — no named segments, no resource-tracker
  cleanup, freed by the kernel when the last process unmaps.
- Every front end owns a fixed PARTITION of the slots (its admission
  queue): slot claim/release is event-loop confined per worker. The only
  cross-process lock a FRONT END ever takes is the submission queue's
  head lock (microseconds of index arithmetic, shared by PRODUCERS
  only); the completion queue's lock belongs to engine threads alone.
  BOTH queue consumers are lock-free — the ordering fence on
  weakly-ordered CPUs is the COUNTED doorbell in each direction (the
  eventfd value carries the number of published entries; a consumer only
  consumes what a drained ring has credited). A kill -9'd front end
  therefore cannot orphan the completion lock and wedge the engine, and
  a kill -9'd ENGINE cannot orphan the submission lock and wedge the
  front ends (ISSUE 11 — the engine never takes it).
- Engine INCARNATION counter (ISSUE 11): the engine process is
  restartable in place. A respawned engine bumps ``eng_vals`` 's
  incarnation word, recovers the completion lock its predecessor may
  have died holding (only engine processes ever take it, and they are
  serialized by the supervisor), seeds its monitor totals from the shm
  aggregate so exported counters stay monotone, and REPLAYS every busy
  slot whose completion never arrived — safe because request slabs hold
  the full pre-encoded input and the packed predict programs are pure
  (same AOT artifacts + same inputs = bit-identical outputs). The engine
  stamps its incarnation into ``resp_incarnation`` alongside every
  completion; consumers drop completions carrying a dead incarnation
  (the replay re-answers those slots) so a half-trustworthy leftover can
  never be double-served.
- Two slot classes per worker: ``small`` slabs hold up to
  ``GROUP_ROW_BUCKET`` rows (the coalescable class — batch-1 traffic),
  ``large`` slabs hold up to ``max_batch`` rows (solo dispatches; small
  requests may overflow into a free large slab, never the reverse).
  Exhausting a class is the load-shed signal: the front end answers
  503 + Retry-After instead of queueing unboundedly.
- Per-slot GENERATION counters: a front end bumps the generation when it
  claims a slot, the engine stamps the response with the request's
  generation, and completions with a stale generation are dropped — a
  crashed-and-restarted front end can never be handed a dead request's
  response, and a crashed front end never wedges the ring (the engine
  always answers into the slab and moves on; nobody has to read it).
- Wakeups are ``eventfd``-style doorbells (``os.eventfd`` where the
  kernel provides it, a non-blocking self-pipe otherwise): one rung by
  front ends when they enqueue, one per worker rung by the engine when
  responses land. Front ends register theirs with the event loop
  (``loop.add_reader``); the engine's collector thread blocks in
  ``select``.

Lock discipline (tpulint Layer 3): the manifest below is checked
statically by `analysis/concurrency.py` and at runtime by the lock
sanitizer in the seeded stress tests (tests/test_frontend.py). Locks
only ever guard INDEX ARITHMETIC — slab reads/writes happen outside
every lock, on slots exclusively owned between claim and release.
"""

from __future__ import annotations

import logging
import mmap
import multiprocessing
import os
import select
import threading
import time
from typing import Any

import numpy as np

from mlops_tpu import faults
from mlops_tpu.schema import SCHEMA
from mlops_tpu.serve.metrics import (
    AUTO_GRID_GEN,
    AUTO_HAS,
    AUTO_HAS_MEAS,
    AUTO_HAS_PRED,
    AUTO_MEAS_GAIN,
    AUTO_PRED_GAIN,
    AUTOTUNE_OUTCOMES,
    ENG_INCARNATION,
    ENG_REPLAYED,
    ENG_ROWS_DISPATCHED,
    ENG_ROWS_LOST,
    LIFE_AUC_DELTA,
    LIFE_BREAKER_OPEN,
    LIFE_BREAKER_TRIPS,
    LIFE_GENERATION,
    LIFE_HAS,
    LIFE_HAS_DELTA,
    LIFE_OUTCOMES,
    LIFE_RESERVOIR,
    LIFE_TRIGGERS,
    MON_BATCHES,
    MON_FETCHED_AT,
    MON_FETCHES,
    MON_HAS,
    MON_OUTLIERS,
    MON_ROWS,
    RING_STATUSES,
    ROB_DEGRADED,
    ROB_EXPIRED_ENGINE,
    ServingMetrics,
)
from mlops_tpu.serve.tierroute import TIERS  # jax-free
from mlops_tpu.serve.wire import (
    GROUP_ROW_BUCKET,
    GROUP_SLOT_BUCKETS,
    RESP_ERROR,
    RESP_EXPIRED,
    RESP_OK,
)

logger = logging.getLogger("mlops_tpu.serve")

# Declared lock order, OUTERMOST FIRST — the single source of truth for
# both halves of tpulint Layer 3 (static: analysis/concurrency.py TPU401;
# runtime: analysis/lockcheck.py in the perturbed stress tests).
#
# RequestRing._submit_locks[r] and ._complete_locks[r] are the
# cross-process locks (one per descriptor queue's head index, PER ENGINE
# REPLICA r). Beyond mutual exclusion they order the producers' stores:
# plain numpy stores alone would only be ordered under x86 TSO, and a
# weakly-ordered CPU (aarch64) could otherwise observe a head bump
# before the slab bytes it advertises. BOTH consumers are LOCK-FREE and
# credit-fenced instead (`Doorbell.ring(count)` / credit-limited
# `pop_submissions` / `pop_completions`): only front ends ever acquire
# a ``_submit_locks`` entry and only replica r's engine threads ever
# acquire ``_complete_locks[r]``, so a kill -9 on either side can never
# orphan a lock any OTHER process needs (ISSUE 11/13 — engine-replica
# death must be a 1/E brownout, not a wedge; the one residual case, a
# dead replica's own completion lock, is recovered by its serialized
# successor in `recover_engine_locks` — the supervisor runs at most one
# incarnation of each replica, and no replica ever takes another
# replica's lock). All queue locks are leaves — nothing is ever
# acquired under them, and none is held across slab writes, doorbells,
# or blocking work. (The per-replica lists are invisible to the static
# TPU401 walk — subscripted locks have no lexical attribute name — so
# the runtime sanitizer in tests/test_replicaset.py wraps each list
# entry explicitly under the names declared here.)
#
# RingService: ``_inflight`` is the dispatch bound, acquired by the
# collector thread and released by the pool thread that finishes the job
# — a cross-method/cross-thread pair exactly like the micro-batcher's
# (declared below for TPU404). ``_mon_lock`` guards the host-side monitor
# fold for engines without a device accumulator; a leaf. The only nesting
# anywhere is (conceptually) holding an ``_inflight`` permit while taking
# a leaf, which the declared order permits.
TPULINT_LOCK_ORDER = {
    # _profile_lock: serializes the /debug/profile claim-LEASE word's
    # read-check-write only (front ends only — never an engine, never
    # the request hot path, never held across the ack poll: channel
    # ownership itself is the shm lease, which expires if its claimant
    # dies); a leaf like the queue locks (nothing is ever acquired under
    # it, and it is never taken while a queue lock is held).
    # _submit_locks/_complete_locks are PER-REPLICA lists; every entry
    # carries its list's name for order purposes (all leaves anyway).
    "RequestRing": ("_submit_locks", "_complete_locks", "_profile_lock"),
    "RingService": ("_inflight", "_mon_lock"),
}
TPULINT_CROSS_METHOD_SEMAPHORES = {"RingService": ("_inflight",)}

# ---------------------------------------------------------------------------
# Layer-4 shm ownership manifest (tpulint TPU501, `analysis/contracts.py`).
#
# Every field of the plan below has exactly one writer ROLE — that is the
# whole crash-survivability argument: a reader never needs a lock against
# a writer it doesn't share a process with, and a dead process can only
# have torn state the ownership map says it was allowed to tear. The
# analyzer classifies every cell-write (`...ring.field[i] = / +=`) by the
# enclosing class/method's role and gates CI on writes from anyone else.
# A tuple value is a DECLARED handoff: each listed role writes the field
# at a distinct protocol phase (e.g. `ctl`: the supervisor arms draining
# and SLO words, front ends stamp trace arming), which is single-writer
# per word even though the block is shared.
TPULINT_SHM_OWNERSHIP = {
    # control + profile lease
    "ctl": ("supervisor", "frontend-worker"),
    "prof_ctl": ("frontend-worker", "engine-replica"),
    "prof_claim": "frontend-worker",
    # replica liveness (replica stamps ready/incarnation; the supervisor
    # clears it when respawning a corpse)
    "rep_ready": ("engine-replica", "supervisor"),
    "rep_inflight": "frontend-worker",
    # submission ring: producer head/entries, consumer tail
    "sub_entries": "frontend-worker",
    "sub_head": "frontend-worker",
    "sub_tail": "engine-replica",
    # completion rings: producer head/entries, consumer tail
    "comp_entries": "engine-replica",
    "comp_head": "engine-replica",
    "comp_tail": "frontend-worker",
    # request slots: the front end owns the request half...
    "slot_gen": "frontend-worker",
    "slot_n": "frontend-worker",
    "slot_busy": "frontend-worker",
    "slot_tenant": "frontend-worker",
    "slot_replica": "frontend-worker",
    "slot_deadline": "frontend-worker",
    "slot_slo": "frontend-worker",
    # ...the engine owns the response half
    "resp_gen": "engine-replica",
    "resp_status": "engine-replica",
    "resp_incarnation": "engine-replica",
    "resp_trace": ("engine-replica", "frontend-worker"),
    # slabs: requests in, responses out
    "small_cat": "frontend-worker",
    "small_num": "frontend-worker",
    "large_cat": "frontend-worker",
    "large_num": "frontend-worker",
    "small_resp": "engine-replica",
    "large_resp": "engine-replica",
    # per-worker metrics blocks (each worker writes only its own row)
    "req_counts": "frontend-worker",
    "lat_counts": "frontend-worker",
    "lat_sum_ms": "frontend-worker",
    "lat_n": "frontend-worker",
    "pred_lat_counts": "frontend-worker",
    "pred_lat_n": "frontend-worker",
    "shed": "frontend-worker",
    "inflight": "frontend-worker",
    "quota_shed": "frontend-worker",
    "expired": "frontend-worker",
    "parked": "frontend-worker",
    "brownout_shed": "frontend-worker",
    "tier_demote": "frontend-worker",
    "brownout_demote": "frontend-worker",
    "trace_dropped": "frontend-worker",
    "flight_dumps": "frontend-worker",
    "loop_lag_ms": "frontend-worker",
    # engine telemetry blocks (the engine's telemetry loop publishes;
    # reattach/recovery paths on the replica rebuild them)
    "shape_meta": "telemetry-loop",
    "shape_keys": "telemetry-loop",
    "shape_vals": "telemetry-loop",
    "rob_vals": ("engine-replica", "telemetry-loop"),
    "tier_counts": "engine-replica",
    "mon_vals": ("engine-replica", "telemetry-loop"),
    "mon_drift_last": ("engine-replica", "telemetry-loop"),
    "mon_drift_mean": ("engine-replica", "telemetry-loop"),
    "mon_drift_sum": ("engine-replica", "telemetry-loop"),
    "eng_vals": ("engine-replica", "supervisor"),
    "eng_rows_tenant": "engine-replica",
    # sloscope plane: the supervisor arms, the telemetry loop publishes
    "slo_meta": ("supervisor", "frontend-worker"),
    "slo_vals": "telemetry-loop",
    "alert_vals": "telemetry-loop",
    "ledger_meta": "telemetry-loop",
    "ledger_keys": "telemetry-loop",
    "ledger_vals": "telemetry-loop",
    "life_vals": "telemetry-loop",
    "life_promos": "telemetry-loop",
    # gridtuner (mlops_tpu/autotune/): per-replica controller state +
    # the shape-mirror overflow marker, published per telemetry tick
    "auto_vals": "telemetry-loop",
    "auto_plans": "telemetry-loop",
    "shape_evicted": "telemetry-loop",
}

# Which process role a lexical context runs as. Most specific wins:
# "Class.method" over "Class"; bare names are module-level functions.
# RequestRing is the shared library both sides import, so it gets NO
# class-wide role — each mutating method is pinned to the role that is
# allowed to call it (calling `submit` from an engine would be flagged
# exactly because the method's role, not the caller's import, decides).
TPULINT_SHM_ROLES = {
    "FrontendServer": "frontend-worker",
    "ShmWorkerMetrics": "frontend-worker",
    "RingClient": "frontend-worker",
    "RingService": "engine-replica",
    "RingService._telemetry_loop": "telemetry-loop",
    "RingService._write_autotune": "telemetry-loop",
    "RingService._write_ledger": "telemetry-loop",
    "RingService._write_robustness": "telemetry-loop",
    "RingService._write_shapes": "telemetry-loop",
    # RequestRing methods, by protocol side:
    "RequestRing.submit": "frontend-worker",
    "RequestRing.pop_completions": "frontend-worker",
    "RequestRing.set_tracing": "frontend-worker",
    "RequestRing.try_claim_profile": "frontend-worker",
    "RequestRing.release_profile": "frontend-worker",
    "RequestRing.post_profile_request": "frontend-worker",
    "RequestRing.cancel_profile_request": "frontend-worker",
    "RequestRing.pop_submissions": "engine-replica",
    "RequestRing.push_completion": "engine-replica",
    "RequestRing.set_ready": "engine-replica",
    "RequestRing.recover_engine_locks": "engine-replica",
    "RequestRing.set_draining": "supervisor",
    "RequestRing.arm_slo": "supervisor",
    "RequestRing.write_monitor": "telemetry-loop",
    "RequestRing.write_lifecycle": "telemetry-loop",
    "RequestRing.write_autotune": "telemetry-loop",
    # module-level process mains
    "_engine_main": "engine-replica",
    "serve_multi_worker": "supervisor",
}

SMALL, LARGE = 0, 1  # slot classes (stats/gauge indices)

# Serving-tier geometry for the shm tier_counts block (ISSUE 19): column
# i of a replica's row counts requests dispatched through TIERS[i].
N_TIERS = len(TIERS)
_TIER_IDX = {tier: i for i, tier in enumerate(TIERS)}

STATUSES = RING_STATUSES  # closed status set for the request matrices
_STATUS_IDX = {s: i for i, s in enumerate(STATUSES)}
_ROUTES = ServingMetrics.KNOWN_ROUTES + ("<other>",)
_ROUTE_IDX = {r: i for i, r in enumerate(_ROUTES)}


class Doorbell:
    """A cross-process wakeup: ``eventfd`` when the kernel provides it, a
    non-blocking self-pipe otherwise. Created before fork, shared by
    inheritance. ``ring()`` never blocks (a full pipe already means the
    reader has a pending wakeup) and tolerates a closed peer (a crashed
    front end must not take the engine down with EPIPE).

    ``ring(count)`` / ``drain() -> count`` make the doorbell a COUNTER,
    not just a wakeup: the worker doorbells carry the number of
    completions published, and that count is the consumer's CREDIT (see
    `RingClient.on_doorbell`). The eventfd write/read pair synchronizes
    through the kernel, so every store the producer made before ringing
    is visible to the consumer after draining — the cross-process fence
    that lets the completion consumer stay lock-free on weakly-ordered
    CPUs."""

    def __init__(self) -> None:
        if hasattr(os, "eventfd"):
            fd = os.eventfd(0, os.EFD_NONBLOCK)
            self._rfd = self._wfd = fd
        else:  # pragma: no cover - non-Linux fallback
            self._rfd, self._wfd = os.pipe()
            os.set_blocking(self._rfd, False)
            os.set_blocking(self._wfd, False)

    def fileno(self) -> int:
        return self._rfd

    def ring(self, count: int = 1) -> None:
        if self._rfd == self._wfd:
            payload = count.to_bytes(8, "little")  # eventfd accumulates
        else:  # pragma: no cover - non-Linux fallback
            # One byte per unit of credit. Un-drained bytes are bounded
            # by the completion queue's capacity (a slot cannot complete
            # again before its credit is consumed), orders of magnitude
            # under the 64 KiB pipe buffer — the fallback still exists
            # only for dev harnesses; production multi-worker serving
            # gates on eventfd (serve_multi_worker).
            payload = b"\x01" * count
        try:
            os.write(self._wfd, payload)
        except (BlockingIOError, BrokenPipeError, OSError):
            pass  # full pipe = wakeup already pending; closed peer = gone

    def wait(self, timeout_s: float | None = None) -> int:
        """Block (in select, so other processes' writes wake us) until the
        doorbell rings or the timeout passes; drains the counter and
        returns it (0 on timeout) — truthiness keeps the old bool
        contract, and the count is the consumer's CREDIT."""
        ready, _, _ = select.select([self._rfd], [], [], timeout_s)
        if ready:
            return self.drain()
        return 0

    def drain(self) -> int:
        """Swallow the pending count and return it (0 on a spurious or
        already-drained wake)."""
        total = 0
        try:
            while True:
                data = os.read(self._rfd, 8)
                if not data:
                    break
                if self._rfd == self._wfd:
                    return int.from_bytes(data, "little")  # whole counter
                total += len(data)  # pragma: no cover - pipe fallback
        except (BlockingIOError, OSError):
            pass
        return total

    def close(self) -> None:
        for fd in {self._rfd, self._wfd}:
            try:
                os.close(fd)
            except OSError:
                pass


def _pack(slot: int, gen: int) -> int:
    return (int(gen) & 0xFFFFFFFF) << 32 | (int(slot) & 0xFFFFFFFF)


def _unpack(entry: int) -> tuple[int, int]:
    return int(entry) & 0xFFFFFFFF, (int(entry) >> 32) & 0xFFFFFFFF


class RequestRing:
    """The shared-memory segment + typed views + descriptor queues.

    Build ONCE in the parent (`RequestRing(...)`) before forking; every
    forked process sees the same pages through the inherited ``mmap``.
    All multi-word data races are excluded by ownership (a slot belongs
    to exactly one side between claim and completion; stats blocks have
    one writer each); the descriptor queues use 8-byte aligned
    head/tail counters, one queue per engine replica. Submissions:
    producers share replica r's ``_submit_locks[r]``, whose
    acquire/release pairing orders the slab stores against the head bump
    on weakly-ordered CPUs. Completions: producers (replica r's engine
    threads only) share ``_complete_locks[r]``; every consumer is
    lock-free and is fenced by its queue's counted doorbell credit
    instead (see `pop_completions`) — front ends never take a completion
    lock and no replica takes a sibling's, so neither front-end crashes
    nor sibling-replica crashes can ever orphan a lock this process
    needs.
    """

    def __init__(
        self,
        workers: int,
        slots_small: int,
        slots_large: int,
        large_rows: int,
        small_rows: int = GROUP_ROW_BUCKET,
        tenant_names: tuple[str, ...] = ("default",),
        replicas: int = 1,
    ) -> None:
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if not tenant_names:
            raise ValueError("tenant_names must name at least one tenant")
        self.workers = workers
        # Engine replica set (ISSUE 13): replica INDEX is the position on
        # every per-replica queue/stats axis, fixed for the plane's
        # lifetime — the shm slot tag (``slot_replica``) names which
        # replica owns a submitted slot's dispatch and replay.
        self.replicas = R = replicas
        # Tenant fleet (mlops_tpu/tenancy/): tenant INDEX — the shm slot
        # tag, every per-tenant stats row — is the position in this tuple,
        # fixed for the plane's lifetime (the names themselves are plain
        # Python state inherited through fork, never stored in shm). The
        # default single-name tuple makes every pre-tenancy caller a
        # 1-tenant fleet with identical layout semantics.
        self.tenant_names = tuple(tenant_names)
        self.tenants = T = len(self.tenant_names)
        self.slots_small = slots_small
        self.slots_large = slots_large
        self.small_rows = small_rows
        self.large_rows = max(large_rows, small_rows)
        self.n_small = workers * slots_small
        self.n_large = workers * slots_large
        self.n_slots = self.n_small + self.n_large
        C, N = SCHEMA.num_categorical, SCHEMA.num_numeric
        self.n_features = D = C + N
        self._nb = len(ServingMetrics.LATENCY_BUCKETS)

        from mlops_tpu.trace.shapes import (
            TABLE_KEY_BYTES,
            TABLE_ROWS,
            TABLE_VALS,
        )

        from mlops_tpu.slo.engine import N_ENGINE_ALERTS, SLO_FIELDS
        from mlops_tpu.slo.ledger import (
            TABLE_KEY_BYTES as LEDGER_KEY_BYTES,
            TABLE_ROWS as LEDGER_ROWS,
            TABLE_VALS as LEDGER_VALS,
        )

        plan: list[tuple[str, np.dtype, tuple[int, ...]]] = [
            # control flags: [0] reserved (readiness moved to the
            # per-replica rep_ready words), [1] draining, [2] tracing
            # armed (tracewire — gates every per-slot stamp store),
            # [3] sloscope armed (gates the SLO/alert render block)
            ("ctl", np.dtype(np.uint64), (4,)),
            # /debug/profile control words (front end -> engine): [0] the
            # request word (seq << 8 | action), [1] the acknowledgement
            # (seq << 16 | http status). Each word is ONE u64 store, so
            # the ack and its status can never tear apart on any memory
            # model; `_profile_lock` serializes requesting front ends.
            ("prof_ctl", np.dtype(np.uint64), (2,)),
            # Profile-channel claim LEASE (monotonic expiry; 0 = free):
            # the channel's ownership lives in shm, not in the mp lock,
            # so a front end killed mid-poll releases by expiry instead
            # of wedging /debug/profile into permanent 409 (the lock is
            # held only across the microsecond claim-word update — the
            # same micro-window residual-leak class as the slot busy
            # flag, vs an unbounded one if it spanned the ack poll).
            ("prof_claim", np.dtype(np.float64), (1,)),
            # Per-replica readiness flags (ISSUE 13): replica r's engine
            # flips its own word at warm/attach; the supervisor clears it
            # when r dies. Plane readiness = ANY replica ready (the
            # router routes around the holes).
            ("rep_ready", np.dtype(np.uint64), (R,)),
            # Per-(worker, replica) live ring depth — slots this worker
            # routed to replica r and has not released yet. Single writer
            # (that worker's event loop, the inflight-cell discipline);
            # the router sums a replica's column for its load signal.
            ("rep_inflight", np.dtype(np.uint64), (workers, R)),
            # submission queues, ONE PER REPLICA (MPSC: front ends ->
            # replica r's collector)
            ("sub_entries", np.dtype(np.uint64), (R, self.n_slots)),
            ("sub_head", np.dtype(np.uint64), (R,)),
            ("sub_tail", np.dtype(np.uint64), (R,)),
            # per-(replica, worker) completion queues (replica r -> one
            # front end). Capacity stays one worker's slot count: a slot
            # completes on exactly the replica it was routed to, so even
            # one replica holding every slot of a worker cannot overflow
            # its row.
            ("comp_entries", np.dtype(np.uint64),
             (R, workers, slots_small + slots_large)),
            ("comp_head", np.dtype(np.uint64), (R, workers)),
            ("comp_tail", np.dtype(np.uint64), (R, workers)),
            # per-slot headers. slot_busy marks submitted-but-not-released
            # slots IN SHM so the state survives a front-end crash: a
            # respawned incarnation must quarantine those slots (the
            # engine may still write their slabs) instead of re-freeing
            # them — see RingClient.__init__.
            ("slot_gen", np.dtype(np.uint32), (self.n_slots,)),
            ("slot_n", np.dtype(np.uint32), (self.n_slots,)),
            ("slot_busy", np.dtype(np.uint32), (self.n_slots,)),
            # Tenant index of the request occupying the slot (stamped by
            # the front end at CLAIM, before the descriptor is visible):
            # the engine dispatches the slot against this tenant's bundle
            # and the respawn replay re-answers it under the same tenant
            # — the tag survives both front-end and engine crashes
            # because it lives in shm with the busy flag.
            ("slot_tenant", np.dtype(np.uint32), (self.n_slots,)),
            # Replica index the router assigned the slot to (stamped by
            # the front end at submit, BEFORE the busy flag): replica
            # r's dispatch, completion, and — after a kill -9 — its
            # respawned incarnation's replay all key off this tag, so a
            # dead replica's busy slots are replayed by exactly its own
            # successor and never double-answered by a sibling.
            ("slot_replica", np.dtype(np.uint32), (self.n_slots,)),
            # Absolute request deadline (time.monotonic seconds — the same
            # CLOCK_MONOTONIC the front ends' event loops read, so values
            # compare across processes on one host; 0 = no deadline). The
            # engine checks it BEFORE dispatching and completes expired
            # descriptors RESP_EXPIRED without touching the device.
            ("slot_deadline", np.dtype(np.float64), (self.n_slots,)),
            # Routed SLO class of the request occupying the slot (ISSUE
            # 19, serve/tierroute.py — 0 default / 1 cheap / 2 accurate,
            # POST brownout demotion: the front end's governor demotes
            # before the claim, so what rides the slot is the class the
            # engine must serve). Stamped with slot_tenant at CLAIM, so
            # the engine's dispatch and a respawned engine's replay both
            # route the slot through the SAME tier — a replay can never
            # silently upgrade or downgrade an in-flight request.
            ("slot_slo", np.dtype(np.uint32), (self.n_slots,)),
            ("resp_gen", np.dtype(np.uint32), (self.n_slots,)),
            ("resp_status", np.dtype(np.uint32), (self.n_slots,)),
            # Engine incarnation that produced this slot's response
            # (stamped with resp_gen, checked by the completion consumer
            # against eng_vals[ENG_INCARNATION]): a completion left
            # behind by a dead engine incarnation is DROPPED — the
            # respawned engine's replay re-answers the slot — so a
            # leftover from a process that died mid-batch can never be
            # served as fresh (ISSUE 11).
            ("resp_incarnation", np.dtype(np.uint32), (self.n_slots,)),
            # tracewire engine-half span stamps, carried per slot exactly
            # like slot_deadline: [collect, jobstart, dispatched, fetched]
            # CLOCK_MONOTONIC stamps plus [kind, geom] naming the compiled
            # entry (kind 1 = bucket with geom rows; 2 = group with geom
            # slots*100000+rows). Written by the engine BEFORE the
            # completion push, read by the owning front end before slot
            # release — the same ownership window as the response slab,
            # fenced by the same completion credit. Zeroed unless the
            # tracing ctl flag is set.
            ("resp_trace", np.dtype(np.float64), (self.n_slots, 6)),
            # request slabs (front end writes, engine reads)
            ("small_cat", np.dtype(np.int32), (self.n_small, small_rows, C)),
            ("small_num", np.dtype(np.float32), (self.n_small, small_rows, N)),
            ("large_cat", np.dtype(np.int32),
             (self.n_large, self.large_rows, C)),
            ("large_num", np.dtype(np.float32),
             (self.n_large, self.large_rows, N)),
            # response slabs (engine writes, front end reads): f64
            # [predictions rows ‖ outliers rows ‖ drift D] — f64 because
            # that is exactly what `fetch_*_raw` hands `format_response`,
            # so the bytes the front end formats are the bytes the
            # single-process path would have formatted (bit-identity)
            ("small_resp", np.dtype(np.float64),
             (self.n_small, 2 * small_rows + D)),
            ("large_resp", np.dtype(np.float64),
             (self.n_large, 2 * self.large_rows + D)),
            # per-worker serving stats (single writer: that worker),
            # tenant-dimensioned (mlops_tpu/tenancy/): row T is the
            # tenant index; a 1-tenant plane carries exactly one row.
            ("req_counts", np.dtype(np.uint64),
             (workers, T, len(_ROUTES), len(STATUSES) + 1)),
            ("lat_counts", np.dtype(np.uint64), (workers, T, self._nb)),
            ("lat_sum_ms", np.dtype(np.float64), (workers, T)),
            ("lat_n", np.dtype(np.uint64), (workers, T)),
            # /predict-scoped latency histogram for the sloscope latency
            # SLO (ServingMetrics.predict_latency_counts' ring twin):
            # the all-routes block above stays the exported histogram;
            # the SLO must not let probe/scrape latencies dilute
            # /predict violations. Single writer per worker row.
            ("pred_lat_counts", np.dtype(np.uint64), (workers, T, self._nb)),
            ("pred_lat_n", np.dtype(np.uint64), (workers, T)),
            ("shed", np.dtype(np.uint64), (workers, T, 2)),
            ("inflight", np.dtype(np.uint64), (workers, T, 2)),
            # quota rejections (admission refused by the tenant's own
            # weighted max-min floor, not physical exhaustion) — the
            # fairness contract's observable, single writer per worker
            ("quota_shed", np.dtype(np.uint64), (workers, T)),
            # dead-work sheds counted FRONT-END side (admission/budget
            # checks answering 504 before a slot submits) — single writer
            # per worker, like the shed counters
            ("expired", np.dtype(np.uint64), (workers,)),
            # ISSUE 11 — per-worker survivability cells (single writer:
            # that worker's event loop). `parked` is a GAUGE: requests
            # admitted while the engine was down, currently holding a
            # slot awaiting the respawned engine's replay.
            # `brownout_shed` counts 503s answered because the parking
            # partition filled DURING an engine outage (they also count
            # in the per-class `shed` cells — brownout is a shed with a
            # respawn-ETA Retry-After, not a new status).
            ("parked", np.dtype(np.uint64), (workers,)),
            ("brownout_shed", np.dtype(np.uint64), (workers,)),
            # ISSUE 19 — SLO tier-routing demotions counted FRONT-END
            # side (single writer per worker, like expired/shed):
            # tier_demote = every request served below its requested
            # class; brownout_demote = the subset demoted by the
            # brownout governor (pressure), not by an explicit header.
            ("tier_demote", np.dtype(np.uint64), (workers,)),
            ("brownout_demote", np.dtype(np.uint64), (workers,)),
            # tracewire spans each front end's bounded recorder DROPPED
            # (single writer per worker, like expired/shed)
            ("trace_dropped", np.dtype(np.uint64), (workers,)),
            # sloscope flight-recorder dumps written by each front end
            # (single writer per worker): the fleet-wide observable that
            # an anomaly tripped evidence capture somewhere — scrape any
            # worker, see every worker's dumps.
            ("flight_dumps", np.dtype(np.uint64), (workers,)),
            # loopcheck event-loop lag gauge (single writer per worker):
            # each front end's LoopLagSanitizer window max in ms, 0 when
            # the monitor is off or the window was quiet — the
            # always-emit contract needs a real zero, not a gap.
            ("loop_lag_ms", np.dtype(np.float64), (workers,)),
            # tracewire shape-histogram mirror (trace/shapes.py): the
            # engine's telemetry loop writes its ShapeStats into this
            # fixed table so ANY front end renders the _bucket series on
            # a scrape. shape_meta[0] = the stats' armed-at monotonic
            # time (0 = tracing off), the useful_rows_per_s rate base.
            ("shape_meta", np.dtype(np.float64), (R,)),
            ("shape_keys", np.dtype(np.uint8),
             (R, TABLE_ROWS, TABLE_KEY_BYTES)),
            ("shape_vals", np.dtype(np.float64), (R, TABLE_ROWS, TABLE_VALS)),
            # robustness counters with ENGINE-PROCESS writers (pool
            # threads under RingService._mon_lock): ROB_EXPIRED_ENGINE =
            # descriptors completed RESP_EXPIRED without a dispatch,
            # ROB_DEGRADED = the engine's degraded-dispatch total
            # (mirrored by the telemetry loop)
            ("rob_vals", np.dtype(np.float64), (R, 2)),
            # requests dispatched per serving tier (ISSUE 19 — column i
            # is tierroute.TIERS[i]; pool threads under
            # RingService._mon_lock, one row per replica): the ring twin
            # of ServingMetrics.tier_requests, summed over replicas by
            # the render.
            ("tier_counts", np.dtype(np.float64), (R, N_TIERS)),
            # monitor aggregate, ONE ROW PER (REPLICA, TENANT) — single
            # writer: that replica's engine process (each tenant engine
            # owns its own device accumulator and exact host totals,
            # mirrored here per telemetry tick); the /metrics render
            # FOLDS the replica axis into one per-tenant aggregate.
            # mon_drift_sum carries the UNROUNDED cumulative sums so a
            # respawned replica can seed each tenant's exact host totals
            # (ISSUE 11) — and so the render's cross-replica drift mean
            # is an exact weighted fold, not a mean of rounded means.
            ("mon_vals", np.dtype(np.float64), (R, T, 8)),
            ("mon_drift_last", np.dtype(np.float64), (R, T, D)),
            ("mon_drift_mean", np.dtype(np.float64), (R, T, D)),
            ("mon_drift_sum", np.dtype(np.float64), (R, T, D)),
            # engine-supervision block, ONE ROW PER REPLICA (ISSUE 11/13;
            # serve/metrics.py ENG_* indices): incarnation, down-since
            # stamp, respawn/replay/rows-lost counters, rows-dispatched
            # telemetry baseline (a row's ROWS_DISPATCHED cell keeps that
            # replica's fleet-wide sum; eng_rows_tenant carries the
            # per-tenant baselines its respawn's rows-lost accounting
            # differences). One writer per cell, per row.
            ("eng_vals", np.dtype(np.float64), (R, 6)),
            ("eng_rows_tenant", np.dtype(np.float64), (R, T)),
            # sloscope (ISSUE 14, mlops_tpu/slo/). slo_meta carries the
            # armed SLO geometry (four burn windows + targets +
            # latency threshold — written once by the supervisor at
            # arm_slo, so any front end can label the window dimension
            # without config plumbing); slo_vals/alert_vals are the
            # per-tenant SLO state the LEAD replica's telemetry loop
            # mirrors each tick (single writer; the write_monitor
            # tearing contract). Front ends render fleet verdicts from
            # these rows — during a full engine outage the gauges serve
            # last-known values and the render raises engine_down
            # itself.
            ("slo_meta", np.dtype(np.float64), (8,)),
            ("slo_vals", np.dtype(np.float64), (T, SLO_FIELDS)),
            ("alert_vals", np.dtype(np.float64), (T, N_ENGINE_ALERTS)),
            # device-time cost ledger mirror (slo/ledger.py), ONE TABLE
            # PER REPLICA like the shape tables: ledger_meta[r] > 0 =
            # replica r's ledger is armed and mirrored; the render
            # merges by entry key.
            ("ledger_meta", np.dtype(np.float64), (R,)),
            ("ledger_keys", np.dtype(np.uint8),
             (R, LEDGER_ROWS, LEDGER_KEY_BYTES)),
            ("ledger_vals", np.dtype(np.float64),
             (R, LEDGER_ROWS, LEDGER_VALS)),
            # lifecycle loop state, ONE ROW PER TENANT (single writer:
            # the engine process's per-tenant controller telemetry —
            # serve/metrics.py LIFE_* indices), so ANY front end renders
            # each tenant's bundle generation / trigger / promotion
            # gauges from shm.
            ("life_vals", np.dtype(np.float64), (T, 8)),
            ("life_promos", np.dtype(np.float64), (T, len(LIFE_OUTCOMES))),
            # gridtuner state (mlops_tpu/autotune/), ONE ROW PER REPLICA
            # (single writer: that replica's telemetry loop —
            # serve/metrics.py AUTO_* indices): grid generation plus the
            # predicted/measured gain audit pair in auto_vals,
            # per-outcome plan counters in auto_plans. shape_evicted[r]
            # mirrors replica r's ShapeStats mirror-overflow count
            # (trace/shapes.py evicted_total) so a saturated shape table
            # is VISIBLE on ring scrapes, not a silent demand bias.
            ("auto_vals", np.dtype(np.float64), (R, 6)),
            ("auto_plans", np.dtype(np.float64),
             (R, len(AUTOTUNE_OUTCOMES))),
            ("shape_evicted", np.dtype(np.float64), (R,)),
        ]
        offset = 0
        offsets = {}
        for name, dtype, shape in plan:
            offset = (offset + 63) & ~63  # 64-byte align every region
            offsets[name] = offset
            offset += dtype.itemsize * int(np.prod(shape))
        self._mm = mmap.mmap(-1, offset)  # anonymous MAP_SHARED
        for name, dtype, shape in plan:
            view = np.frombuffer(
                self._mm, dtype=dtype, count=int(np.prod(shape)),
                offset=offsets[name],
            ).reshape(shape)
            setattr(self, name, view)

        # The cross-process queue locks, PER REPLICA (one per descriptor
        # queue's head index); "fork" context — the whole plane is built
        # on inheritance. ``_submit_locks[r]`` is producers-only (front
        # ends); ``_complete_locks[r]`` belongs to replica r's engine
        # threads alone — so no process's death can orphan a lock any
        # OTHER process needs.
        ctx = multiprocessing.get_context("fork")
        self._submit_locks = [ctx.Lock() for _ in range(R)]
        self._complete_locks = [ctx.Lock() for _ in range(R)]
        # Serializes updates to the profile claim-lease word (one
        # outstanding /debug/profile request at a time). Never taken by
        # an engine, never on any request hot path, held only across
        # the microsecond lease update (busy/orphaned -> 409) — so it
        # can neither wedge the plane nor order against the queue locks.
        self._profile_lock = ctx.Lock()
        self.engine_doorbells = [Doorbell() for _ in range(R)]
        # Flat [worker * replicas + replica] so a 1-replica plane's
        # ``worker_doorbells[w]`` stays exactly the pre-replica object
        # (every existing caller and test indexes it that way).
        self.worker_doorbells = [Doorbell() for _ in range(workers * R)]

    # ---------------------------------------------------------- doorbells
    @property
    def engine_doorbell(self) -> Doorbell:
        """Replica 0's submission doorbell — the pre-replica name."""
        return self.engine_doorbells[0]

    def worker_doorbell(self, worker: int, replica: int = 0) -> Doorbell:
        """The doorbell replica ``replica`` rings for ``worker``'s
        completions (one per pair: the counted credit is a per-queue
        fence, and queues are per (replica, worker))."""
        return self.worker_doorbells[worker * self.replicas + replica]

    # ------------------------------------------------------ control flags
    @property
    def engine_ready(self) -> bool:
        """ANY replica ready: the plane serves as long as one engine is
        up (the router routes around the rest — a partial outage is a
        capacity brownout, not unreadiness)."""
        return bool(self.rep_ready.any())

    def set_ready(self, ready: bool, replica: int | None = None) -> None:
        """Flip one replica's readiness word (its engine at attach, the
        supervisor at death), or — replica None, the pre-replica caller
        shape — the whole fleet's."""
        if replica is None:
            self.rep_ready[:] = 1 if ready else 0
        else:
            self.rep_ready[replica] = 1 if ready else 0

    def ready_replicas(self) -> list[int]:
        return [r for r in range(self.replicas) if self.rep_ready[r]]

    @property
    def draining(self) -> bool:
        return bool(self.ctl[1])

    def set_draining(self) -> None:
        self.ctl[1] = 1

    @property
    def tracing(self) -> bool:
        return bool(self.ctl[2])

    def set_tracing(self, armed: bool) -> None:
        self.ctl[2] = 1 if armed else 0

    @property
    def slo_armed(self) -> bool:
        return bool(self.ctl[3])

    def arm_slo(self, slo_config) -> None:
        """Supervisor-side (before fork): publish the SLO geometry so
        every front end can render the block — window labels included —
        without any config plumbing, and flip the armed flag that gates
        the render."""
        self.slo_meta[0] = float(slo_config.fast_short_s)
        self.slo_meta[1] = float(slo_config.fast_long_s)
        self.slo_meta[2] = float(slo_config.slow_short_s)
        self.slo_meta[3] = float(slo_config.slow_long_s)
        self.slo_meta[4] = float(slo_config.availability_target)
        self.slo_meta[5] = float(slo_config.latency_target)
        self.slo_meta[6] = float(slo_config.latency_threshold_ms)
        self.ctl[3] = 1

    def slo_counts(
        self, latency_threshold_ms: float
    ) -> dict[str, tuple[int, int, int, int]]:
        """The sloscope counter source for the ring plane (the fleet
        twin of `ServingMetrics.slo_counts`): per tenant, cumulative
        ``(avail_good, avail_total, lat_good, lat_total)`` summed over
        every worker's shm request matrices. Lock-free reads of
        monotone counters — a read racing an increment under-counts by
        at most one in-flight request, which the next tick absorbs."""
        from mlops_tpu.serve.metrics import (
            SLO_BAD_STATUSES,
            latency_good_buckets,
        )

        route_i = _ROUTE_IDX["/predict"]
        bad_cols = [_STATUS_IDX[s] for s in SLO_BAD_STATUSES]
        k = latency_good_buckets(latency_threshold_ms)
        out: dict[str, tuple[int, int, int, int]] = {}
        for t, tenant in enumerate(self.tenant_names):
            counts = self.req_counts[:, t, route_i, :]
            total = int(counts.sum())
            bad = int(counts[:, bad_cols].sum())
            lat_good = int(self.pred_lat_counts[:, t, :k].sum())
            lat_total = int(self.pred_lat_n[:, t].sum())
            out[tenant] = (total - bad, total, lat_good, lat_total)
        return out

    # ---------------------------------------------------- slot geometry
    def worker_slots(self, worker: int) -> tuple[range, range]:
        """(small slot ids, large slot ids) owned by ``worker``."""
        s0 = worker * self.slots_small
        l0 = self.n_small + worker * self.slots_large
        return (
            range(s0, s0 + self.slots_small),
            range(l0, l0 + self.slots_large),
        )

    def slot_class(self, slot: int) -> int:
        return SMALL if slot < self.n_small else LARGE

    def slot_owner(self, slot: int) -> int:
        if slot < self.n_small:
            return slot // self.slots_small
        return (slot - self.n_small) // self.slots_large

    def request_views(self, slot: int) -> tuple[np.ndarray, np.ndarray]:
        """(cat int32[rows, C], num f32[rows, N]) slab views for ``slot``
        — full slab; callers slice by the row count they wrote."""
        if slot < self.n_small:
            return self.small_cat[slot], self.small_num[slot]
        i = slot - self.n_small
        return self.large_cat[i], self.large_num[i]

    def response_views(
        self, slot: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(predictions, outliers, drift) f64 views of the response slab,
        sliced to the slot's recorded row count."""
        n = int(self.slot_n[slot])
        if slot < self.n_small:
            resp, rows = self.small_resp[slot], self.small_rows
        else:
            resp, rows = self.large_resp[slot - self.n_small], self.large_rows
        return resp[:n], resp[rows : rows + n], resp[2 * rows :]

    # ------------------------------------------------------- descriptors
    def submit(self, slot: int, gen: int, replica: int = 0) -> None:
        """Front-end side: enqueue a filled slot for engine replica
        ``replica``. The lock (PRODUCERS only — no engine ever takes it,
        so an engine kill -9 can never orphan it) guards the head bump;
        the doorbell rings outside it and carries one unit of the
        consumer's credit."""
        entry = _pack(slot, gen)
        with self._submit_locks[replica]:
            head = int(self.sub_head[replica])
            self.sub_entries[replica, head % self.n_slots] = entry
            self.sub_head[replica] = head + 1
        self.engine_doorbells[replica].ring()

    def pop_submissions(
        self, limit: int | None = None, replica: int = 0
    ) -> list[tuple[int, int]]:
        """Engine side (single consumer per replica, on its OWN queue):
        LOCK-FREE, the mirror of `pop_completions` — the tail has one
        writer (this consumer) and the consumer never touches the
        producers' lock, so a kill -9'd replica cannot wedge front-end
        submits and a kill -9'd front end cannot wedge any replica.
        Ordering safety comes from ``limit``: the collector passes the
        credit accumulated from its counted doorbell (seeded with the
        already-queued entry count at attach — a dead incarnation takes
        drained credit to its grave); entries beyond the credit wait for
        their ring."""
        out: list[tuple[int, int]] = []
        head = int(self.sub_head[replica])
        tail = int(self.sub_tail[replica])
        if limit is not None:
            head = min(head, tail + limit)
        while tail < head:
            out.append(
                _unpack(int(self.sub_entries[replica, tail % self.n_slots]))
            )
            tail += 1
        self.sub_tail[replica] = tail
        return out

    def pending_submissions(self, replica: int = 0) -> set[int]:
        """Slot ids with a descriptor currently queued for ``replica``
        (published, not yet popped) — the re-attach replay scan excludes
        these: they reach the new engine through the normal pop path.
        Lock-free snapshot (no engine may take the producers' lock); a
        submit racing the scan lands either in this set or as a visible
        busy flag with its doorbell credit still pending — both paths
        answer it exactly once in the common case, and the worst-case
        race is one redundant idempotent dispatch, never a lost or
        corrupt response."""
        head = int(self.sub_head[replica])
        tail = int(self.sub_tail[replica])
        return {
            _unpack(int(self.sub_entries[replica, i % self.n_slots]))[0]
            for i in range(tail, head)
        }

    def recover_engine_locks(self, replica: int = 0) -> None:
        """Engine-side re-attach step (ISSUE 11): free replica
        ``replica``'s completion lock if its dead incarnation was killed
        while holding it (pushing a completion is microseconds of index
        arithmetic, but kill -9 has no grace). Safe by serialization:
        only replica r's engine incarnations ever take lock r, the
        supervisor runs at most one incarnation of each replica at a
        time, and this runs before the new engine starts any pool thread
        — so a failed non-blocking acquire can only mean an orphaned
        hold, and releasing an unheld semaphore-backed mp.Lock just
        frees it. Sibling replicas' locks are untouched — their owners
        are alive and a recovery here would corrupt THEIR exclusion."""
        lock = self._complete_locks[replica]
        if lock.acquire(block=False):
            lock.release()
            return
        try:
            lock.release()
            logger.warning(
                "recovered completion lock orphaned by dead engine "
                "replica %d", replica,
            )
        except ValueError:  # pragma: no cover - platform-dependent guard
            logger.exception("completion-lock recovery failed")

    def push_completion(self, slot: int, gen: int, replica: int = 0) -> None:
        """Engine side: hand a finished slot back to its owner through
        ``replica``'s own queue row. The lock (acquired by THAT replica's
        engine threads only — neither a crashed front end nor a sibling
        replica can orphan it and wedge this replica) serializes its
        producing pool threads; its acquisition order IS the queue order,
        so the counted doorbell rung after a batch's last push fences
        every earlier-queued entry too. Per-row capacity equals the
        worker's slot count, so no row can ever overflow."""
        worker = self.slot_owner(slot)
        cap = self.comp_entries.shape[2]
        with self._complete_locks[replica]:
            head = int(self.comp_head[replica, worker])
            self.comp_entries[replica, worker, head % cap] = _pack(slot, gen)
            self.comp_head[replica, worker] = head + 1

    def pop_completions(
        self, worker: int, limit: int | None = None, replica: int = 0
    ) -> list[tuple[int, int]]:
        """Front-end side (single consumer per (worker, replica) queue):
        LOCK-FREE — the tail has one writer (this consumer) and the
        consumer never touches a cross-process lock, so a kill -9'd
        front end cannot wedge the ring. Ordering safety comes from
        ``limit``: callers pass the credit accumulated from that PAIR's
        counted doorbell, and an entry is only consumed once a doorbell
        rung AFTER its publication has been drained (the eventfd syscall
        pair is the fence). Entries beyond the credit wait for their
        ring."""
        out: list[tuple[int, int]] = []
        cap = self.comp_entries.shape[2]
        head = int(self.comp_head[replica, worker])
        tail = int(self.comp_tail[replica, worker])
        if limit is not None:
            head = min(head, tail + limit)
        while tail < head:
            out.append(
                _unpack(int(self.comp_entries[replica, worker, tail % cap]))
            )
            tail += 1
        self.comp_tail[replica, worker] = tail
        return out

    # ---------------------------------------------------- profile control
    # Claim-lease lifetime: must exceed the front end's ack-poll window
    # (frontend._PROFILE_ACK_S = 10 s) so a live poller is never usurped;
    # a dead claimant frees by expiry in this bound.
    PROFILE_LEASE_S = 15.0

    def try_claim_profile(self) -> float | None:
        """Non-blocking claim of the profile-request channel (front-end
        side; busy -> the caller answers 409 without waiting). The claim
        is a LEASE in shm — a claimant that dies mid-poll expires out
        instead of holding the channel forever. Returns the claim TOKEN
        (the lease word this claimant wrote): release/cancel require it,
        so a claimant stalled PAST its own expiry cannot clobber a
        successor's live lease or pending request word. The mp lock only
        serializes the read-check-write of the lease word itself and is
        never held across the ack poll."""
        if not self._profile_lock.acquire(timeout=0.2):
            return None  # contended (or micro-window orphan): busy
        try:
            now = time.monotonic()
            if float(self.prof_claim[0]) > now:
                return None  # live claim
            token = now + self.PROFILE_LEASE_S
            # _profile_lock IS held here — the enclosing timeout-acquire
            # above, which the static guard inference cannot follow.
            self.prof_claim[0] = token  # tpulint: disable=TPU402
            return token
        finally:
            self._profile_lock.release()

    def release_profile(self, token: float) -> None:
        """Free the lease IF it is still this claimant's: after an expiry
        takeover the stale ex-claimant's release must be a no-op."""
        with self._profile_lock:
            if float(self.prof_claim[0]) == token:
                self.prof_claim[0] = 0.0

    def post_profile_request(self, action_code: int) -> int:
        """Publish the next profile request word (caller holds the
        channel LEASE); returns the seq the acknowledgement must echo.
        The word update rides the same mutex as the cancel path so a
        stale ex-claimant's token-checked cancel can never interleave
        with a successor's post. Deliberately does NOT ring the engine
        doorbell: that counter is the submission queue's consumption
        CREDIT (a profile wakeup would be a phantom credit), and the
        collector polls this word on its <=1 s idle tick anyway — well
        inside the front end's 10 s ack budget."""
        with self._profile_lock:
            seq = ((int(self.prof_ctl[0]) >> 8) + 1) & 0xFFFFFFFF
            if seq == 0:
                seq = 1  # 0 means "no request yet" to the collector
            self.prof_ctl[0] = (seq << 8) | (action_code & 0xFF)
        return seq

    def read_profile_ack(self, seq: int) -> int | None:
        """The engine's HTTP status for ``seq``, or None while pending."""
        resp = int(self.prof_ctl[1])
        if (resp >> 16) == seq:
            return resp & 0xFFFF
        return None

    def cancel_profile_request(self, seq: int, token: float) -> None:
        """Timed-out ack wait: overwrite the pending request word with a
        no-op action at the SAME seq before releasing the lease. If the
        collector has not consumed the original word yet, it now
        acknowledges a 404 no-op instead of executing a start/stop the
        client was already told failed (profiler-state desync); keeping
        the seq preserves the monotone numbering the next request derives
        from. Token-guarded like `release_profile`: a claimant stalled
        past its own lease must not clobber a successor's pending word.
        If the collector read the word in the microseconds before this
        store, the action still runs — the window shrinks from unbounded
        to one racy read, and the late ack is ignored (its seq is
        already abandoned)."""
        with self._profile_lock:
            if float(self.prof_claim[0]) == token:
                self.prof_ctl[0] = (int(seq) << 8) | 0

    # ----------------------------------------------------------- monitor
    def write_monitor(
        self, snapshot: dict[str, Any], tenant: int = 0, replica: int = 0
    ) -> None:
        """Engine-process single writer (one row per (replica, tenant)):
        install one tenant's `monitor_snapshot` aggregate for the front
        ends' /metrics renders. Field-at-a-time f64 stores are
        individually atomic; a scrape racing this write can see a
        mid-update mix, which Prometheus gauges tolerate (same contract
        as a scrape racing the single-process fetch)."""
        if not snapshot:
            return
        row = self.mon_vals[replica, tenant]
        row[MON_ROWS] = float(snapshot["rows"])
        row[MON_OUTLIERS] = float(snapshot["outliers"])
        row[MON_BATCHES] = float(snapshot["batches"])
        self.mon_drift_last[replica, tenant, :] = np.fromiter(
            snapshot["drift_last"].values(), np.float64, self.n_features
        )
        self.mon_drift_mean[replica, tenant, :] = np.fromiter(
            snapshot["drift_mean"].values(), np.float64, self.n_features
        )
        # Unrounded cumulative sums (monitor_snapshot exports them for
        # the lifecycle windows): the respawn seed reads these back so an
        # engine restart never injects rounding error into the totals.
        drift_sum = snapshot.get("drift_sum")
        if drift_sum is not None:
            self.mon_drift_sum[replica, tenant, :] = np.asarray(
                drift_sum, np.float64
            )
        row[MON_FETCHES] += 1
        row[MON_FETCHED_AT] = time.monotonic()
        row[MON_HAS] = 1.0

    def write_lifecycle(
        self, snapshot: dict[str, Any], tenant: int = 0
    ) -> None:
        """Engine-process single writer: install one tenant's lifecycle
        controller snapshot (`lifecycle/controller.py metrics_snapshot`)
        for the front ends' /metrics renders. Same tearing contract as
        `write_monitor`: per-field f64 stores are individually atomic and
        a mid-update mix is gauge-tolerable."""
        if not snapshot:
            return
        row = self.life_vals[tenant]
        row[LIFE_GENERATION] = float(snapshot["generation"])
        row[LIFE_TRIGGERS] = float(snapshot["drift_triggers"])
        delta = snapshot.get("shadow_auc_delta")
        row[LIFE_AUC_DELTA] = 0.0 if delta is None else float(delta)
        row[LIFE_HAS_DELTA] = 0.0 if delta is None else 1.0
        row[LIFE_RESERVOIR] = float(snapshot.get("reservoir_rows") or 0)
        row[LIFE_BREAKER_OPEN] = (
            1.0 if snapshot.get("breaker_open") else 0.0
        )
        row[LIFE_BREAKER_TRIPS] = float(snapshot.get("breaker_trips", 0))
        promotions = snapshot.get("promotions", {})
        for i, outcome in enumerate(LIFE_OUTCOMES):
            self.life_promos[tenant, i] = float(promotions.get(outcome, 0))
        row[LIFE_HAS] = 1.0

    def write_autotune(
        self, snapshot: dict[str, Any], replica: int = 0
    ) -> None:
        """Engine-process single writer: install one replica's autotune
        controller snapshot (`autotune/apply.py metrics_snapshot`) for
        the front ends' /metrics renders. Same tearing contract as
        `write_monitor`: per-field f64 stores are individually atomic
        and a mid-update mix is gauge-tolerable."""
        if not snapshot:
            return
        row = self.auto_vals[replica]
        row[AUTO_GRID_GEN] = float(snapshot["grid_generation"])
        predicted = snapshot.get("predicted_gain_pct")
        row[AUTO_PRED_GAIN] = 0.0 if predicted is None else float(predicted)
        row[AUTO_HAS_PRED] = 0.0 if predicted is None else 1.0
        measured = snapshot.get("measured_gain_pct")
        row[AUTO_MEAS_GAIN] = 0.0 if measured is None else float(measured)
        row[AUTO_HAS_MEAS] = 0.0 if measured is None else 1.0
        plans = snapshot.get("plans", {})
        for i, outcome in enumerate(AUTOTUNE_OUTCOMES):
            self.auto_plans[replica, i] = float(plans.get(outcome, 0))
        row[AUTO_HAS] = 1.0

    def close(self) -> None:
        for bell in (*self.engine_doorbells, *self.worker_doorbells):
            bell.close()
        # The mmap itself is left to the garbage collector / process exit:
        # numpy views pin the buffer, and the kernel reclaims the pages
        # when the last process goes away.


class ShmWorkerMetrics:
    """`ServingMetrics.observe_request`-compatible recorder writing into a
    worker's shared stats block — single writer (that worker's event
    loop), so no lock; cross-process readers see monotonic counters."""

    def __init__(
        self, ring: RequestRing, worker: int, default_tenant: int = 0
    ) -> None:
        self._ring = ring
        self._worker = worker
        self._buckets = ServingMetrics.LATENCY_BUCKETS
        # Tenant LABEL -> shm row. Labels are bounded upstream
        # (TenantRouter.label); the closed unknown marker — requests
        # 404'd for naming no declared tenant — lands on the default
        # tenant's row (there is no stranger row to bill).
        self._tenant_idx = {
            name: i for i, name in enumerate(ring.tenant_names)
        }
        self._default_tenant = int(default_tenant)

    def observe_request(
        self,
        route: str,
        status: int,
        latency_ms: float,
        tenant: str = "default",
    ) -> None:
        ring, w = self._ring, self._worker
        t = self._tenant_idx.get(tenant, self._default_tenant)
        r = _ROUTE_IDX.get(route, _ROUTE_IDX["<other>"])
        s = _STATUS_IDX.get(status, len(STATUSES))
        ring.req_counts[w, t, r, s] += 1
        ring.lat_sum_ms[w, t] += latency_ms
        ring.lat_n[w, t] += 1
        for i, edge in enumerate(self._buckets):
            if latency_ms <= edge:
                ring.lat_counts[w, t, i] += 1
                break
        if route == "/predict":
            # The latency-SLO scope (see the pred_lat_counts plan note).
            ring.pred_lat_n[w, t] += 1
            for i, edge in enumerate(self._buckets):
                if latency_ms <= edge:
                    ring.pred_lat_counts[w, t, i] += 1
                    break

    def count_deadline_expired(self) -> None:
        """Front-end-side dead-work shed (admission/budget 504 before any
        slot submitted) — single-writer cell, same discipline as shed."""
        self._ring.expired[self._worker] += 1

    def set_loop_lag(self, lag_ms: float) -> None:
        """Publish this worker's event-loop lag window max (loopcheck's
        ``snapshot_ms``) — single-writer gauge cell, overwritten each
        publish; any front end renders every worker's cell on a scrape."""
        self._ring.loop_lag_ms[self._worker] = lag_ms


class RingClient:
    """One front end's view of the ring: slot claim/submit/release plus
    the completion doorbell. Everything here is EVENT-LOOP CONFINED to
    the owning worker process (the free lists, the pending map, the
    inflight gauges) — the only shared mutations go through
    `RequestRing.submit` (locked) and the slabs (exclusively owned)."""

    def __init__(
        self, ring: RequestRing, worker: int, affinity_slack: int = 4
    ) -> None:
        from mlops_tpu.replicaset.router import ReplicaRouter

        self.ring = ring
        self.worker = worker
        # Engine replica set (ISSUE 13): the per-submit replica choice —
        # least-loaded by live ring depth, sticky per (tenant, class) on
        # the coalescable small class (``affinity_slack`` =
        # serve.replica_affinity_slack on the production plane).
        # Event-loop confined like the free lists (its only
        # cross-process reads are gauge cells).
        self.router = ReplicaRouter(ring, affinity_slack=affinity_slack)
        small, large = ring.worker_slots(worker)
        # Restart-safe: generations AND the busy flags persist in shm. A
        # slot the DEAD incarnation submitted but never released
        # (slot_busy == 1) may still have an engine write in flight
        # against its response slab — it goes into QUARANTINE, not the
        # free list, until the engine's completion for it arrives (the
        # engine answers every accepted descriptor, so quarantine always
        # drains; the residual leak windows — a crash in the microseconds
        # between the busy-flag store and the descriptor push, or inside
        # the consume-completion-then-release callback — cost one slot of
        # capacity until the pod restarts, never correctness). Bumping every
        # generation makes any completion addressed to the dead
        # incarnation stale on arrival, and the engine's stale-generation
        # write guard (RingService._run_job) refuses to touch a slab
        # whose slot has moved on.
        self._free: tuple[list[int], list[int]] = ([], [])
        self._quarantined: set[int] = set()
        # Partition capacity (both classes) — the denominator of the
        # brownout governor's pressure signal (ISSUE 19): slot
        # occupancy over THIS worker's partition, the same bounded
        # admission queue whose exhaustion is the shed signal, so
        # "demote before shed" keys off exactly the resource whose
        # exhaustion sheds.
        self.partition_slots = len(small) + len(large)
        for slot in (*small, *large):
            ring.slot_gen[slot] += 1
            if int(ring.slot_busy[slot]):
                self._quarantined.add(slot)
            else:
                self._free[ring.slot_class(slot)].append(slot)
        # The ring_depth gauge restarts at the quarantined-slot count, not
        # zero: those slots are still occupied (the engine may be writing
        # them) and the drain path in `on_doorbell` decrements as each one
        # returns to the free list — so the gauge never undercounts after
        # a worker crash. Quarantined slots keep their shm tenant tag, so
        # the per-tenant depth cells stay attributed correctly too.
        ring.inflight[worker, :, :] = 0
        # Slots the dead incarnation had SUBMITTED keep counting toward
        # their replica's live depth until the completion frees them —
        # the router must keep seeing a crashed worker's in-flight load,
        # or it would pile fresh traffic onto an already-occupied
        # replica. Rebuilt from the shm replica tags, like the per-class
        # gauge below.
        self._routed: set[int] = set(self._quarantined)
        ring.rep_inflight[worker, :] = 0
        for slot in self._quarantined:
            tenant = int(ring.slot_tenant[slot]) % ring.tenants
            ring.inflight[worker, tenant, ring.slot_class(slot)] += 1
            replica = int(ring.slot_replica[slot]) % ring.replicas
            ring.rep_inflight[worker, replica] += 1
        # The parked gauge's decrements lived in the dead incarnation's
        # event loop: any requests it had parked died with their
        # connections, so the respawned worker's cell restarts at zero —
        # otherwise a front-end crash during an engine outage would
        # report phantom parked requests for the life of the pod.
        ring.parked[worker] = 0
        # Completion-consumption CREDIT, one cell per replica queue (see
        # pop_completions): normally accumulated from the counted
        # doorbell; seeded here with the entries already queued, whose
        # doorbell credit a dead incarnation may have drained and taken
        # to its grave. A push racing this exact read could hand over a
        # half-published entry — the gen/pending checks in on_doorbell
        # drop it, costing at most one quarantined slot of capacity
        # until the pod restarts (the same documented leak class as a
        # crash between busy-flag and descriptor push), never a corrupt
        # response.
        self._credit = [
            int(ring.comp_head[r, worker]) - int(ring.comp_tail[r, worker])
            for r in range(ring.replicas)
        ]
        # slot -> (generation, future). A future that died waiting (the
        # request deadline) leaves its entry as a ZOMBIE: the slot is NOT
        # reusable until the engine's completion arrives — reusing it
        # early would let a stale in-flight response scribble over a new
        # request's slab.
        self._pending: dict[int, tuple[int, Any]] = {}

    # -------------------------------------------------------------- claim
    def claim(
        self, n_rows: int, tenant: int = 0, allow_overflow: bool = True,
        slo: int = 0,
    ) -> int | None:
        """A free slot whose slab fits ``n_rows``, or None (shed). Small
        requests prefer the small class and (with ``allow_overflow``,
        the 1-tenant default) may overflow into large; large requests
        never take a small slab. A multi-tenant caller passes
        ``allow_overflow=False``: the per-class quota governors admit
        against the class the ROW COUNT names, so a small request
        sneaking into a large slab (reachable when quarantined slots
        shrink the small free list) would occupy large capacity the
        large-class governor never accounted — a hot tenant could starve
        cold tenants' large floors with no quota signal. The slot is
        TAGGED with ``tenant`` in shm before any counter moves: the
        engine (and a respawned engine's replay) dispatches it against
        that tenant's bundle, and the per-tenant depth/release
        bookkeeping reads the tag back rather than threading the index
        through every path."""
        small_free, large_free = self._free
        if n_rows <= self.ring.small_rows:
            if small_free:
                slot = small_free.pop()
            elif allow_overflow and large_free:
                slot = large_free.pop()
            else:
                return None
        elif large_free:
            slot = large_free.pop()
        else:
            return None
        self.ring.slot_tenant[slot] = tenant
        # SLO class rides the slot header with the tenant tag (ISSUE 19,
        # stamped BEFORE any counter moves / descriptor visibility): the
        # engine's dispatch AND a respawned engine's replay both read
        # the class back from shm, so the serving tier survives every
        # crash window the tenant tag survives.
        self.ring.slot_slo[slot] = slo
        self.ring.inflight[
            self.worker, tenant, self.ring.slot_class(slot)
        ] += 1
        return slot

    def count_shed(self, n_rows: int, tenant: int = 0) -> None:
        cls = SMALL if n_rows <= self.ring.small_rows else LARGE
        self.ring.shed[self.worker, tenant, cls] += 1

    def count_quota_shed(self, tenant: int) -> None:
        """One admission refused by the tenant's own weighted max-min
        quota (free slots existed; the tenant's floor did not allow the
        claim) — the fairness contract's per-tenant observable."""
        self.ring.quota_shed[self.worker, tenant] += 1

    def pressure(self) -> float:
        """Occupied fraction of this worker's slot partition (0.0 =
        idle, 1.0 = the next claim sheds) — event-loop confined like the
        free lists it reads. Quarantined slots count as occupied: they
        hold real capacity until the engine's completion frees them."""
        if not self.partition_slots:
            return 0.0
        free = len(self._free[SMALL]) + len(self._free[LARGE])
        return 1.0 - free / self.partition_slots

    def count_demotion(self, brownout: bool = False) -> None:
        """One request served below its requested SLO class (ISSUE 19).
        ``brownout`` marks the governor-driven subset — demote-over-shed
        under pressure — vs. a deliberate cheap-tier header. Single
        writer: this worker's event loop (the expired/shed discipline)."""
        self.ring.tier_demote[self.worker] += 1
        if brownout:
            self.ring.brownout_demote[self.worker] += 1

    def submit(
        self,
        slot: int,
        cat: np.ndarray,
        num: np.ndarray,
        deadline: float | None = None,
        replica: int | None = None,
    ):
        """Write the encoded arrays into the slot's slab and enqueue it
        on one engine replica's submission queue — ``replica`` None (the
        default) lets the `ReplicaRouter` pick (least-loaded live depth,
        small-class tenant affinity). Returns the asyncio future the
        completion resolves (with the engine's response status).
        ``deadline`` — absolute ``time.monotonic`` seconds (the event
        loop's clock) — rides in the slot header so the engine can
        complete an already-expired descriptor as RESP_EXPIRED instead
        of dispatching dead work."""
        import asyncio

        n = cat.shape[0]
        ring = self.ring
        if replica is None:
            replica = self.router.route(
                int(ring.slot_tenant[slot]), ring.slot_class(slot)
            )
        slab_cat, slab_num = ring.request_views(slot)
        slab_cat[:n] = cat
        slab_num[:n] = num
        ring.slot_n[slot] = n
        ring.slot_deadline[slot] = deadline if deadline is not None else 0.0
        # Replica tag BEFORE busy (which is BEFORE the descriptor push):
        # whatever window this process dies in, the slot's owner replica
        # is already named in shm, so the quarantine depth rebuild and
        # the replica's replay both see a consistent tag.
        ring.slot_replica[slot] = replica
        gen = (int(ring.slot_gen[slot]) + 1) & 0xFFFFFFFF
        ring.slot_gen[slot] = gen
        # Busy BEFORE the descriptor push: if this process dies anywhere
        # past here, the next incarnation quarantines the slot instead of
        # racing the engine for its slab.
        ring.slot_busy[slot] = 1
        self._routed.add(slot)
        ring.rep_inflight[self.worker, replica] += 1
        future = asyncio.get_running_loop().create_future()
        self._pending[slot] = (gen, future)
        ring.submit(slot, gen, replica)
        return future

    def release(self, slot: int) -> None:
        """Return a slot whose response has been consumed (or that was
        never submitted) to the free list."""
        self._pending.pop(slot, None)
        self.ring.slot_busy[slot] = 0
        cls = self.ring.slot_class(slot)
        tenant = int(self.ring.slot_tenant[slot]) % self.ring.tenants
        self._free[cls].append(slot)
        self.ring.inflight[self.worker, tenant, cls] -= 1
        if slot in self._routed:
            # Submitted slots counted toward their replica's live depth
            # at submit; a claim released un-submitted (deadline before
            # encode, error paths) never incremented it.
            self._routed.discard(slot)
            replica = int(self.ring.slot_replica[slot]) % self.ring.replicas
            self.ring.rep_inflight[self.worker, replica] -= 1

    def abandon(self, slot: int) -> None:
        """Deadline/error path after a successful submit: if the response
        already landed, the slot is safe to reuse now; otherwise leave
        the pending entry as a zombie — the completion handler releases
        it when the engine answers (never reuse a slab with an engine
        write potentially in flight)."""
        entry = self._pending.get(slot)
        if entry is None:
            # Already handled: `asyncio.wait_for` yields to the loop
            # between cancelling the future and raising TimeoutError, and
            # if the completion lands in that window `on_doorbell`'s
            # zombie path releases the slot first. Releasing again here
            # would put the slot on the free list twice — two requests
            # sharing one slab — and underflow the inflight gauge.
            return
        # A deadline-CANCELLED future means the engine's answer is still
        # in flight — only a future that actually carries the response
        # (done, not cancelled) proves the slab is quiescent.
        if entry[1].done() and not entry[1].cancelled():
            self.release(slot)

    def response_arrays(
        self, slot: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        return self.ring.response_views(slot)

    # -------------------------------------------------------- completions
    def on_doorbell(self, replica: int = 0) -> None:
        """Event-loop reader callback for this worker's per-replica
        doorbell (one registered fd per engine replica): drain that
        replica's completion descriptors, resolve live futures, release
        zombies, and drain the quarantine (slots inherited busy from a
        crashed incarnation — the engine answering them is the proof
        their slabs are quiescent)."""
        ring = self.ring
        credit = self._credit[replica] + ring.worker_doorbell(
            self.worker, replica
        ).drain()
        self._credit[replica] = 0
        # Any credit beyond what pops is SURPLUS, not a future
        # entitlement (entries are always published before their ring,
        # and a respawn's seeded credit can overlap the dead
        # incarnation's still-undrained doorbell) — discard it rather
        # than let a later consume run ahead of the fence; un-credited
        # entries always arrive with their own ring.
        popped = ring.pop_completions(self.worker, limit=credit,
                                      replica=replica)
        for slot, gen in popped:
            entry = self._pending.get(slot)
            if entry is None or entry[0] != gen:
                # Stale generation: a completion addressed to the dead
                # incarnation. If the slot sat in quarantine, this is the
                # all-clear to reuse it.
                if slot in self._quarantined:
                    self._quarantined.discard(slot)
                    ring.slot_busy[slot] = 0
                    cls = ring.slot_class(slot)
                    tenant = int(ring.slot_tenant[slot]) % ring.tenants
                    self._free[cls].append(slot)
                    ring.inflight[self.worker, tenant, cls] -= 1
                    if slot in self._routed:
                        self._routed.discard(slot)
                        owner = int(ring.slot_replica[slot]) % ring.replicas
                        ring.rep_inflight[self.worker, owner] -= 1
                continue
            _, future = entry
            if future.cancelled():
                self.release(slot)  # zombie: waiter gave up; reuse now
            elif future.done():
                # Duplicate completion for a live (slot, gen): possible
                # only across an engine respawn, when the replay
                # re-answers a slot whose original completion the dead
                # incarnation had already queued. The first pop resolved
                # the future and its awaiting handler owns the release —
                # releasing here too would double-free the slot (two
                # requests sharing one slab). Drop the duplicate.
                continue
            elif int(ring.resp_incarnation[slot]) != int(
                ring.eng_vals[replica, ENG_INCARNATION]
            ):
                # Incarnation guard (ISSUE 11): this completion was
                # produced by a DEAD incarnation of this replica (it may
                # have died mid-batch; nothing about its leftovers is
                # trusted). Leave the future pending — the respawned
                # replica's replay re-answers this slot with a fresh
                # completion, or the request's deadline budget turns it
                # into a 504 and the zombie path reclaims the slot.
                logger.info(
                    "dropping completion for slot %d from dead engine "
                    "replica %d incarnation %d (current %d); replay will "
                    "re-answer",
                    slot, replica, int(ring.resp_incarnation[slot]),
                    int(ring.eng_vals[replica, ENG_INCARNATION]),
                )
            elif int(ring.resp_gen[slot]) != gen:
                # Descriptor/slab mismatch: the slab does not carry THIS
                # request's answer (should be impossible for a live
                # incarnation — the engine stamps resp_gen before the
                # completion). Leave the future pending; the deadline
                # turns it into a 503 and the zombie path reclaims.
                logger.error(
                    "ring completion for slot %d gen %d but slab carries "
                    "gen %d; dropping", slot, gen, int(ring.resp_gen[slot]),
                )
            else:
                future.set_result(int(ring.resp_status[slot]))

    def pending_count(self) -> int:
        return len(self._pending)


class RingService:
    """Engine-process half: collect submitted slots, coalesce small
    requests into grouped device dispatches (the micro-batcher's policy,
    greedy over whatever is queued — under load the queue is never
    empty, which is exactly when grouping pays), run them on a small
    thread pool so device round trips overlap, write raw responses into
    the slabs, and ring the owners' doorbells.

    The engine always answers every accepted descriptor — success or a
    status-1 error — so front-end futures never wait on a dropped slot,
    and it never blocks on front-end state, so front-end churn (crash,
    restart, kill -9) cannot wedge the engine.
    """

    def __init__(
        self,
        engine: Any,
        ring: RequestRing,
        max_group: int = 64,
        max_inflight: int = 4,
        threads: int = 8,
        monitor_fetch_every_s: float = 2.0,
        monitor_fetch_every_requests: int = 512,
        engines: list[Any] | None = None,
        replica: int = 0,
    ) -> None:
        import concurrent.futures

        self.engine = engine
        # Engine replica set (ISSUE 13): this service consumes submission
        # queue ``replica``, pushes completions through ITS queue rows
        # under ITS completion lock, and mirrors telemetry into ITS rows
        # of every engine-written stats block. 0 — the pre-replica call
        # shape — is the lead replica (profile forwarding, lifecycle).
        self.replica = int(replica)
        if not 0 <= self.replica < ring.replicas:
            raise ValueError(
                f"replica {replica} outside the ring's {ring.replicas} "
                "replica rows"
            )
        # Tenant fleet (mlops_tpu/tenancy/): ``engines[t]`` serves slot
        # tenant index ``t``. The single-engine call shape (every
        # pre-tenancy caller, the test stubs) is the degenerate 1-tenant
        # fleet — identical dispatch behavior by construction.
        self.engines: list[Any] = (
            list(engines) if engines is not None else [engine]
        )
        # Exactly one engine per tenant row — FEWER would make
        # _slot_tenant's defensive clamp wrap a declared tenant's tag
        # onto another tenant's model and serve the wrong portfolio
        # with a 200 (front ends route by the ring's tenant_names, so
        # every row is reachable).
        if len(self.engines) != ring.tenants:
            raise ValueError(
                f"{len(self.engines)} engines but the ring carries "
                f"{ring.tenants} tenant rows"
            )
        self.ring = ring
        # A group can never exceed the largest warmed slot bucket — beyond
        # it there is no compiled shape to run (same clamp as the
        # in-process micro-batcher).
        self.max_group = max(2, min(max_group, GROUP_SLOT_BUCKETS[-1]))
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2, threads), thread_name_prefix="ring"
        )
        self._inflight = threading.BoundedSemaphore(max_inflight)
        self._mon_lock = threading.Lock()
        self._stop = threading.Event()
        self._collector: threading.Thread | None = None
        self._telemetry: threading.Thread | None = None
        self._mon_period = monitor_fetch_every_s
        self._mon_every = monitor_fetch_every_requests
        self._accumulating = [
            bool(getattr(eng, "monitor_accumulating", False))
            for eng in self.engines
        ]
        self._any_accumulating = any(self._accumulating)
        # Optional lifecycle controllers (mlops_tpu/lifecycle/), attached
        # by the engine process after warmup — ONE PER TENANT (tenant A
        # drifting retrains, shadows, and promotes A alone): the
        # telemetry loop mirrors each controller's gauge snapshot into
        # its tenant's shm row every tick so any front end can render
        # the whole fleet's loop state. ``lifecycle`` keeps the
        # pre-tenancy single-controller surface (tenant 0).
        self.lifecycle: Any = None
        self.lifecycles: list[Any] | None = None
        # Respawn bases (ISSUE 11, set by `reattach`): the degraded /
        # lifecycle counter mirrors below are ABSOLUTE writes from
        # in-process totals that restart at zero in a respawned engine —
        # the dead incarnation's last-published values are carried as
        # additive bases so the exported counters stay monotone (the
        # same contract as `seed_monitor_totals`). Life bases are keyed
        # by tenant row.
        self._degraded_base = 0.0
        self._life_base: dict[int, dict[str, Any]] = {}
        # /debug/profile forwarding (tracewire): the engine process owns
        # the device, so front ends forward start/stop through the ring's
        # profile-control word; `profiler` is the engine-side handler
        # (serve/server.py JaxProfiler.control — set by serve_multi_worker
        # when serve.profile_dir is configured), None = 404.
        self.profiler: Any = None
        # sloscope (ISSUE 14): the LEAD replica's telemetry loop ticks
        # an attached `slo/engine.SLOEngine` (reading the fleet's shm
        # request counters) and mirrors its view into the slo/alert
        # rows; an attached `slo/ledger.CostLedger` mirrors into this
        # replica's ledger table. Both attach after construction,
        # before start() (engine-process wiring in _engine_main).
        self.slo: Any = None
        self.cost_ledger: Any = None
        # gridtuner (ISSUE 18): each replica's engine process attaches
        # its `autotune.AutotuneController` (lead = planner, siblings =
        # adopt mode) after warmup; the telemetry loop mirrors its gauge
        # snapshot into this replica's auto_vals/auto_plans rows so any
        # front end renders the fleet's regrid state.
        self.autotune: Any = None
        self._slo_last = 0.0  # telemetry-thread private tick clock
        self._prof_handled = 0  # collector-thread private
        self._requests_since_fetch = 0  # collector-thread private counter;
        # the telemetry thread only READS it (a torn read costs one fetch
        # of cadence, never correctness — the totals live on device)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        self._collector = threading.Thread(
            target=self._collect, name="ring-collector", daemon=True
        )
        self._collector.start()
        # The telemetry thread runs for EITHER consumer: the monitor
        # mirror (accumulating engines with a nonzero cadence — the
        # pre-sloscope condition) or sloscope's evaluator/ledger mirror,
        # which must tick even when serve.monitor_fetch_every_s=0
        # disables the monitor timer (an operator arming slo.enabled
        # must never get a silently dead alert layer).
        wants_monitor = self._any_accumulating and self._mon_period > 0
        wants_slo = self.slo is not None or self.cost_ledger is not None
        if wants_monitor or wants_slo:
            self._telemetry = threading.Thread(
                target=self._telemetry_loop, name="ring-telemetry", daemon=True
            )
            self._telemetry.start()

    def stop(self) -> None:
        """Drain: stop collecting, finish in-flight jobs, final monitor
        write. Safe to call twice."""
        self._stop.set()
        # wake the collector's select
        self.ring.engine_doorbells[self.replica].ring()
        for thread in (self._collector, self._telemetry):
            if thread is not None:
                thread.join(timeout=10)
        self._pool.shutdown(wait=True)
        for t, eng in enumerate(self.engines):
            if not self._accumulating[t]:
                continue
            try:
                self.ring.write_monitor(
                    eng.monitor_snapshot(), t, self.replica
                )
            except Exception:  # tpulint: disable=TPU201
                logger.exception("final monitor snapshot failed on drain")
        self._write_lifecycle()
        self._write_robustness()
        self._write_shapes()
        self._tick_slo(force=True)
        self._write_ledger()

    # ------------------------------------------------------------ collect
    def _collect(self) -> None:
        ring = self.ring
        # Submission-consumption CREDIT (the mirror of RingClient._credit
        # — see pop_submissions): normally accumulated from the counted
        # engine doorbell; seeded here with the entries already queued,
        # whose credit a dead engine incarnation may have drained and
        # taken to its grave. Surplus credit after a pop is DISCARDED,
        # never banked — un-credited entries always arrive with their own
        # ring, and banking surplus would let a later consume run ahead
        # of the eventfd fence.
        credit = int(ring.sub_head[self.replica]) - int(
            ring.sub_tail[self.replica]
        )
        while not self._stop.is_set():
            if self.replica == 0:
                # /debug/profile rides the single shm control word and is
                # answered by the LEAD replica only (one device trace at
                # a time; the channel has one seq space).
                self._handle_profile()
            descs = (
                ring.pop_submissions(limit=credit, replica=self.replica)
                if credit
                else []
            )
            credit = 0
            if not descs:
                credit = ring.engine_doorbells[self.replica].wait(
                    timeout_s=1.0
                )
                continue
            if ring.tracing:
                # Engine-half span stamp 1: the descriptor left the ring
                # queue (ring_wait ends). One clock read per pop batch —
                # the whole batch was popped together.
                now = time.monotonic()
                for slot, _ in descs:
                    ring.resp_trace[slot, 0] = now
            self._requests_since_fetch += len(descs)
            for job in self._make_jobs(descs):
                # Backpressure: the dispatch bound blocks the collector,
                # submissions pile in the ring, front ends run out of
                # slots, and the SHED path answers 503 — bounded end to
                # end with no unbounded queue anywhere.
                self._inflight.acquire()
                self._pool.submit(self._run_job, job)

    def _slot_tenant(self, slot: int) -> int:
        """The slot's shm tenant tag, clamped into the engine list.
        The constructor guarantees one engine per tenant row, so every
        tag a front end can stamp maps to exactly its own engine; the
        modulo only defends against a garbage value (a crashed writer's
        scribble) ever indexing out of range — the tag itself is a
        single aligned store written before submit, so a torn read is
        not a real failure mode."""
        return int(self.ring.slot_tenant[slot]) % len(self.engines)

    def _slot_tier(self, slot: int, tenant: int) -> str | None:
        """The serving tier the slot's shm SLO class resolves to on its
        tenant's engine (None = the default tier — the plain exec keys,
        bit-for-bit the historical dispatch). Reading the class back out
        of shm — instead of threading it through descriptors — is what
        makes the respawn replay tier-faithful for free: the replay
        calls the same resolver over the same header."""
        slo_tags = getattr(self.ring, "slot_slo", None)
        route = getattr(self.engines[tenant], "route_tier", None)
        if slo_tags is None or route is None:
            return None
        return route(int(slo_tags[slot]))

    def _make_jobs(
        self, descs: list[tuple[int, int]]
    ) -> list[list[tuple[int, int]]]:
        """The coalescing policy, shared by the live collector and the
        re-attach replay: small requests group up to ``max_group`` per
        device dispatch, everything else runs solo. Grouping is PER
        (TENANT, TIER) — a grouped dispatch runs one tenant's compiled
        program for one serving tier with one tenant's params and folds
        one tenant's monitor accumulator, so slots from different
        tenants — or different SLO tiers of one tenant (ISSUE 19) — can
        never share a device dispatch (they still share the pool and
        the ring)."""
        ring = self.ring
        groupable: dict[
            tuple[int, str | None], list[tuple[int, int]]
        ] = {}
        solo: list[tuple[int, int]] = []
        for slot, gen in descs:
            n = int(ring.slot_n[slot])
            tenant = self._slot_tenant(slot)
            can_group = getattr(
                self.engines[tenant], "supports_grouping", False
            )
            if can_group and 1 <= n <= GROUP_ROW_BUCKET:
                tier = self._slot_tier(slot, tenant)
                groupable.setdefault((tenant, tier), []).append((slot, gen))
            else:
                solo.append((slot, gen))
        jobs: list[list[tuple[int, int]]] = []
        for key in sorted(groupable, key=lambda k: (k[0], k[1] or "")):
            batch = groupable[key]
            for i in range(0, len(batch), self.max_group):
                jobs.append(batch[i : i + self.max_group])
        jobs.extend([d] for d in solo)
        return jobs

    # ----------------------------------------------------------- reattach
    def reattach(self) -> dict[str, Any]:
        """Engine-incarnation re-attach + busy-slot replay (ISSUE 11):
        run by the engine process after warmup and BEFORE `start`, every
        boot (a first boot just finds nothing to replay).

        Steps, in order: (1) bump the shm engine-incarnation word — every
        completion a dead incarnation left behind becomes droppable on
        arrival (the consumer's incarnation guard); (2) recover the
        completion lock the dead incarnation may have died holding;
        (3) seed the engine's exact host-side monitor totals from the shm
        aggregate so exported counters stay monotone across the respawn,
        and count the accumulator window that died with the old process
        in ``monitor_rows_lost_total`` (bounded by the telemetry fetch
        cadence — never silently wrong); (4) REPLAY every busy slot whose
        descriptor is not still queued (those reach the collector
        normally): the request slabs hold the full pre-encoded input and
        the packed programs are pure, so the replayed answer is
        bit-identical to what the dead engine would have served; (5) ring
        every worker doorbell with its full outstanding completion count
        — stranded entries whose credit died with the old incarnation
        flush through (surplus credit is discarded consumer-side)."""
        ring = self.ring
        # Injection point (mlops_tpu/faults): delay = a slow re-attach
        # (stretches the brownout window the chaos smoke measures);
        # raise = a failed re-attach — this engine process exits nonzero
        # and the supervisor retries with a fresh fork.
        faults.fire("serve.ring.reattach")
        rep = self.replica
        incarnation = int(ring.eng_vals[rep, ENG_INCARNATION]) + 1
        ring.eng_vals[rep, ENG_INCARNATION] = incarnation
        ring.recover_engine_locks(rep)
        # Monotone-counter seeding for the ABSOLUTE mirrors: degraded
        # dispatches, lifecycle counters, and shape histograms all mirror
        # in-process totals that restart at zero with this process —
        # without bases/seeding, the first telemetry tick after a respawn
        # would regress the exported counters (a Prometheus counter
        # reset, and a chaos-smoke monotonicity failure).
        self._degraded_base = float(ring.rob_vals[rep, ROB_DEGRADED])
        for t in range(len(self.engines)):
            if float(ring.life_vals[t, LIFE_HAS]):
                self._life_base[t] = {
                    "drift_triggers": float(ring.life_vals[t, LIFE_TRIGGERS]),
                    "breaker_trips": float(
                        ring.life_vals[t, LIFE_BREAKER_TRIPS]
                    ),
                    "promotions": {
                        outcome: float(ring.life_promos[t, i])
                        for i, outcome in enumerate(LIFE_OUTCOMES)
                    },
                }
        stats = getattr(self.engine, "shape_stats", None)
        if stats is not None and float(ring.shape_meta[rep]) > 0:
            from mlops_tpu.trace.shapes import read_table

            stats.seed(
                read_table(ring.shape_keys[rep], ring.shape_vals[rep]),
                t0=float(ring.shape_meta[rep]),
            )
        rows_lost = 0.0
        for t, eng in enumerate(self.engines):
            if self._accumulating[t] and float(
                ring.mon_vals[rep, t, MON_HAS]
            ):
                eng.seed_monitor_totals(
                    float(ring.mon_vals[rep, t, MON_ROWS]),
                    float(ring.mon_vals[rep, t, MON_OUTLIERS]),
                    float(ring.mon_vals[rep, t, MON_BATCHES]),
                    np.asarray(ring.mon_drift_sum[rep, t], np.float64),
                    np.asarray(ring.mon_drift_last[rep, t], np.float64),
                )
        pending = ring.pending_submissions(rep)
        # Only THIS replica's busy slots replay: a sibling replica's
        # in-flight slots are its own live work (or its own successor's
        # replay) — re-answering them here would double-serve a slab a
        # live process may be writing.
        replay = [
            (slot, int(ring.slot_gen[slot]))
            for slot in range(ring.n_slots)
            if int(ring.slot_busy[slot])
            and int(ring.slot_replica[slot]) % ring.replicas == rep
            and slot not in pending
        ]
        replay_rows = sum(int(ring.slot_n[slot]) for slot, _ in replay)
        replay_rows_by_tenant: dict[int, int] = {}
        for slot, _ in replay:
            t = self._slot_tenant(slot)
            replay_rows_by_tenant[t] = (
                replay_rows_by_tenant.get(t, 0) + int(ring.slot_n[slot])
            )
        fetched_total = 0.0
        for t in range(len(self.engines)):
            if not self._accumulating[t]:
                continue
            # The dead engine's device accumulator window, PER TENANT:
            # rows it folded on device (eng_rows_tenant) minus rows a
            # telemetry fetch preserved (that tenant's MON_ROWS), minus
            # the rows the replay below re-folds into that tenant's
            # accumulator. Counted, then the dispatch baseline
            # re-anchors to the fetched totals so the replayed rows land
            # exactly once — per tenant, so one tenant's loss can never
            # hide inside another tenant's surplus.
            dispatched = float(ring.eng_rows_tenant[rep, t])
            fetched = float(ring.mon_vals[rep, t, MON_ROWS])
            fetched_total += fetched
            rows_lost += max(
                0.0,
                dispatched - fetched - replay_rows_by_tenant.get(t, 0),
            )
            ring.eng_rows_tenant[rep, t] = fetched
        if rows_lost:
            ring.eng_vals[rep, ENG_ROWS_LOST] += rows_lost
        ring.eng_vals[rep, ENG_ROWS_DISPATCHED] = fetched_total
        if replay:
            import concurrent.futures

            pending_jobs = []
            for job in self._make_jobs(replay):
                self._inflight.acquire()
                pending_jobs.append(self._pool.submit(self._run_job, job))
            # Synchronous by design: every parked request is re-answered
            # (or expired against its own deadline) before the ready flag
            # flips — "resume" in the runbook's timeline means exactly
            # this join having completed. An unexpected _run_job failure
            # re-raises: a half-replayed engine must exit and let the
            # supervisor retry with a fresh fork, not limp into ready.
            concurrent.futures.wait(pending_jobs)
            for job_future in pending_jobs:
                exc = job_future.exception()
                if exc is not None:
                    raise exc
            ring.eng_vals[rep, ENG_REPLAYED] += len(replay)
            self._requests_since_fetch += len(replay)
        # Generous credit flush, replay or not: any completion entry
        # still queued in THIS replica's rows (stranded by the death
        # window between a push and its doorbell ring, or published for
        # a worker that has not drained yet) gets credited; consumers
        # discard the surplus.
        for worker in range(ring.workers):
            outstanding = int(ring.comp_head[rep, worker]) - int(
                ring.comp_tail[rep, worker]
            )
            if outstanding > 0:
                ring.worker_doorbell(worker, rep).ring(outstanding)
        return {
            "incarnation": incarnation,
            "replayed_slots": len(replay),
            "replay_rows": replay_rows,
            "monitor_rows_lost": rows_lost,
        }

    def _handle_profile(self) -> None:
        """Claim a pending /debug/profile request word. Single-word
        protocol both ways (request = seq<<8 | action, ack = seq<<16 |
        status), so neither side can observe a half-written exchange on
        any memory model; the issuing front end holds the profile lease
        until it sees the ack, so there is exactly one outstanding seq.
        The profiler call itself runs on the POOL, never here — a slow
        ``jax.profiler.start_trace`` on the collector thread would stall
        the plane's only dispatcher and every in-flight request with it;
        one occupied pool thread just costs capacity."""
        req = int(self.ring.prof_ctl[0])
        seq = req >> 8
        if not seq or seq == self._prof_handled:
            return
        self._prof_handled = seq
        action = {1: "start", 2: "stop"}.get(req & 0xFF)
        if self.profiler is None or action is None:
            self._ack_profile(seq, 404)
        else:
            self._pool.submit(self._run_profile, seq, action)

    def _run_profile(self, seq: int, action: str) -> None:
        try:
            status = int(self.profiler(action)[0])
        # A profiler bug costs the request a 500, never the pool thread.
        except Exception:  # tpulint: disable=TPU201
            logger.exception("ring profile %s failed", action)
            status = 500
        self._ack_profile(seq, status)

    def _ack_profile(self, seq: int, status: int) -> None:
        # Never regress the ack word: an op abandoned by its front end's
        # timeout acks late (the profiler serializes ops, so acks arrive
        # in seq order — this guard is the backstop for that invariant,
        # keeping a stale ack from masking a live op's answer).
        if seq >= int(self.ring.prof_ctl[1]) >> 16:
            self.ring.prof_ctl[1] = (seq << 16) | (status & 0xFFFF)

    # --------------------------------------------------------------- jobs
    def _run_job(self, job: list[tuple[int, int]]) -> None:
        ring = self.ring
        try:
            if ring.tracing:
                # Engine-half span stamp 2: a pool thread owns the job
                # (engine_queue ends; dispatch begins).
                now = time.monotonic()
                for slot, _ in job:
                    ring.resp_trace[slot, 1] = now
            # Dead-work shedding (ISSUE 9): a descriptor whose deadline
            # budget (slot header, stamped by the front end at submit)
            # ran out while it queued is completed RESP_EXPIRED WITHOUT
            # dispatching — under overload the device's cycles go to
            # requests whose clients are still listening. The engine
            # still answers every accepted descriptor, expired included.
            now = time.monotonic()
            live: list[tuple[int, int]] = []
            expired: list[tuple[int, int]] = []
            for slot, gen in job:
                slot_deadline = float(ring.slot_deadline[slot])
                if slot_deadline and now >= slot_deadline:
                    expired.append((slot, gen))
                else:
                    live.append((slot, gen))
            if expired:
                with self._mon_lock:
                    ring.rob_vals[self.replica, ROB_EXPIRED_ENGINE] += len(
                        expired
                    )
            raws, status = None, RESP_OK
            tenant = self._slot_tenant(job[0][0]) if job else 0
            if live:
                try:
                    raws = self._score(live, tenant)
                # The breadth is the contract: ANY scoring failure (device
                # error, geometry bug) must become an error completion on
                # every waiting slot — a dropped descriptor would strand
                # the front end's future until its deadline.
                except Exception:  # tpulint: disable=TPU201
                    logger.exception(
                        "ring dispatch failed (%d slots)", len(live)
                    )
                    raws, status = None, RESP_ERROR
            if live and status == RESP_OK and self._accumulating[tenant]:
                # Rows now folded into the tenant's device accumulator but
                # not yet preserved by a telemetry fetch — the re-attach
                # reads this against the tenant's MON_ROWS to bound what
                # an engine death loses (monitor_rows_lost_total, ISSUE
                # 11). The eng_vals cell keeps the fleet sum.
                rows = sum(int(ring.slot_n[s]) for s, _ in live)
                with self._mon_lock:
                    ring.eng_rows_tenant[self.replica, tenant] += rows
                    ring.eng_vals[self.replica, ENG_ROWS_DISPATCHED] += rows
            incarnation = int(ring.eng_vals[self.replica, ENG_INCARNATION])
            for i, (slot, gen) in enumerate(live):
                # Stale-generation write guard: if the slot has moved on
                # (its front end crashed and the respawned incarnation
                # bumped the generation), REFUSE to touch the slab — with
                # the quarantine on the client side this job's slot
                # cannot have been re-claimed, but the guard keeps slab
                # writes correct even if a future client mismanages the
                # free list. The completion still goes out: it is what
                # releases the quarantined slot.
                if status == RESP_OK and int(ring.slot_gen[slot]) == gen:
                    pred, out, drift = raws[i]
                    resp_pred, resp_out, resp_drift = ring.response_views(slot)
                    resp_pred[:] = pred
                    resp_out[:] = out
                    resp_drift[:] = drift
                ring.resp_status[slot] = status
                # Incarnation stamp (with resp_gen, before the push): the
                # consumer trusts a completion only when this matches the
                # live incarnation word — a dead engine's leftovers are
                # dropped and re-answered by the replay instead.
                ring.resp_incarnation[slot] = incarnation
                ring.resp_gen[slot] = gen
            for slot, gen in expired:
                ring.resp_status[slot] = RESP_EXPIRED
                ring.resp_incarnation[slot] = incarnation
                ring.resp_gen[slot] = gen
            # The doorbell count IS the owner's consumption credit: ring
            # AFTER the pushes with how many landed, per owner.
            owners: dict[int, int] = {}
            for slot, gen in job:
                ring.push_completion(slot, gen, self.replica)
                owner = ring.slot_owner(slot)
                owners[owner] = owners.get(owner, 0) + 1
            for worker, count in owners.items():
                ring.worker_doorbell(worker, self.replica).ring(count)
        finally:
            self._inflight.release()

    def _score(
        self, job: list[tuple[int, int]], tenant: int = 0
    ) -> list[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Score one job -> per-slot raw (predictions, outliers, drift).
        Multi-slot jobs ride ONE grouped device dispatch
        (`dispatch_group_arrays` — the arrays come pre-encoded from the
        front ends, so the engine process does zero per-record Python).
        ``tenant`` selects the bundle: every slot in a job belongs to one
        tenant (`_make_jobs` partitions), so the whole job dispatches
        through that tenant's engine — its params, its monitor
        accumulator, its temperature."""
        ring, engine = self.ring, self.engines[tenant]
        tracing = ring.tracing
        # Serving tier (ISSUE 19): every slot in a job resolves to ONE
        # tier (`_make_jobs` partitions per (tenant, tier)), so the
        # whole job dispatches through that tier's compiled entries.
        # The kwarg is only passed when a tier actually resolved — the
        # single-tier call shape stays byte-identical for stub engines.
        tier = self._slot_tier(job[0][0], tenant)
        parts = []
        for slot, _ in job:
            n = int(ring.slot_n[slot])
            cat, num = ring.request_views(slot)
            parts.append((cat[:n], num[:n]))
        if len(parts) >= 2:
            handle = (
                engine.dispatch_group_arrays(parts, tier=tier)
                if tier is not None
                else engine.dispatch_group_arrays(parts)
            )
            if tracing:
                self._stamp_dispatched(job, handle, kind=2)
            sizes, preds, outs, drifts = engine.fetch_group_raw(handle)
            raws = [
                (preds[i, :n], outs[i, :n], drifts[i])
                for i, n in enumerate(sizes)
            ]
        else:
            cat, num = parts[0]
            handle = (
                engine.dispatch_arrays(cat, num, tier=tier)
                if tier is not None
                else engine.dispatch_arrays(cat, num)
            )
            if tracing:
                self._stamp_dispatched(job, handle, kind=1)
            handle.start_copy()
            raws = [engine.fetch_arrays_raw(handle)]
        label = tier if tier is not None else getattr(
            engine, "default_tier", None
        )
        if label in _TIER_IDX and getattr(ring, "tier_counts", None) is not None:
            with self._mon_lock:
                ring.tier_counts[self.replica, _TIER_IDX[label]] += len(job)
        if tracing:
            # Engine-half span stamp 4: the blocking host copy landed
            # (device_fetch ends; the remainder to the front end's
            # "respond" stamp is completion-doorbell wait + formatting).
            now = time.monotonic()
            for slot, _ in job:
                ring.resp_trace[slot, 3] = now
        if not self._accumulating[tenant]:
            self._fold_host_monitor(raws, tenant)
        return raws

    def _stamp_dispatched(
        self, job: list[tuple[int, int]], handle: Any, kind: int
    ) -> None:
        """Engine-half span stamp 3 (device enqueued + async D2H started)
        plus the compiled-entry encoding the front end decodes back into
        a name: kind 1 = solo bucket (geom = padded rows), kind 2 = group
        (geom = slots * 100000 + rows, from the geometry ints the handle
        carries — degraded-fallback aware, since the engine sets them
        AFTER choosing the shape that actually served)."""
        ring = self.ring
        if kind == 2:
            geom = int(getattr(handle, "slots", 0)) * 100000 + int(
                getattr(handle, "rows", 0)
            )
        else:
            geom = int(getattr(handle, "rows", 0))
        now = time.monotonic()
        for slot, _ in job:
            ring.resp_trace[slot, 2] = now
            ring.resp_trace[slot, 4] = float(kind)
            ring.resp_trace[slot, 5] = float(geom)

    def _fold_host_monitor(
        self,
        raws: list[tuple[np.ndarray, np.ndarray, np.ndarray]],
        tenant: int = 0,
    ) -> None:
        """Host-side monitor fold for engines without a device accumulator
        (the sklearn flavor / test stubs) — the seed's per-response
        `observe_prediction`, landed in the tenant's shared block
        instead. The numpy reductions run OUTSIDE the lock; only the
        scalar read-modify-writes sit inside."""
        rows = sum(len(pred) for pred, _, _ in raws)
        outliers = float(sum(float(out.sum()) for _, out, _ in raws))
        last = raws[-1][2]
        ring, rep = self.ring, self.replica
        with self._mon_lock:
            ring.mon_vals[rep, tenant, MON_ROWS] += rows
            ring.mon_vals[rep, tenant, MON_OUTLIERS] += outliers
            ring.mon_vals[rep, tenant, MON_BATCHES] += len(raws)
            ring.mon_drift_last[rep, tenant, :] = last
            ring.mon_vals[rep, tenant, MON_HAS] = 1.0

    # ----------------------------------------------------------- telemetry
    def _telemetry_loop(self) -> None:
        """Single-flight monitor aggregate reads, ENGINE PROCESS ONLY (the
        front ends render whatever this loop last wrote): fetch when K
        ring requests accumulated or the T-second cadence lapses with
        traffic outstanding — the device is never fetched per request or
        per scrape."""
        # mon_period can be 0 here (monitor timer disabled, sloscope
        # armed): the tick then floors at 0.25 s instead of busy-looping,
        # and the monitor-fetch block below is skipped entirely.
        tick = min(0.25, self._mon_period) if self._mon_period > 0 else 0.25
        last_fetch = time.monotonic()
        while not self._stop.wait(tick):
            self._write_lifecycle()
            self._write_robustness()
            self._write_shapes()
            self._tick_slo()
            self._write_ledger()
            self._write_autotune()
            if not (self._any_accumulating and self._mon_period > 0):
                continue
            due_k = self._mon_every and (
                self._requests_since_fetch >= self._mon_every
            )
            due_t = (
                time.monotonic() - last_fetch >= self._mon_period
                and self._requests_since_fetch > 0
            )
            never = any(
                self._accumulating[t]
                and self.ring.mon_vals[self.replica, t, MON_HAS] == 0.0
                for t in range(len(self.engines))
            )
            if not (due_k or due_t or never):
                continue
            self._requests_since_fetch = 0
            last_fetch = time.monotonic()
            for t, eng in enumerate(self.engines):
                if not self._accumulating[t]:
                    continue
                try:
                    self.ring.write_monitor(
                        eng.monitor_snapshot(), t, self.replica
                    )
                # A transient device fetch failure keeps the last-written
                # gauges; the next tick retries (same contract as the
                # single-process fetch task's done-callback).
                except Exception:  # tpulint: disable=TPU201
                    logger.exception(
                        "ring monitor fetch failed (tenant %d); gauges "
                        "stale", t,
                    )

    def _tick_slo(self, force: bool = False) -> None:
        """One sloscope evaluation + shm mirror (LEAD replica only — the
        rows have one writer, and the engine reads the same fleet-wide
        shm counters from any replica anyway). Rate-limited to the
        configured tick; ``force`` (the drain path) publishes the final
        state regardless."""
        slo = self.slo
        if slo is None or self.replica != 0:
            return
        now = time.monotonic()
        if not force and now - self._slo_last < float(slo.config.tick_s):
            return
        self._slo_last = now
        try:
            slo.tick()
            slo.write_rows(self.ring.slo_vals, self.ring.alert_vals)
        # Telemetry breadth contract: an evaluator bug costs one tick of
        # gauge freshness, never the telemetry thread.
        except Exception:  # tpulint: disable=TPU201
            logger.exception("slo tick failed; alert gauges stale")

    def _write_ledger(self) -> None:
        """Mirror this replica's cost-ledger totals into its shm table
        (host counter reads + f64 stores, no device work) so any front
        end's /metrics renders the entry_* series; the render merges
        replica tables by entry key."""
        ledger = self.cost_ledger
        if ledger is None:
            return
        rep = self.replica
        ledger.write_table(
            self.ring.ledger_keys[rep], self.ring.ledger_vals[rep]
        )
        self.ring.ledger_meta[rep] = 1.0

    def _write_robustness(self) -> None:
        """Mirror the fleet's degraded-dispatch total into shm (host int
        reads + one f64 store, no device work) so every front end's
        /metrics renders it. The respawn base keeps the exported counter
        monotone across engine incarnations (reattach)."""
        degraded = sum(
            getattr(eng, "degraded_dispatch_total", 0)
            for eng in self.engines
        )
        with self._mon_lock:
            self.ring.rob_vals[self.replica, ROB_DEGRADED] = (
                self._degraded_base + float(degraded)
            )

    def _write_shapes(self) -> None:
        """Mirror the engine's tracewire shape histograms into this
        replica's rows of the ring's fixed table (host counter reads +
        f64 stores, no device work) so every front end's /metrics renders
        the _bucket series — the render MERGES the replica tables by
        entry key."""
        stats = getattr(self.engine, "shape_stats", None)
        if stats is None:
            return
        rep = self.replica
        stats.write_table(self.ring.shape_keys[rep], self.ring.shape_vals[rep])
        self.ring.shape_meta[rep] = stats.t0
        # Eviction mirror (ISSUE 18 satellite): max() keeps the exported
        # counter monotone across an engine respawn — fresh stats restart
        # at zero, the shm row remembers the dead incarnation's total.
        self.ring.shape_evicted[rep] = max(
            float(self.ring.shape_evicted[rep]), float(stats.evicted_total)
        )

    def _write_autotune(self) -> None:
        """Mirror the attached autotune controller's gauge snapshot into
        this replica's shm row (host-dict reads plus f64 stores — no
        device work) so every front end's /metrics renders the fold."""
        controller = self.autotune
        if controller is None:
            return
        try:
            self.ring.write_autotune(
                controller.metrics_snapshot(), self.replica
            )
        # Telemetry breadth contract: a controller mid-regrid (or a
        # snapshot bug) costs one gauge refresh, never the thread.
        except Exception:  # tpulint: disable=TPU201
            logger.exception("ring autotune write failed; gauges stale")

    def _tenant_lifecycles(self) -> list[tuple[int, Any]]:
        """(tenant index, controller) pairs: the per-tenant list when the
        fleet attached one, else the pre-tenancy single controller on
        tenant row 0."""
        if self.lifecycles is not None:
            return [
                (t, ctl)
                for t, ctl in enumerate(self.lifecycles)
                if ctl is not None
            ]
        if self.lifecycle is not None:
            return [(0, self.lifecycle)]
        return []

    def _write_lifecycle(self) -> None:
        """Mirror each attached controller's gauge snapshot into its
        tenant's shm row (host-dict reads plus f64 stores — no device
        work)."""
        for tenant, lifecycle in self._tenant_lifecycles():
            try:
                snapshot = lifecycle.metrics_snapshot()
                base = self._life_base.get(tenant)
                if base and snapshot:
                    # Respawn bases: a fresh controller's counters restart
                    # at zero — fold the dead incarnation's published
                    # totals back in so drift_trigger/promotions/
                    # breaker-trip counters never regress across an
                    # engine respawn.
                    snapshot = dict(snapshot)
                    snapshot["drift_triggers"] = (
                        snapshot.get("drift_triggers", 0)
                        + base["drift_triggers"]
                    )
                    snapshot["breaker_trips"] = (
                        snapshot.get("breaker_trips", 0)
                        + base["breaker_trips"]
                    )
                    promotions = dict(snapshot.get("promotions", {}))
                    for outcome, count in base["promotions"].items():
                        promotions[outcome] = (
                            promotions.get(outcome, 0) + count
                        )
                    snapshot["promotions"] = promotions
                self.ring.write_lifecycle(snapshot, tenant)
            # Telemetry breadth contract: a controller mid-transition (or
            # a snapshot bug) costs one gauge refresh, never the
            # telemetry thread.
            except Exception:  # tpulint: disable=TPU201
                logger.exception(
                    "ring lifecycle write failed (tenant %d); gauges "
                    "stale", tenant,
                )
