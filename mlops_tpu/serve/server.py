"""Dependency-free asyncio HTTP/1.1 server for the predict service.

The reference serves via FastAPI + uvicorn (`app/main.py:35-39,92-93`);
neither is a baked-in dependency here, so the framework carries its own thin
HTTP layer: an asyncio protocol server with keep-alive, routing, pydantic
validation (422 on bad bodies, matching FastAPI's contract), and the
reference's structured two-event JSON logging per request
(`app/main.py:57-84`). Model compute runs in a small thread pool so the
event loop keeps accepting connections while the device works.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import logging
import threading

from mlops_tpu.config import ServeConfig
from mlops_tpu.serve.batcher import MicroBatcher
from mlops_tpu.serve.engine import InferenceEngine

# The engine-free protocol layer lives in serve/httpcore.py (shared with
# the multi-worker front ends); the names re-exported here keep the
# seed-era import surface (`from mlops_tpu.serve.server import ...`)
# working.
from mlops_tpu.serve.httpcore import (  # noqa: F401  (re-exports)
    HttpProtocol,
    _DOCS_HTML,
    _LazyJson,
    _dumps,
    deadline_response,
    profile_payload,
)
from mlops_tpu.serve.metrics import ServingMetrics
from mlops_tpu.serve.tierroute import BrownoutGovernor
from mlops_tpu.serve.wire import DeadlineExceeded

logger = logging.getLogger("mlops_tpu.serve")


# tpulint Layer-3 manifest: JaxProfiler's one leaf lock serializes
# control() calls — debug-endpoint cadence only, never a request path.
TPULINT_LOCK_ORDER = {"JaxProfiler": ("_lock",)}

# tpulint Layer-5 manifest: HttpServer's mutable state is EVENT-LOOP
# CONFINED (the prose contract below, machine-checked since Layer 5) —
# every method runs on the one asyncio thread, so no method may make a
# blocking call; device/file work goes through self._executor.
TPULINT_LOOP_CONFINED = ("HttpServer",)


class JaxProfiler:
    """`jax.profiler` start/stop control for whichever process owns the
    device: the single-process server drives it from its /debug/profile
    routes; on the multi-worker plane the ENGINE process drives it from
    the ring's profile-control word (serve/ipc.py — front ends own no
    device, so they forward). Returns HTTP statuses; the payload shapes
    live in `httpcore.profile_payload` so both planes answer
    identically. ``_lock`` serializes calls: on the ring plane ops run
    on pool threads, and a front end whose ack wait timed out releases
    the channel lease while the consumed op may still be executing — a
    second client's op must queue behind it, not interleave with the
    unsynchronized ``_running`` state (serialized execution also keeps
    ack words in seq order). Holding a lock across a slow profiler call
    is the point here: it blocks only the next profile op, never a
    request."""

    def __init__(self, profile_dir: str) -> None:
        self.profile_dir = profile_dir
        self._running = False
        self._lock = threading.Lock()

    def control(self, action: str) -> tuple[int, str | None]:
        """-> (status, error-detail-or-None). Callers pre-filter unknown
        actions to their own 'not found'; the guard here keeps a bogus
        action from paying the jax import or touching profiler state."""
        if action not in ("start", "stop") or not self.profile_dir:
            return 404, None
        import jax

        with self._lock:
            return self._control_locked(jax, action)

    def _control_locked(self, jax, action: str) -> tuple[int, str | None]:
        try:
            if action == "start":
                if self._running:
                    return 409, None
                jax.profiler.start_trace(self.profile_dir)
                self._running = True
                return 200, None
            if action == "stop":
                if not self._running:
                    return 409, None
                jax.profiler.stop_trace()
                self._running = False
                return 200, None
        # Unwritable dir, profiler state errors: logged + reported as a
        # 500 body, never a dropped connection on a debug endpoint.
        except Exception as err:  # tpulint: disable=TPU201
            logger.exception("profiler %s failed", action)
            self._running = False
            return 500, str(err)
        return 404, None


class HttpServer(HttpProtocol):
    """The single-process server: HTTP protocol + a live InferenceEngine
    in one process (micro-batcher, predict thread pool, device-monitor
    telemetry). The multi-worker plane (serve/frontend.py) runs the same
    protocol in N SO_REUSEPORT processes against the shared-memory ring
    instead."""

    def __init__(
        self,
        engine: InferenceEngine,
        config: ServeConfig,
        lifecycle=None,
        registry=None,
    ):
        super().__init__(config.validate())
        self.engine = engine
        # Tenant fleet (mlops_tpu/tenancy/): ``registry`` (a
        # TenantRegistry) installs N engines behind the one HTTP plane —
        # requests route by the ``x-tenant`` header through the shared
        # shell's TenantRouter; each tenant gets its OWN micro-batcher
        # (tenants never share a grouped dispatch: one group = one
        # tenant's compiled program + params + monitor fold) over the
        # ONE shared predict thread pool. None = the 1-tenant fleet
        # around ``engine`` — the pre-tenancy server, bit-identically.
        self.registry = registry
        self.engines = list(registry.engines) if registry else [engine]
        if registry is not None:
            from mlops_tpu.tenancy import TenantRouter

            self.engine = registry.default_engine
            self.tenants = TenantRouter(
                registry.names, registry.default_index
            )
        # Optional lifecycle controllers (mlops_tpu/lifecycle/): owned
        # and started by _serve — one per tenant (a bare controller is
        # the 1-tenant form); the server's only jobs are exposing their
        # gauges on /metrics scrapes and keeping zero coupling on the
        # request path (each controller observes through its engine tee).
        self.lifecycle = lifecycle
        # The request cap can never exceed the largest warmed bucket, or
        # steady-state traffic would hit exact-shape recompiles. Clamps
        # land in LOCALS, never back into the caller's ServeConfig: a
        # config object reused to build a second server (tests, multi-
        # port deployments) must see its original values (ADVICE r5).
        # This one stays a runtime clamp (not a ServeConfig.validate
        # error) because the bound is the ENGINE's bucket grid, which the
        # config layer cannot see.
        self.max_batch = config.max_batch
        max_bucket = min(eng.max_bucket for eng in self.engines)
        if config.max_batch > max_bucket:
            logger.warning(
                "serve.max_batch=%d exceeds largest warmup bucket %d; clamping",
                config.max_batch,
                max_bucket,
            )
            self.max_batch = max_bucket
        self.metrics = ServingMetrics()
        max_workers = max(1, config.max_workers)
        # validate() guarantees dispatch bound + fetch ring (>= 1) + one
        # thread of headroom (solo fast path, monitor fetch) fit the pool.
        max_inflight = config.max_inflight
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="predict"
        )
        self._profiler = JaxProfiler(config.profile_dir)
        # sloscope (mlops_tpu/slo/), armed by _serve when slo.enabled:
        # the SLO engine ticks on its own timer task (start()) against
        # this server's ServingMetrics counters; the cost ledger renders
        # on scrapes. Both None = every hook is one is-None check.
        self.slo_engine = None
        self.cost_ledger = None
        # gridtuner (mlops_tpu/autotune/), armed by _serve when
        # autotune.enabled: the controller loops on its own thread; the
        # server's only job is rendering its gauges on scrapes.
        self.autotune = None
        self._slo_task: asyncio.Task | None = None
        # Device-resident monitor aggregate telemetry (serve/engine.py
        # monitor_snapshot): the request path only counts requests; the
        # aggregate is fetched OFF the hot path — after K requests, on the
        # T-second timer (started by start()), and on /metrics scrapes.
        # Concurrency note (tpulint Layer 3): every mutable field below
        # (_monitor_requests, _monitor_task, and the base class's drain
        # sets) is EVENT-LOOP CONFINED — touched only from coroutines on
        # the one asyncio thread, never from the predict executor — which
        # is why none of them carries a lock. Work crossing into the
        # executor goes through run_in_executor and returns via awaited
        # futures; keep it that way rather than adding locks here.
        self._accumulating = [
            bool(getattr(eng, "monitor_accumulating", False))
            for eng in self.engines
        ]
        self._monitor_accumulating = any(self._accumulating)
        self._monitor_requests = 0  # predicts since the last fetch
        self._monitor_task: asyncio.Task | None = None
        self._monitor_timer_task: asyncio.Task | None = None
        # One micro-batcher per tenant over the ONE shared executor:
        # grouping is a per-tenant affair (each grouped dispatch threads
        # one tenant's monitor accumulator through one tenant's compiled
        # program). The inflight/fetch bounds are DIVIDED across the
        # fleet: validate()'s pool-sizing invariant (dispatch bound +
        # fetch ring + one thread of headroom fit max_workers) assumes
        # the bounds describe the whole plane, so N batchers each
        # keeping the full bounds would admit N*(inflight+fetch)
        # executor tasks and queue dispatches inside the pool — exactly
        # the saturation the sizing exists to prevent. The division is
        # also the plane's fairness mechanism: each tenant's slice of
        # the pool is its own, so a hot tenant's flood queues in ITS
        # batcher while every other tenant's dispatch capacity stays
        # reserved. Floors at 1 keep tiny fleets serving (a fleet
        # larger than the pool can still oversubscribe — size
        # max_workers to the tenant count). The 1-tenant fleet keeps
        # the undivided bounds, exactly the pre-tenancy batcher.
        fetch_inflight = min(
            max_inflight, max(1, max_workers - max_inflight - 1)
        )
        n_tenants = len(self.engines)
        t_inflight = max(1, max_inflight // n_tenants)
        t_fetch = max(1, fetch_inflight // n_tenants)
        self.batchers = [
            MicroBatcher(
                eng,
                self._executor,
                window_ms=config.batch_window_ms,
                max_group=config.max_group,
                max_inflight=t_inflight,
                fetch_inflight=t_fetch,
                batch_mode=config.batch_mode,
                admit_fraction=config.batch_admit_fraction,
                # Accumulating engines fold monitor totals on device, so
                # _score's else-branch (observe_prediction, which needs the
                # dict) never runs for them — they can take the wire path:
                # responses come back as pre-encoded bytes built in the
                # executor, and the event loop skips the per-response
                # json.dumps (the encode-bound residue, ~7% of loop time
                # at c128). Non-accumulating (sklearn) engines keep dicts.
                wire_responses=self._accumulating[i],
            )
            for i, eng in enumerate(self.engines)
        ]
        self.batcher = self.batchers[
            registry.default_index if registry else 0
        ]
        # SLO tier routing + brownout (ISSUE 19, serve/tierroute.py):
        # armed only when the config asks for it AND at least one engine
        # actually committed a second tier (a single-tier fleet routing
        # by class would just rename the default path). Pressure is the
        # plane's in-flight predict depth over its dispatch capacity
        # (max_inflight overlapped groups of max_group requests) — the
        # same saturation signal that decides when work queues. All
        # fields are event-loop confined like the rest of the server.
        self.slo_routing = self.slo_routing and any(
            len(getattr(eng, "available_tiers", ())) > 1
            for eng in self.engines
        )
        self._brownout = (
            BrownoutGovernor(
                demote_depth=config.brownout_demote_depth,
                restore_depth=config.brownout_restore_depth,
            )
            if self.slo_routing
            else None
        )
        self._score_inflight = 0
        self._score_capacity = max(
            1, max_inflight * self.batcher.max_group
        )

    # ------------------------------------------------------------- routes
    def _ready(self) -> bool:
        return all(bool(eng.ready) for eng in self.engines)

    async def _metrics_endpoint(self):
        # Idle replicas scrape free: once a fetch has drained the
        # device window and no predicts arrived since, the window
        # is provably all-zero — skip the device round trip
        # (~70-90 ms on a remote-attached chip) per scrape.
        if self._monitor_accumulating and (
            self._monitor_requests > 0
            or self.metrics.monitor_fetches == 0
        ):
            # Scrapes read FRESH: at most one aggregate fetch per
            # scrape (Prometheus cadence, ~15 s) — the per-request
            # path stays fetch-free. Awaits the single-flight slot
            # (joining any fetch already in flight) so a scrape
            # racing the K-trigger/timer can never apply an older
            # snapshot after a newer one. BOUNDED + best-effort: a
            # stalled device read (tunnel hang) or a failing one
            # must never wedge or 500 the scrape — on timeout or
            # error the gauges keep their last values (the task's
            # done-callback logs the failure) and Prometheus still
            # gets a page. shield(): the timeout abandons the wait,
            # never cancels the shared fetch task. Flat 1 s,
            # INDEPENDENT of the cadence knob in both directions: a
            # raised monitor_fetch_every_s must not let a stalled
            # fetch hold scrapes toward Prometheus's 10 s
            # scrape_timeout, and a sub-second cadence must not
            # shrink the wait below what a healthy remote-chip
            # fetch needs.
            timeout = 1.0
            with contextlib.suppress(Exception):
                await asyncio.wait_for(
                    asyncio.shield(self._spawn_monitor_fetch()),
                    timeout=timeout,
                )
        for tenant_label, controller in self._tenant_lifecycles():
            # Pure host-dict read (the controller's leaf lock, no device
            # work): scrapes always render each loop's current state.
            with contextlib.suppress(Exception):
                self.metrics.set_lifecycle(
                    controller.metrics_snapshot(), tenant=tenant_label
                )
        if self.autotune is not None:
            # gridtuner gauges (host-dict read under the controller's
            # leaf lock, no device work).
            with contextlib.suppress(Exception):
                self.metrics.set_autotune(self.autotune.metrics_snapshot())
        # Robustness counters (host-side reads, no device work): degraded
        # dispatches live on the engines (`_dispatch_padded`), deadline
        # sheds accumulate in the metrics object itself.
        self.metrics.set_degraded(
            sum(
                getattr(eng, "degraded_dispatch_total", 0)
                for eng in self.engines
            )
        )
        if self.tracer is not None:
            self.metrics.set_trace_dropped(self.tracer.dropped)
        if self.flightrec is not None:
            self.metrics.set_flight_dumps(self.flightrec.landed)
        if self.loop_monitor is not None:
            # Worst callback wall time since the previous scrape (the
            # window resets on read — gauge semantics, 0.0 = quiet).
            self.metrics.set_loop_lag(self.loop_monitor.snapshot_ms())
        text = self.metrics.render()
        shape_stats = getattr(self.engine, "shape_stats", None)
        if shape_stats is not None:
            # tracewire shape histograms (trace/shapes.py): the same
            # series names the ring renderer emits from its shm mirror.
            lines = shape_stats.render_lines()
            if lines:
                text += "\n".join(lines) + "\n"
        if self.slo_engine is not None:
            # Fresh SLO/alert gauges per scrape (an extra tick is cheap
            # host arithmetic; the timer task keeps them fresh between
            # scrapes too) — same series names as the ring render's shm
            # block. engine_down is structurally False here: the engine
            # lives in THIS process.
            self.slo_engine.tick()
            text += "\n".join(self.slo_engine.render_lines()) + "\n"
        if self.cost_ledger is not None:
            lines = self.cost_ledger.render_lines()
            if lines:
                text += "\n".join(lines) + "\n"
        return 200, text, "text/plain; version=0.0.4"

    def _slo_view(self):
        # /healthz verdict source (httpcore._healthz): the in-process
        # engine's current view.
        if self.slo_engine is None:
            return None
        return self.slo_engine.view()

    async def _profile(self, action: str):
        """On-demand device tracing (SURVEY.md SS5.1: the reference has no
        profiler at all; here the serving process can capture a
        ``jax.profiler`` trace of live traffic for TensorBoard). The
        start/stop state machine and wire shapes are shared with the
        multi-worker plane (`JaxProfiler` + `profile_payload`) — the ring
        front ends forward to the engine process's twin of this."""
        if action not in ("start", "stop"):
            # Same body as the ring front end's unknown-action answer —
            # distinct from the 'profiling disabled' 404.
            return 404, {"detail": "not found"}, "application/json"
        status, err = self._profiler.control(action)
        return profile_payload(status, action, self.config.profile_dir, err)

    def _tenant_lifecycles(self):
        """(tenant label, controller) pairs: a per-tenant list when the
        fleet attached one, else the pre-tenancy single controller on the
        default tenant label."""
        lifecycle = self.lifecycle
        if lifecycle is None:
            return []
        if isinstance(lifecycle, (list, tuple)):
            return [
                (self.tenants.names[t], controller)
                for t, controller in enumerate(lifecycle)
                if controller is not None
            ]
        return [(self.tenants.names[self.tenants.default_index], lifecycle)]

    async def _score(
        self,
        record_dicts: list[dict],
        request_id: str,
        deadline: float | None = None,
        span=None,
        tenant: int = 0,
        slo: int = 0,
    ):
        """The single-process scoring hook under the shared `_predict`
        shell (serve/httpcore.py): micro-batcher -> engine, with the
        deadline and failure contracts. ``span`` (tracewire) rides into
        the batcher/engine for the queue/encode/dispatch/fetch stamps.
        ``tenant`` (resolved from ``x-tenant`` by the shell) picks the
        batcher+engine pair — tenants share the thread pool and the HTTP
        plane, never a grouped dispatch. ``slo`` (the request's SLO
        class, resolved at admission) maps to a serving tier here —
        through the brownout governor first, which demotes DEFAULT-class
        traffic to the cheaper tier while the plane's in-flight depth is
        past the demote threshold (degraded answers instead of 503s)."""
        batcher = self.batchers[tenant]
        tier: str | None = None
        if self._brownout is not None:
            eng = self.engines[tenant]
            self._brownout.observe(
                self._score_inflight / self._score_capacity
            )
            routed_cls, demoted = self._brownout.route(slo)
            tier = eng.route_tier(routed_cls)
            tier_label = tier or eng.default_tier
            self.metrics.count_tier(tier_label)
            if demoted:
                self.metrics.count_demotion(brownout=True)
            if span is not None:
                span.tier = tier_label
        self._score_inflight += 1
        try:
            # Small concurrent requests coalesce into one vmapped dispatch
            # (serve/batcher.py); everything else runs solo in the pool.
            # The deadline exists for a STALLED DEVICE (observed live: a
            # remote-attached chip's tunnel hanging dispatches 40+ min):
            # without it every in-flight request wedges until the client
            # gives up, while liveness stays green. A client deadline
            # budget (x-request-deadline-ms) tightens the server-wide
            # timeout per request AND rides into the batcher so an
            # already-expired entry is purged engine-side instead of
            # dispatched (dead-work shedding under overload).
            timeout = self.config.request_timeout_s or None
            if deadline is not None:
                remaining = deadline - asyncio.get_running_loop().time()
                timeout = min(timeout or remaining, remaining)
            # Disarmed call shape unchanged (test stubs pin it): the
            # span/tier kwargs only appear when tracing/routing armed
            # them.
            if span is None and tier is None:
                call = batcher.predict(record_dicts, deadline=deadline)
            elif tier is None:
                call = batcher.predict(
                    record_dicts, deadline=deadline, span=span
                )
            else:
                call = batcher.predict(
                    record_dicts, deadline=deadline, span=span, tier=tier
                )
            if timeout is not None:
                response = await asyncio.wait_for(call, max(timeout, 0.0))
            else:
                response = await call
        except DeadlineExceeded:
            # Engine-side shed: the batcher's claim-time purge found the
            # budget already spent and never dispatched — count the dead
            # work it avoided; the wire answer is the same documented 504.
            # (The purge completed the entry before any dispatch task saw
            # it, so nothing else holds the span — no abandon needed.)
            self.metrics.count_deadline_expired()
            return deadline_response()
        except asyncio.TimeoutError:
            logger.error(
                "prediction deadline (%.1fs) exceeded request_id=%s — "
                "device stall?",
                timeout,
                request_id,
            )
            if span is not None:
                # The engine call keeps running in its executor thread and
                # may still stamp this span: hand it over entirely (never
                # finish/record a span another thread can be writing).
                span.abandoned = True
            return deadline_response(
                f"prediction exceeded the {timeout:g}s deadline"
            )
        # Top-of-handler boundary: ANY prediction failure (device error
        # included) must become a logged 500, not a dropped connection —
        # the breadth is the contract here, and logger.exception keeps
        # the traceback.
        except Exception:  # tpulint: disable=TPU201
            logger.exception("prediction failed request_id=%s", request_id)
            if span is not None:
                span.abandoned = True  # a grouped dispatch may outlive us
            return 500, {"detail": "prediction failed"}, "application/json"
        finally:
            # Event-loop confined, like the increment: the depth fraction
            # the brownout governor samples counts only requests whose
            # scoring is actually outstanding.
            self._score_inflight -= 1
        if self._accumulating[tenant]:
            # Monitor totals are folded ON DEVICE inside the fused predict
            # (monitor/state.py MonitorAccumulator) — the hot path only
            # counts requests toward the K-trigger; no per-response host
            # fold, no per-request aggregate fetch.
            self._monitor_requests += 1
            self._maybe_fetch_monitor()
        else:
            self.metrics.observe_prediction(
                response, tenant=self.tenants.names[tenant]
            )
        return response

    # ------------------------------------------------- monitor telemetry
    def _spawn_monitor_fetch(self) -> asyncio.Task:
        """SINGLE-FLIGHT aggregate fetch: every trigger (K requests, the
        T-second timer, a /metrics scrape) funnels through one task slot.
        Two concurrent fetches could apply an OLDER cumulative snapshot
        after a newer one, making the exported counters go backwards for
        one scrape — which Prometheus reads as a counter reset."""
        task = self._monitor_task
        if task is None or task.done():
            task = asyncio.get_running_loop().create_task(
                self._fetch_monitor()
            )
            task.add_done_callback(self._observe_monitor_fetch)
            self._monitor_task = task
        return task

    @staticmethod
    def _observe_monitor_fetch(task: asyncio.Task) -> None:
        # Retrieve + log: an unobserved failure (device stall mid-read)
        # would otherwise die silently and only surface as a GC-time
        # "Task exception was never retrieved" warning while the gauges
        # froze at stale values.
        if not task.cancelled() and task.exception() is not None:
            logger.error(
                "monitor aggregate fetch failed; gauges keep their last "
                "values until the next trigger succeeds",
                exc_info=task.exception(),
            )

    def _maybe_fetch_monitor(self) -> None:
        """Kick an async aggregate fetch when K requests accumulated since
        the last one. Never blocks the request path; at most one fetch is
        in flight (a running task absorbs the trigger)."""
        k = self.config.monitor_fetch_every_requests
        if not k or self._monitor_requests < k:
            return
        self._spawn_monitor_fetch()

    async def _fetch_monitor(self) -> None:
        """One aggregate read per accumulating tenant: device -> host ->
        that tenant's metrics gauges (sequential on the one executor
        slot — the fetches stay single-flight as a set). Failures are
        isolated PER TENANT (same discipline as the ring plane's
        telemetry loop): one tenant's failing device read must not
        freeze every later tenant's gauges."""
        loop = asyncio.get_running_loop()
        self._monitor_requests = 0
        failed = None
        for t, eng in enumerate(self.engines):
            if not self._accumulating[t]:
                continue
            try:
                snapshot = await loop.run_in_executor(
                    self._executor, eng.monitor_snapshot
                )
                self.metrics.set_monitor_aggregate(
                    snapshot, tenant=self.tenants.names[t]
                )
            except Exception as err:  # tpulint: disable=TPU201
                # Gauges keep their last values; the fetch-age gauge
                # (min over tenants) surfaces the staleness.
                logger.error(
                    "monitor fetch failed for tenant %r",
                    self.tenants.names[t], exc_info=True,
                )
                failed = err
        if failed is not None and len(self.engines) == 1:
            # Pre-tenancy contract: a single-tenant fetch failure still
            # propagates to the task's done-callback log.
            raise failed

    async def _monitor_timer(self) -> None:
        """T-second cadence floor for the aggregate gauges: bounds their
        staleness even under a trickle of traffic that never reaches the
        K-request trigger (docs/operations.md documents the bound)."""
        period = self.config.monitor_fetch_every_s
        while True:
            await asyncio.sleep(period)
            if self._monitor_requests > 0:
                self._spawn_monitor_fetch()

    # ------------------------------------------------------------ lifecycle
    async def _slo_timer(self) -> None:
        """The sloscope evaluation cadence (slo.tick_s): burn rates and
        alert transitions advance even when nobody scrapes — the alert
        contract ("flips within two ticks") and the flight recorder's
        alert trigger both ride this task."""
        period = self.slo_engine.config.tick_s
        while True:
            await asyncio.sleep(period)
            try:
                self.slo_engine.tick()
            # An evaluator bug costs one tick of gauge freshness, never
            # the timer task (logged; the next tick retries).
            except Exception:  # tpulint: disable=TPU201
                logger.exception("slo tick failed; alert gauges stale")

    async def start(self) -> asyncio.AbstractServer:
        if self._monitor_accumulating and self.config.monitor_fetch_every_s > 0:
            # Strong ref: a bare create_task could be garbage-collected.
            self._monitor_timer_task = asyncio.get_running_loop().create_task(
                self._monitor_timer()
            )
        if self.slo_engine is not None:
            self._slo_task = asyncio.get_running_loop().create_task(
                self._slo_timer()
            )
        return await asyncio.start_server(
            self.handle_connection, self.config.host, self.config.port
        )

    def stop_telemetry(self) -> None:
        """Cancel the monitor timer (an infinite loop) and any in-flight
        fetch on shutdown: left pending, asyncio logs 'Task was destroyed
        but it is pending!' on every clean rollout and the leaked task
        keeps the engine alive in start/stop test harnesses."""
        for task in (
            self._monitor_timer_task, self._monitor_task, self._slo_task
        ):
            if task is not None and not task.done():
                task.cancel()


async def _serve(
    engine: InferenceEngine,
    config: ServeConfig,
    lifecycle=None,
    trace=None,
    registry=None,
    slo=None,
    autotune=None,
) -> None:
    server = HttpServer(engine, config, lifecycle=lifecycle, registry=registry)
    server.autotune = autotune
    flightrec = None
    ledger = None
    if slo is not None and (slo.enabled or slo.ledger_dir):
        # sloscope (mlops_tpu/slo/): SLO engine + flight recorder when
        # slo.enabled; the cost ledger arms independently off
        # slo.ledger_dir (autotuner input, not alerting). Disabled, every
        # hot path keeps its is-None check.
        slo.validate()
        if slo.enabled:
            from mlops_tpu.slo import FlightRecorder, SLOEngine

            tenant_names = tuple(server.tenants.names)
            if slo.flightrec_enabled:
                flightrec = FlightRecorder(
                    slo.flightrec_dir,
                    capacity=slo.flightrec_capacity,
                    cooldown_s=slo.flightrec_cooldown_s,
                    keep=slo.flightrec_keep,
                    source="single",
                    spike_errors=slo.flightrec_spike_errors,
                    spike_window_s=slo.flightrec_spike_window_s,
                )
                server.flightrec = flightrec

            def _breakers() -> dict:
                # The lifecycle circuit breaker surfaces as an alert
                # (and therefore a flight-recorder trigger): host dict
                # reads under each controller's own leaf lock.
                out = {}
                for label, controller in server._tenant_lifecycles():
                    try:
                        snapshot = controller.metrics_snapshot()
                        out[label] = bool(snapshot.get("breaker_open"))
                    except Exception:  # tpulint: disable=TPU201
                        logger.exception(
                            "breaker probe failed (tenant %r)", label
                        )
                return out

            server.slo_engine = SLOEngine(
                slo,
                tenant_names,
                source=lambda: server.metrics.slo_counts(
                    slo.latency_threshold_ms, tenant_names
                ),
                breaker_source=_breakers,
                on_alert=(
                    flightrec.note_alert if flightrec is not None else None
                ),
            )
            logger.info(
                "sloscope armed (availability %.4f, latency %.4f @ %gms)",
                slo.availability_target, slo.latency_target,
                slo.latency_threshold_ms,
            )
        if slo.ledger_dir:
            from mlops_tpu.slo import CostLedger

            ledger = CostLedger(
                slo.ledger_dir, flush_interval_s=slo.ledger_flush_s
            )
            server.cost_ledger = ledger
            for eng in server.engines:
                eng.set_cost_ledger(ledger)
            logger.info("cost ledger armed -> %s", ledger.path)
    tracer = None
    if trace is not None and trace.enabled:
        # tracewire (mlops_tpu/trace/): spans to <trace.dir>/spans.jsonl,
        # shape histograms on the engine(s) — ONE shared ShapeStats
        # across the tenant fleet, since entries key by compiled shape —
        # both gated here; a disabled trace section leaves every hot
        # path at its is-None check.
        from pathlib import Path

        from mlops_tpu.trace import ShapeStats, TraceRecorder

        trace.validate()
        tracer = TraceRecorder(
            Path(trace.dir) / "spans.jsonl",
            capacity=trace.ring_capacity,
            flush_interval_s=trace.flush_interval_s,
        )
        server.tracer = tracer
        stats = ShapeStats()
        for eng in server.engines:
            eng.set_shape_stats(stats)
        logger.info("tracewire armed; spans -> %s", tracer.path)
    srv = await server.start()
    logger.info(
        "serving %s on %s:%s", config.service_name, config.host, config.port
    )
    # Bind FIRST, warm up concurrently: probes are reachable immediately and
    # /healthz/ready flips to 200 when every bucket is compiled. (Warming
    # before binding would make K8s liveness probes connection-refuse through
    # the whole compile window and restart the pod.)
    loop = asyncio.get_running_loop()
    if config.loop_lag_monitor:
        # Runtime half of the Layer-5 discipline: time every callback on
        # this loop, drain the window max into the
        # mlops_tpu_event_loop_lag_ms gauge on each /metrics scrape.
        from mlops_tpu.analysis.loopcheck import LoopLagSanitizer

        server.loop_monitor = LoopLagSanitizer(
            slow_ms=config.loop_lag_slow_ms
        )
        server.loop_monitor.attach(loop)
        logger.info(
            "loop-lag sanitizer armed (slow_ms=%g)", config.loop_lag_slow_ms
        )
    warmup_error: list[BaseException] = []

    async def _warm() -> None:
        try:
            if registry is not None:
                # Fleet warmup with architecture-level executable dedupe
                # (tenancy/registry.py): distinct architectures compile
                # once; twins adopt the donor's exec table by reference.
                report = await loop.run_in_executor(None, registry.warmup)
                logger.info("warmup complete; ready %s", _LazyJson(report))
            else:
                await loop.run_in_executor(None, engine.warmup)
                # warmup_stats carries the AOT compile-cache evidence:
                # wall time, program count, and hit/miss/bypass counts
                # with per-program compile vs deserialize seconds
                # (engine.py).
                logger.info(
                    "warmup complete; ready %s",
                    _LazyJson(getattr(engine, "warmup_stats", {})),
                )
            for _, controller in server._tenant_lifecycles():
                # Start each loop only once the live exec tables are
                # fully warmed: candidate shadow warm-sharing snapshots
                # them, and a pre-warmup trigger would have nothing to
                # mirror into.
                controller.start()
            if lifecycle is not None:
                logger.info("lifecycle controller(s) started")
            if autotune is not None:
                # Same post-warmup gate as lifecycle: the regrid loop
                # measures the warmed grid and warms new entries into
                # the live exec table — both need it fully built first.
                autotune.start()
                logger.info("autotune controller started")
        # Compile failure/OOM: die loudly so the orchestrator restarts the
        # pod instead of a forever-503 zombie. Not swallowed — the error is
        # stored and re-raised by _serve after the server closes.
        except BaseException as err:  # tpulint: disable=TPU201
            warmup_error.append(err)
            logger.error("warmup failed, shutting down: %s", err)
            srv.close()

    # Graceful drain on SIGTERM (K8s sends it on rollout/scale-down; the
    # default would sever in-flight requests mid-response): stop
    # accepting, flip readiness to 503 so the endpoint leaves the
    # Service, close IDLE keep-alive connections immediately (they would
    # otherwise hold ``wait_closed`` open forever), let busy exchanges
    # finish their current response, then exit 0.
    import signal

    draining = asyncio.Event()

    def _drain(signum, frame=None) -> None:
        logger.info("SIGTERM: draining (no new connections)")
        server.draining = True
        for eng in server.engines:
            eng.ready = False  # /healthz/ready -> 503
        draining.set()
        srv.close()
        for w in list(server._connections - server._busy):
            w.close()  # idle readline() sees EOF; handler exits
        if flightrec is not None:
            # Evidence-gated: a drain during an incident preserves the
            # ring's tail; a clean drain writes nothing (the serve-smoke
            # zero-dump contract). Executor, like every other dump site:
            # the busy exchanges this drain is letting finish must not
            # stall behind a disk write (asyncio.run's shutdown joins the
            # executor, so the dump always completes before exit).
            loop.run_in_executor(
                None, flightrec.dump_if_evidence, "sigterm"
            )

    try:
        loop.add_signal_handler(signal.SIGTERM, _drain, signal.SIGTERM)
    except (NotImplementedError, RuntimeError):
        pass  # non-unix event loops: no graceful path, default semantics

    warm_task = asyncio.create_task(_warm())
    try:
        # NOT ``async with srv``: its __aexit__ awaits wait_closed(),
        # which on 3.12+ blocks until every connection drops — an idle
        # keep-alive client would stall shutdown past the kubelet's
        # SIGKILL. The drain path closes connections itself.
        await srv.serve_forever()
    except asyncio.CancelledError:
        pass
    except BaseException:
        if flightrec is not None:
            # Fatal server-loop failure: preserve the ring's last N
            # seconds unconditionally — this dump IS the post-mortem.
            flightrec.dump("fatal")
        raise
    finally:
        srv.close()
        if server.loop_monitor is not None:
            server.loop_monitor.detach()
            server.loop_monitor = None
        server.stop_telemetry()
        for _, controller in server._tenant_lifecycles():
            # Controller drain (joins its worker thread, detaches the
            # engine tee, snapshots the reservoir) happens in the
            # executor: stop() joins a thread, which must not block the
            # event loop mid-drain.
            await loop.run_in_executor(None, controller.stop)
        if autotune is not None:
            # Joins the gridtuner thread (a mid-warm tick finishes its
            # current compile-cache write, then exits) — executor, same
            # reason as the lifecycle drains above.
            await loop.run_in_executor(None, autotune.stop)
        await warm_task
        if draining.is_set():
            # Warmup may have finished AFTER the drain flip and
            # re-advertised readiness; a draining pod is never ready.
            for eng in server.engines:
                eng.ready = False
            # Busy exchanges get a bounded window to write their
            # responses (serve.drain_deadline_s; the kubelet's
            # terminationGracePeriodSeconds is the hard stop); whatever
            # remains is then force-closed.
            deadline = loop.time() + config.drain_deadline_s
            while server._busy and loop.time() < deadline:
                await asyncio.sleep(0.05)
            for w in list(server._connections):
                w.close()
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(srv.wait_closed(), timeout=5)
            logger.info("drained; exiting")
        if tracer is not None:
            # AFTER the busy-drain window: every exchange that finished
            # its response has recorded its span. close() joins the
            # writer thread — run it in the executor so the final flush
            # never blocks the event loop.
            await loop.run_in_executor(None, tracer.close)
        if ledger is not None:
            # Final atomic flush of the cost ledger (close joins its
            # writer thread — executor, same reason as the tracer).
            await loop.run_in_executor(None, ledger.close)
    if warmup_error:
        raise SystemExit(f"warmup failed: {warmup_error[0]}")


def serve_forever(
    engine: InferenceEngine,
    config: ServeConfig,
    lifecycle=None,
    trace=None,
    registry=None,
    slo=None,
    autotune=None,
) -> None:
    """Blocking entry point (the uvicorn.run analogue, `app/main.py:92-93`).
    ``lifecycle`` is an optional `LifecycleController` (or a per-tenant
    list of them): started once warmup completes, drained on shutdown,
    gauges on /metrics. ``trace`` is the optional `TraceConfig` section:
    enabled, every /predict request records a stage span to
    <trace.dir>/spans.jsonl and the engine exports shape histograms
    (mlops_tpu/trace/). ``registry`` (a `TenantRegistry`) serves N
    tenants from this one plane; None = the 1-tenant fleet around
    ``engine``. ``autotune`` is an optional `AutotuneController`
    (mlops_tpu/autotune/): started once warmup completes (it warms new
    grid entries into the live exec table), drained on shutdown, gauges
    on /metrics."""
    asyncio.run(
        _serve(
            engine, config, lifecycle=lifecycle, trace=trace,
            registry=registry, slo=slo, autotune=autotune,
        )
    )
