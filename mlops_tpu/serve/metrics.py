"""In-process serving metrics with a Prometheus text exposition endpoint.

The reference ships logs only — drift monitoring is "grep the Log Analytics
table" (SURVEY.md SS5.5). Here the service additionally exposes ``/metrics``:
request counts by route/status, latency percentiles, rows scored, outlier
counts, and the last per-feature drift scores.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

# The closed serving-tier set (ISSUE 19): tier label values on the
# routing series below come from this tuple only, never request text.
from mlops_tpu.serve.tierroute import TIERS  # jax-free

# ---- multi-worker exposition constants (shared with serve/ipc.py) ----
# Closed status set for the per-worker shared-memory request matrices
# (the protocol layer's reason set); anything else lands in the
# catch-all column rendered as status="other". 504 is the deadline
# contract (distinct from shed's 503+Retry-After — docs/operations.md
# "Failure domains & degraded modes").
RING_STATUSES = (200, 400, 404, 409, 413, 422, 500, 503, 504)
RING_CLASSES = ("small", "large")  # slot classes (ring depth/shed labels)
# Field indices of the ring's monitor-aggregate block (engine-process
# single writer; see RequestRing.write_monitor).
MON_ROWS, MON_OUTLIERS, MON_BATCHES, MON_FETCHES, MON_FETCHED_AT, MON_HAS = (
    range(6)
)
# Field indices of the ring's lifecycle block (engine-process single
# writer; see RequestRing.write_lifecycle). AUC delta rides two fields
# (value + has-flag) because 0.0 is a legitimate delta, not "unknown".
(
    LIFE_GENERATION,
    LIFE_TRIGGERS,
    LIFE_AUC_DELTA,
    LIFE_HAS_DELTA,
    LIFE_RESERVOIR,
    LIFE_HAS,
    LIFE_BREAKER_OPEN,
    LIFE_BREAKER_TRIPS,
) = range(8)
# Field indices of the ring's robustness block (engine-process writers
# under RingService._mon_lock; see RequestRing rob_vals): engine-side
# deadline expiries (descriptors completed RESP_EXPIRED without a
# dispatch) and degraded-shape dispatches.
ROB_EXPIRED_ENGINE, ROB_DEGRADED = range(2)
# Field indices of the ring's engine-supervision block (ISSUE 11,
# RequestRing eng_vals). One writer per cell: INCARNATION / REPLAYED /
# ROWS_LOST / ROWS_DISPATCHED belong to the (single, serialized) engine
# process; DOWN_SINCE and RESPAWNS to the supervisor (DOWN_SINCE is also
# cleared by the engine at ready — the two writers never race because the
# supervisor only stamps it after the engine died).
(
    ENG_INCARNATION,
    ENG_DOWN_SINCE,
    ENG_RESPAWNS,
    ENG_REPLAYED,
    ENG_ROWS_LOST,
    ENG_ROWS_DISPATCHED,
) = range(6)
# Promotion outcomes, in their ring-array order (write_lifecycle /
# render_ring_metrics and the single-process render share this tuple so
# the label sets can never diverge between telemetry planes).
LIFE_OUTCOMES = ("promoted", "rejected", "rolled_back")
# gridtuner plan outcomes (mlops_tpu/autotune/), in their ring-array
# order (write_autotune / render_ring_metrics and the single-process
# render share this tuple — same discipline as LIFE_OUTCOMES):
# applied = hot regrid landed; planned = dry-run winner persisted but
# not applied (autotune.apply=false); rejected = searched but below
# min_gain_pct (or already optimal); rolled_back = operator bail-out;
# failed = tick error / promotion raced the warm phase.
AUTOTUNE_OUTCOMES = (
    "applied", "planned", "rejected", "rolled_back", "failed"
)
# Field indices of the ring's per-replica autotune gauge block
# (engine-process telemetry-loop writer; see RequestRing.write_autotune).
# Gains ride value + has-flag pairs because 0.0 is a legitimate gain,
# not "no audit yet" (the LIFE_AUC_DELTA convention).
(
    AUTO_GRID_GEN,
    AUTO_PRED_GAIN,
    AUTO_HAS_PRED,
    AUTO_MEAS_GAIN,
    AUTO_HAS_MEAS,
    AUTO_HAS,
) = range(6)


DEFAULT_TENANT_LABEL = "default"

# sloscope (mlops_tpu/slo/): the statuses that spend availability error
# budget — every server-side failure in the closed ring set. 500 is the
# failure contract, 503 the shed (a shed request is not goodput — the
# fleet-goodput framing), 504 the deadline expiry.
SLO_BAD_STATUSES = (500, 503, 504)

# ---------------------------------------------------------------------------
# Layer-4 series-contract manifests (tpulint TPU502, `analysis/seriesreg.py`).
#
# The two scrape roots below must emit the same series surface — a panel
# wired against one plane has to survive a redeploy onto the other. The
# analyzer rebuilds the registry from the renderers' f-strings on every CI
# run; these declarations only name the roots, the deliberate exceptions,
# and the label keys whose values come from closed sets.
TPULINT_SERIES_PLANES = {
    "single": ("HttpServer._metrics_endpoint",),
    "ring": ("FrontendServer._metrics_endpoint",),
}
# Series that exist on exactly one plane ON PURPOSE. The ring plane's
# extras are its fleet anatomy (per-worker ring depth/quota, per-replica
# liveness) — physical structure the single-process plane doesn't have.
TPULINT_PLANE_ONLY_SERIES = {
    "ring": (
        "mlops_tpu_ring_depth",
        "mlops_tpu_shed_total",
        "mlops_tpu_tenant_quota_shed_total",
        "mlops_tpu_replica_ready",
        "mlops_tpu_replica_ring_depth",
        "mlops_tpu_replica_incarnation",
        "mlops_tpu_replica_respawn_total",
        "mlops_tpu_replica_replayed_slots_total",
        "mlops_tpu_replica_rows_scored_total",
    ),
}
# Label keys whose runtime values are closed sets (route/status tables,
# schema feature names, tenant registry, bucket bounds...). A formatted
# label value under any OTHER key is unbounded cardinality and gates.
TPULINT_BOUNDED_LABELS = (
    "alert",
    "backend",
    "class",
    "entry",
    "feature",
    "jax",
    "jaxlib",
    "le",
    "model",
    "outcome",
    "replica",
    "route",
    "severity",
    "slo",
    "status",
    "tenant",
    "tier",
    "version",
    "window",
    "worker",
)

_BUILD_INFO_LINES: list[str] | None = None


def build_info_lines() -> list[str]:
    """``mlops_tpu_build_info{version,jax,jaxlib,backend}`` — the
    standard fleet-inventory gauge (value 1, identity in the labels),
    emitted by BOTH planes' renders.

    Computed once, WITHOUT importing jax: the ring front ends are
    jax-free by construction, so the jax/jaxlib versions come from
    installed-package metadata and ``backend`` is the CONFIGURED
    platform (the first JAX_PLATFORMS entry, or "default" for
    jax's own resolution) — identical label sets across planes by
    construction, which is what makes the series joinable fleet-wide."""
    global _BUILD_INFO_LINES
    if _BUILD_INFO_LINES is None:
        import importlib.metadata
        import os

        from mlops_tpu.version import __version__

        def _pkg(name: str) -> str:
            try:
                return importlib.metadata.version(name)
            except importlib.metadata.PackageNotFoundError:
                return "absent"

        backend = (
            os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
            or "default"
        )
        _BUILD_INFO_LINES = [
            "# TYPE mlops_tpu_build_info gauge",
            f'mlops_tpu_build_info{{backend="{backend}",'
            f'jax="{_pkg("jax")}",jaxlib="{_pkg("jaxlib")}",'
            f'version="{__version__}"}} 1',
        ]
    return list(_BUILD_INFO_LINES)


def latency_good_buckets(threshold_ms: float) -> int:
    """How many histogram buckets count as "good" for the latency SLO:
    the smallest edge >= the configured threshold is the EFFECTIVE
    threshold (the histogram is the only latency source both planes
    share)."""
    buckets = ServingMetrics.LATENCY_BUCKETS
    for i, edge in enumerate(buckets):
        if edge >= threshold_ms:
            return i + 1
    return len(buckets)


def _zero_monitor_block() -> dict:
    return {
        "rows": 0,
        "outliers": 0,
        "batches": 0,
        "last_drift": {},
        "mean_drift": {},
    }


class ServingMetrics:
    # Fixed latency histogram buckets (ms).
    LATENCY_BUCKETS = (0.5, 1, 2, 5, 10, 25, 50, 100, 250, 1000, float("inf"))

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Every per-traffic series carries a ``tenant`` dimension
        # (mlops_tpu/tenancy/): untagged pre-tenancy traffic lands on the
        # "default" label, so single-tenant dashboards keep parsing —
        # they just gain one constant label. Tenant label values are
        # BOUNDED upstream (TenantRouter.bill_label: declared names
        # only — strangers' 404s bill the default tenant's row, same as
        # the ring plane's fixed shm rows), never raw header text.
        self.requests: dict[tuple[str, int, str], int] = defaultdict(int)
        self.latency_counts: dict[str, list[int]] = {
            DEFAULT_TENANT_LABEL: [0] * len(self.LATENCY_BUCKETS)
        }
        self.latency_sum_ms: dict[str, float] = defaultdict(float)
        self.latency_n: dict[str, int] = defaultdict(int)
        # /predict-scoped latency histogram for the sloscope latency SLO
        # (same buckets): the all-routes histogram above stays the
        # exported series, but an SLO computed over it would let fast
        # probe/scrape traffic DILUTE /predict violations — a plane
        # whose every user-facing request breaks the threshold could
        # still read healthy. Two extra increments per predict.
        self.predict_latency_counts: dict[str, list[int]] = {}
        self.predict_latency_n: dict[str, int] = defaultdict(int)
        # tenant label -> monitor aggregate block (rows/outliers/batches/
        # drift gauges). The default tenant's block always exists so the
        # zero baseline stays exported (chaos-smoke monotonicity).
        self.monitor: dict[str, dict] = {
            DEFAULT_TENANT_LABEL: _zero_monitor_block()
        }
        self.monitor_fetches = 0
        # time.monotonic() of each tenant's last applied snapshot: the
        # age gauge reads the OLDEST (one stuck tenant must not be
        # masked by another's fresh fetch).
        self.monitor_fetched_at: dict[str, float] = {}
        # Robustness counters (ISSUE 9): dead-work sheds (requests
        # answered 504 WITHOUT their work running — the admission check
        # and the batcher's claim-time purge) and degraded-shape
        # dispatches (mirrored from the engine's counter per scrape).
        self.deadline_expired = 0
        self.degraded_dispatches = 0
        # tracewire (mlops_tpu/trace/): spans the bounded recorder DROPPED
        # rather than block the hot path — mirrored from the recorder per
        # scrape; stays 0 (and still exported) with tracing disarmed so
        # the chaos smoke's monotonicity check covers it.
        self.trace_dropped = 0
        # sloscope flight-recorder dumps (mlops_tpu/slo/flightrec.py) —
        # mirrored from the recorder per scrape; same zero-baseline
        # contract as trace_dropped.
        self.flight_dumps = 0
        # loopcheck event-loop lag (analysis/loopcheck.py) — the
        # sanitizer's window max mirrored per scrape; stays 0.0 (and
        # still exported) with serve.loop_lag_monitor off.
        self.loop_lag_ms = 0.0
        # Lifecycle gauges (mlops_tpu/lifecycle/), per tenant: empty until
        # a controller installs a snapshot — the series are only exported
        # when a loop is actually running, so a loop-less deployment's
        # scrape is byte-identical to pre-lifecycle builds.
        self.lifecycle: dict[str, dict] = {}
        # gridtuner gauges (mlops_tpu/autotune/): one block per PLANE,
        # not per tenant — twin tenants share one exec table, so the
        # grid (and its generation) is plane-level state. None until a
        # controller installs a snapshot (same export-only-when-running
        # contract as the lifecycle block).
        self.autotune: dict | None = None
        # SLO tier routing (ISSUE 19, serve/tierroute.py): requests per
        # routed tier, class demotions (any cause), and the brownout
        # subset. Zero baselines always export — a single-tier plane
        # renders the full closed tier set at 0.
        self.tier_requests: dict[str, int] = defaultdict(int)
        self.tier_demotions = 0
        self.brownout_demotions = 0

    # Known routes only: arbitrary request paths must not become unbounded
    # (and injectable) Prometheus label values.
    KNOWN_ROUTES = (
        "/predict",
        "/",
        "/healthz",
        "/healthz/live",
        "/healthz/ready",
        "/metrics",
    )

    def observe_request(
        self,
        route: str,
        status: int,
        latency_ms: float,
        tenant: str = DEFAULT_TENANT_LABEL,
    ) -> None:
        if route not in self.KNOWN_ROUTES:
            route = "<other>"
        with self._lock:
            self.requests[(route, status, tenant)] += 1
            self.latency_sum_ms[tenant] += latency_ms
            self.latency_n[tenant] += 1
            counts = self.latency_counts.get(tenant)
            if counts is None:
                counts = self.latency_counts[tenant] = (
                    [0] * len(self.LATENCY_BUCKETS)
                )
            for i, edge in enumerate(self.LATENCY_BUCKETS):
                if latency_ms <= edge:
                    counts[i] += 1
                    break
            if route == "/predict":
                pcounts = self.predict_latency_counts.get(tenant)
                if pcounts is None:
                    pcounts = self.predict_latency_counts[tenant] = (
                        [0] * len(self.LATENCY_BUCKETS)
                    )
                self.predict_latency_n[tenant] += 1
                for i, edge in enumerate(self.LATENCY_BUCKETS):
                    if latency_ms <= edge:
                        pcounts[i] += 1
                        break

    def _monitor_block(self, tenant: str) -> dict:
        block = self.monitor.get(tenant)
        if block is None:
            block = self.monitor[tenant] = _zero_monitor_block()
        return block

    def observe_prediction(
        self, response: dict, tenant: str = DEFAULT_TENANT_LABEL
    ) -> None:
        """Host-side per-response fold — the seed path, kept for engines
        without a device monitor accumulator (sklearn flavor, stubs)."""
        with self._lock:
            block = self._monitor_block(tenant)
            block["rows"] += len(response["predictions"])
            block["outliers"] += int(sum(response["outliers"]))
            block["last_drift"] = dict(response["feature_drift_batch"])

    def set_monitor_aggregate(
        self, snapshot: dict, tenant: str = DEFAULT_TENANT_LABEL
    ) -> None:
        """Install a device-accumulator snapshot
        (`serve/engine.py monitor_snapshot`): the device totals are
        absolute counters, so this REPLACES the tenant's monitor gauges
        rather than adding — per-request host folding never runs on this
        path."""
        if not snapshot:
            return
        with self._lock:
            block = self._monitor_block(tenant)
            block["rows"] = int(snapshot["rows"])
            block["outliers"] = int(snapshot["outliers"])
            block["batches"] = int(snapshot["batches"])
            block["last_drift"] = dict(snapshot["drift_last"])
            block["mean_drift"] = dict(snapshot["drift_mean"])
            self.monitor_fetches += 1
            self.monitor_fetched_at[tenant] = time.monotonic()

    def set_lifecycle(
        self, snapshot: dict, tenant: str = DEFAULT_TENANT_LABEL
    ) -> None:
        """Install a lifecycle-controller snapshot
        (`lifecycle/controller.py metrics_snapshot`) for the next render."""
        if not snapshot:
            return
        with self._lock:
            self.lifecycle[tenant] = dict(snapshot)

    def set_autotune(self, snapshot: dict | None) -> None:
        """Install an autotune-controller snapshot
        (`autotune/apply.py metrics_snapshot`) for the next render."""
        if not snapshot:
            return
        with self._lock:
            self.autotune = dict(snapshot)

    def slo_counts(
        self, latency_threshold_ms: float, tenants: tuple[str, ...]
    ) -> dict[str, tuple[int, int, int, int]]:
        """The sloscope counter source (`slo/engine.SLOEngine`): per
        tenant, cumulative ``(avail_good, avail_total, lat_good,
        lat_total)``. BOTH dimensions are ``/predict``-scoped — the
        serving SLO; probe/scrape traffic must never dilute it. A
        status in ``SLO_BAD_STATUSES`` spends availability budget;
        latency counts the predict-scoped histogram against the
        effective threshold bucket."""
        k = latency_good_buckets(latency_threshold_ms)
        out: dict[str, tuple[int, int, int, int]] = {}
        with self._lock:
            for tenant in tenants:
                total = bad = 0
                for (route, status, t), count in self.requests.items():
                    if route != "/predict" or t != tenant:
                        continue
                    total += count
                    if status in SLO_BAD_STATUSES or status >= 500:
                        bad += count
                counts = self.predict_latency_counts.get(tenant)
                lat_good = sum(counts[:k]) if counts else 0
                lat_total = self.predict_latency_n.get(tenant, 0)
                out[tenant] = (total - bad, total, lat_good, lat_total)
        return out

    def count_deadline_expired(self) -> None:
        """One dead-work shed: a request answered the documented 504
        WITHOUT its work dispatching (admission check, batcher purge)."""
        with self._lock:
            self.deadline_expired += 1

    def set_degraded(self, total: int) -> None:
        """Mirror the engine's degraded-dispatch counter (an absolute
        total — `InferenceEngine.degraded_dispatch_total`)."""
        with self._lock:
            self.degraded_dispatches = int(total)

    def set_trace_dropped(self, total: int) -> None:
        """Mirror the trace recorder's drop counter (an absolute total —
        `trace/recorder.TraceRecorder.dropped`)."""
        with self._lock:
            self.trace_dropped = int(total)

    def set_flight_dumps(self, total: int) -> None:
        """Mirror the flight recorder's landed-dump counter (an absolute
        total — `slo/flightrec.FlightRecorder`)."""
        with self._lock:
            self.flight_dumps = int(total)

    def set_loop_lag(self, lag_ms: float) -> None:
        """Mirror the loop sanitizer's window max
        (`analysis/loopcheck.LoopLagSanitizer.snapshot_ms`) — 0.0 when
        the monitor is off or the window since the last scrape was
        quiet."""
        with self._lock:
            self.loop_lag_ms = float(lag_ms)

    def count_tier(self, tier: str) -> None:
        """One request routed to ``tier`` (a member of the closed TIERS
        set — callers resolve through the engine, never request text)."""
        with self._lock:
            self.tier_requests[tier] += 1

    def count_demotion(self, brownout: bool = False) -> None:
        """One SLO-class demotion (a request served a cheaper tier than
        its class asked for); ``brownout`` marks the overload-governor
        subset."""
        with self._lock:
            self.tier_demotions += 1
            if brownout:
                self.brownout_demotions += 1

    @staticmethod
    def tier_lines(
        tier_requests: dict | None,
        demotions: int = 0,
        brownout_demotions: int = 0,
    ) -> list[str]:
        """The SLO tier-routing block (ISSUE 19) — ONE definition shared
        by the single-process render and the ring render so both
        telemetry planes export identical series names. Always emitted
        with the FULL closed tier set at a zero baseline: "no series"
        must never be indistinguishable from "routing off", and the
        chaos smoke's monotonicity check needs the baseline."""
        counts = tier_requests or {}
        lines = ["# TYPE mlops_tpu_tier_requests_total counter"]
        for tier in TIERS:
            lines.append(
                f'mlops_tpu_tier_requests_total{{tier="{tier}"}} '
                f"{int(counts.get(tier, 0))}"
            )
        lines.append("# TYPE mlops_tpu_tier_demotions_total counter")
        lines.append(f"mlops_tpu_tier_demotions_total {int(demotions)}")
        # The brownout subset: demotions taken INSTEAD of 503 sheds while
        # the overload governor is active — the goodput-over-refusal
        # observable (docs/operations.md "Brownout runbook").
        lines.append("# TYPE mlops_tpu_brownout_demote_total counter")
        lines.append(
            f"mlops_tpu_brownout_demote_total {int(brownout_demotions)}"
        )
        return lines

    @staticmethod
    def robustness_lines(
        deadline_expired: int,
        degraded: int,
        trace_dropped: int = 0,
        flight_dumps: int = 0,
    ) -> list[str]:
        """The robustness counter block — ONE definition shared by the
        single-process render and the ring render, so both telemetry
        planes export identical series names. Always emitted (a zero
        baseline is what makes chaos-smoke monotonicity checkable)."""
        return [
            "# TYPE mlops_tpu_deadline_expired_total counter",
            f"mlops_tpu_deadline_expired_total {int(deadline_expired)}",
            "# TYPE mlops_tpu_degraded_dispatch_total counter",
            f"mlops_tpu_degraded_dispatch_total {int(degraded)}",
            "# TYPE mlops_tpu_trace_dropped_total counter",
            f"mlops_tpu_trace_dropped_total {int(trace_dropped)}",
            # Flight-recorder dumps landed (sloscope): nonzero means an
            # anomaly tripped evidence capture — go read runs/.
            "# TYPE mlops_tpu_flightrec_dumps_total counter",
            f"mlops_tpu_flightrec_dumps_total {int(flight_dumps)}",
        ]

    @staticmethod
    def loop_lag_lines(lag_by_worker: list[tuple[str, float]]) -> list[str]:
        """The event-loop lag block (loopcheck, Layer 5's runtime twin) —
        ONE definition shared by the single-process render and the ring
        render so both telemetry planes export identical series names.
        Always emitted at a 0.0 baseline: an absent series must never be
        indistinguishable from "monitor off", and 0.0 is a true reading
        (no callback held the loop since the last scrape)."""
        lines = ["# TYPE mlops_tpu_event_loop_lag_ms gauge"]
        for worker, lag_ms in lag_by_worker:
            lines.append(
                f'mlops_tpu_event_loop_lag_ms{{worker="{worker}"}} '
                f"{float(lag_ms):.3f}"
            )
        return lines

    @staticmethod
    def survivability_lines(
        respawns: int,
        replayed: int,
        rows_lost: float,
        parked: int,
        brownout: int,
        incarnation: int = 0,
    ) -> list[str]:
        """The engine-survivability block (ISSUE 11) — ONE definition
        shared by the single-process render and the ring render so both
        telemetry planes export identical series names. Always emitted
        (zero baseline keeps the chaos smoke's monotonicity contract
        checkable); on the single-process plane — where there is no
        separate engine process to kill — every value is structurally 0."""
        return [
            "# TYPE mlops_tpu_engine_respawn_total counter",
            f"mlops_tpu_engine_respawn_total {int(respawns)}",
            "# TYPE mlops_tpu_replayed_slots_total counter",
            f"mlops_tpu_replayed_slots_total {int(replayed)}",
            "# TYPE mlops_tpu_monitor_rows_lost_total counter",
            f"mlops_tpu_monitor_rows_lost_total {int(rows_lost)}",
            "# TYPE mlops_tpu_parked_requests gauge",
            f"mlops_tpu_parked_requests {int(parked)}",
            "# TYPE mlops_tpu_brownout_shed_total counter",
            f"mlops_tpu_brownout_shed_total {int(brownout)}",
            # 0 on the single-process plane (there is no supervised
            # engine child to count incarnations of) — exported anyway
            # so the series SET is identical across planes.
            "# TYPE mlops_tpu_engine_incarnation gauge",
            f"mlops_tpu_engine_incarnation {int(incarnation)}",
        ]

    @staticmethod
    def lifecycle_lines(
        snapshot: dict | None, tenant: str = DEFAULT_TENANT_LABEL
    ) -> list[str]:
        """The lifecycle gauge block for ONE tenant's controller — ONE
        definition shared by the single-process render and the ring
        render's label set, so the two telemetry planes export identical
        series names. Every series carries the ``tenant`` label: the
        lifecycle loop runs PER TENANT (tenant A drifting retrains and
        promotes A alone), so generation/trigger/promotion gauges are
        only meaningful per tenant."""
        if not snapshot:
            return []
        t = f'tenant="{tenant}"'
        lines = [
            "# TYPE mlops_tpu_bundle_generation gauge",
            f"mlops_tpu_bundle_generation{{{t}}} "
            f"{int(snapshot['generation'])}",
            "# TYPE mlops_tpu_drift_trigger_total counter",
            f"mlops_tpu_drift_trigger_total{{{t}}} "
            f"{int(snapshot['drift_triggers'])}",
        ]
        delta = snapshot.get("shadow_auc_delta")
        if delta is not None:
            lines.append("# TYPE mlops_tpu_shadow_auc_delta gauge")
            lines.append(
                f"mlops_tpu_shadow_auc_delta{{{t}}} {float(delta):.6f}"
            )
        lines.append("# TYPE mlops_tpu_promotions_total counter")
        promotions = snapshot.get("promotions", {})
        for outcome in LIFE_OUTCOMES:
            lines.append(
                f'mlops_tpu_promotions_total{{{t},outcome="{outcome}"}} '
                f"{int(promotions.get(outcome, 0))}"
            )
        rows = snapshot.get("reservoir_rows")
        if rows is not None:
            lines.append("# TYPE mlops_tpu_lifecycle_reservoir_rows gauge")
            lines.append(
                f"mlops_tpu_lifecycle_reservoir_rows{{{t}}} {int(rows)}"
            )
        if "breaker_open" in snapshot:
            # Circuit breaker (lifecycle/controller.py): open = repeated
            # retrain/shadow failures tripped the loop into a cooldown
            # instead of hot-looping; trips count the openings.
            lines.append("# TYPE mlops_tpu_lifecycle_breaker_open gauge")
            lines.append(
                f"mlops_tpu_lifecycle_breaker_open{{{t}}} "
                f"{1 if snapshot['breaker_open'] else 0}"
            )
            lines.append(
                "# TYPE mlops_tpu_lifecycle_breaker_trips_total counter"
            )
            lines.append(
                f"mlops_tpu_lifecycle_breaker_trips_total{{{t}}} "
                f"{int(snapshot.get('breaker_trips', 0))}"
            )
        return lines

    @staticmethod
    def autotune_lines(snapshot: dict | None) -> list[str]:
        """The gridtuner gauge block — ONE definition shared by the
        single-process render and the ring render so both telemetry
        planes export identical series names. Plane-level (no tenant
        label): the grid is the exec table's geometry, shared by every
        tenant adopted onto it. Empty until a controller runs."""
        if not snapshot:
            return []
        lines = [
            "# TYPE mlops_tpu_grid_generation gauge",
            f"mlops_tpu_grid_generation "
            f"{int(snapshot['grid_generation'])}",
            "# TYPE mlops_tpu_autotune_plans_total counter",
        ]
        plans = snapshot.get("plans", {})
        for outcome in AUTOTUNE_OUTCOMES:
            lines.append(
                f'mlops_tpu_autotune_plans_total{{outcome="{outcome}"}} '
                f"{int(plans.get(outcome, 0))}"
            )
        predicted = snapshot.get("predicted_gain_pct")
        if predicted is not None:
            # The audit pair: what the cost model promised for the last
            # searched plan vs what the post-apply ledger window
            # measured — the divergence IS the model's error bar.
            lines.append(
                "# TYPE mlops_tpu_autotune_predicted_gain_pct gauge"
            )
            lines.append(
                f"mlops_tpu_autotune_predicted_gain_pct "
                f"{float(predicted):.3f}"
            )
        measured = snapshot.get("measured_gain_pct")
        if measured is not None:
            lines.append(
                "# TYPE mlops_tpu_autotune_measured_gain_pct gauge"
            )
            lines.append(
                f"mlops_tpu_autotune_measured_gain_pct "
                f"{float(measured):.3f}"
            )
        return lines

    def render(self) -> str:
        """Prometheus text format. Per-traffic series carry the
        ``tenant`` label (constant "default" on a single-tenant plane,
        so pre-tenancy dashboards parse unchanged)."""
        with self._lock:
            lines = build_info_lines()
            lines.append("# TYPE mlops_tpu_requests_total counter")
            for (route, status, tenant), count in sorted(
                self.requests.items(), key=lambda kv: (kv[0][2],) + kv[0][:2]
            ):
                lines.append(
                    f'mlops_tpu_requests_total{{route="{route}",'
                    f'status="{status}",tenant="{tenant}"}} {count}'
                )
            lines.append("# TYPE mlops_tpu_request_latency_ms histogram")
            for tenant in sorted(self.latency_counts):
                cumulative = 0
                for edge, count in zip(
                    self.LATENCY_BUCKETS, self.latency_counts[tenant]
                ):
                    cumulative += count
                    label = "+Inf" if edge == float("inf") else str(edge)
                    lines.append(
                        f'mlops_tpu_request_latency_ms_bucket{{le="{label}",'
                        f'tenant="{tenant}"}} {cumulative}'
                    )
                lines.append(
                    f'mlops_tpu_request_latency_ms_sum{{tenant="{tenant}"}} '
                    f"{self.latency_sum_ms[tenant]}"
                )
                lines.append(
                    f'mlops_tpu_request_latency_ms_count{{tenant="{tenant}"}} '
                    f"{self.latency_n[tenant]}"
                )
            lines.append("# TYPE mlops_tpu_rows_scored_total counter")
            for tenant in sorted(self.monitor):
                lines.append(
                    f'mlops_tpu_rows_scored_total{{tenant="{tenant}"}} '
                    f"{self.monitor[tenant]['rows']}"
                )
            lines.append("# TYPE mlops_tpu_outliers_total counter")
            for tenant in sorted(self.monitor):
                lines.append(
                    f'mlops_tpu_outliers_total{{tenant="{tenant}"}} '
                    f"{self.monitor[tenant]['outliers']}"
                )
            if any(m["last_drift"] for m in self.monitor.values()):
                lines.append("# TYPE mlops_tpu_feature_drift_score gauge")
                for tenant in sorted(self.monitor):
                    for feature, score in self.monitor[tenant][
                        "last_drift"
                    ].items():
                        lines.append(
                            "mlops_tpu_feature_drift_score"
                            f'{{feature="{feature}",tenant="{tenant}"}} '
                            f"{score}"
                        )
            if any(m["mean_drift"] for m in self.monitor.values()):
                lines.append("# TYPE mlops_tpu_feature_drift_mean gauge")
                for tenant in sorted(self.monitor):
                    for feature, score in self.monitor[tenant][
                        "mean_drift"
                    ].items():
                        lines.append(
                            "mlops_tpu_feature_drift_mean"
                            f'{{feature="{feature}",tenant="{tenant}"}} '
                            f"{score}"
                        )
            if self.monitor_fetches:
                lines.append("# TYPE mlops_tpu_monitor_fetches_total counter")
                lines.append(
                    f"mlops_tpu_monitor_fetches_total {self.monitor_fetches}"
                )
                lines.append("# TYPE mlops_tpu_monitor_batches_total counter")
                for tenant in sorted(self.monitor):
                    lines.append(
                        f'mlops_tpu_monitor_batches_total{{tenant="{tenant}"}} '
                        f"{self.monitor[tenant]['batches']}"
                    )
                # The staleness bound docs/operations.md advertises, made
                # observable: seconds since the OLDEST tenant's gauges
                # were refreshed from the device (min over tenants —
                # same alarm semantics as the ring render: one stuck
                # tenant must not hide behind another's fresh fetch).
                age = time.monotonic() - min(
                    self.monitor_fetched_at.values()
                )
                lines.append("# TYPE mlops_tpu_monitor_fetch_age_seconds gauge")
                lines.append(
                    f"mlops_tpu_monitor_fetch_age_seconds {age:.3f}"
                )
            lines.extend(
                self.robustness_lines(
                    self.deadline_expired,
                    self.degraded_dispatches,
                    self.trace_dropped,
                    self.flight_dumps,
                )
            )
            # Single-process plane: ONE event loop, so one worker="0"
            # lag cell (the ring render emits one per front end).
            lines.extend(self.loop_lag_lines([("0", self.loop_lag_ms)]))
            # Single-process plane: the engine lives in THIS process, so
            # there is no respawn/replay/parking machinery — the block is
            # structurally zero but still exported (identical series set
            # across planes; monotonicity stays checkable).
            lines.extend(self.survivability_lines(0, 0, 0, 0, 0))
            lines.extend(
                self.tier_lines(
                    self.tier_requests,
                    self.tier_demotions,
                    self.brownout_demotions,
                )
            )
            for tenant in sorted(self.lifecycle):
                lines.extend(
                    self.lifecycle_lines(self.lifecycle[tenant], tenant)
                )
            lines.extend(self.autotune_lines(self.autotune))
            return "\n".join(lines) + "\n"


def render_ring_metrics(ring) -> str:
    """Prometheus exposition for the MULTI-WORKER plane, rendered entirely
    from the shared-memory ring (serve/ipc.py RequestRing — duck-typed
    here to keep this module import-light): every front end's
    request/latency block with a ``worker`` label, the
    ``mlops_tpu_ring_depth`` / ``mlops_tpu_shed_total`` gauges for every
    worker (always emitted, so a scrape proves each worker exists even
    before it served traffic), and the engine-process monitor aggregate
    (single-flight: only the engine's telemetry loop ever reads the
    device; front ends serve this text from shm, so ANY of the N
    SO_REUSEPORT workers answers a scrape with the full fleet view)."""
    from mlops_tpu.schema import SCHEMA

    routes = ServingMetrics.KNOWN_ROUTES + ("<other>",)
    buckets = ServingMetrics.LATENCY_BUCKETS
    tenants = tuple(getattr(ring, "tenant_names", ("default",)))
    lines = build_info_lines()
    lines.append("# TYPE mlops_tpu_requests_total counter")
    for w in range(ring.workers):
        for t, tenant in enumerate(tenants):
            for r_i, route in enumerate(routes):
                for s_i, status in enumerate(RING_STATUSES):
                    count = int(ring.req_counts[w, t, r_i, s_i])
                    if count:
                        lines.append(
                            f'mlops_tpu_requests_total{{route="{route}",'
                            f'status="{status}",worker="{w}",'
                            f'tenant="{tenant}"}} {count}'
                        )
                other = int(ring.req_counts[w, t, r_i, len(RING_STATUSES)])
                if other:
                    lines.append(
                        f'mlops_tpu_requests_total{{route="{route}",'
                        f'status="other",worker="{w}",'
                        f'tenant="{tenant}"}} {other}'
                    )
    lines.append("# TYPE mlops_tpu_request_latency_ms histogram")
    for w in range(ring.workers):
        for t, tenant in enumerate(tenants):
            cumulative = 0
            for edge, count in zip(buckets, ring.lat_counts[w, t]):
                cumulative += int(count)
                label = "+Inf" if edge == float("inf") else str(edge)
                lines.append(
                    f'mlops_tpu_request_latency_ms_bucket{{le="{label}",'
                    f'worker="{w}",tenant="{tenant}"}} {cumulative}'
                )
            lines.append(
                f'mlops_tpu_request_latency_ms_sum{{worker="{w}",'
                f'tenant="{tenant}"}} {float(ring.lat_sum_ms[w, t])}'
            )
            lines.append(
                f'mlops_tpu_request_latency_ms_count{{worker="{w}",'
                f'tenant="{tenant}"}} {int(ring.lat_n[w, t])}'
            )
    # Ring depth / shed per tenant: the per-tenant cells ARE the
    # partition occupancy (a slot is always held by exactly one tenant),
    # so summing the tenant label away reproduces the pre-tenancy
    # per-worker-per-class values dashboards already graph.
    lines.append("# TYPE mlops_tpu_ring_depth gauge")
    for w in range(ring.workers):
        for c_i, cls in enumerate(RING_CLASSES):
            for t, tenant in enumerate(tenants):
                lines.append(
                    f'mlops_tpu_ring_depth{{worker="{w}",class="{cls}",'
                    f'tenant="{tenant}"}} {int(ring.inflight[w, t, c_i])}'
                )
    lines.append("# TYPE mlops_tpu_shed_total counter")
    for w in range(ring.workers):
        for c_i, cls in enumerate(RING_CLASSES):
            for t, tenant in enumerate(tenants):
                lines.append(
                    f'mlops_tpu_shed_total{{worker="{w}",class="{cls}",'
                    f'tenant="{tenant}"}} {int(ring.shed[w, t, c_i])}'
                )
    # Per-tenant quota sheds: the subset of sheds rejected by the
    # tenant's own weighted max-min quota (its floor was exhausted) as
    # opposed to physical slot exhaustion — the fairness contract's
    # observable (docs/operations.md "Multi-tenant serving").
    lines.append("# TYPE mlops_tpu_tenant_quota_shed_total counter")
    for w in range(ring.workers):
        for t, tenant in enumerate(tenants):
            lines.append(
                f'mlops_tpu_tenant_quota_shed_total{{worker="{w}",'
                f'tenant="{tenant}"}} {int(ring.quota_shed[w, t])}'
            )
    # Monitor aggregates FOLD the replica axis (ISSUE 13): totals sum
    # across replica rows; the cross-replica drift mean is recomputed
    # from the unrounded per-replica sums (an exact weighted fold — a
    # mean of per-replica rounded means would drift with skewed load);
    # drift_last comes from the most recently fetched replica row.
    R = int(getattr(ring, "replicas", 1))
    T = len(tenants)
    lines.append("# TYPE mlops_tpu_rows_scored_total counter")
    for t, tenant in enumerate(tenants):
        lines.append(
            f'mlops_tpu_rows_scored_total{{tenant="{tenant}"}} '
            f"{int(ring.mon_vals[:, t, MON_ROWS].sum())}"
        )
    lines.append("# TYPE mlops_tpu_outliers_total counter")
    for t, tenant in enumerate(tenants):
        lines.append(
            f'mlops_tpu_outliers_total{{tenant="{tenant}"}} '
            f"{int(ring.mon_vals[:, t, MON_OUTLIERS].sum())}"
        )

    def _last_replica(t: int) -> int | None:
        """The replica whose drift_last row is freshest for tenant t:
        latest fetch stamp among rows that HAVE data, falling back to
        the lowest such row (host-fold engines never stamp fetches)."""
        has = [r for r in range(R) if ring.mon_vals[r, t, MON_HAS]]
        if not has:
            return None
        return max(
            has, key=lambda r: (float(ring.mon_vals[r, t, MON_FETCHED_AT]),
                                -r),
        )

    if any(ring.mon_vals[:, t, MON_HAS].any() for t in range(T)):
        lines.append("# TYPE mlops_tpu_feature_drift_score gauge")
        for t, tenant in enumerate(tenants):
            r_last = _last_replica(t)
            if r_last is None:
                continue
            for feature, score in zip(
                SCHEMA.feature_names, ring.mon_drift_last[r_last, t]
            ):
                lines.append(
                    f'mlops_tpu_feature_drift_score{{feature="{feature}",'
                    f'tenant="{tenant}"}} {float(score)}'
                )
        # Mean drift exists only on the device-accumulator path (written
        # by RequestRing.write_monitor, which also counts fetches); the
        # host-side fold for non-accumulating engines tracks no mean, and
        # rendering zeros would read as "no drift" where the
        # single-process server correctly emits no series at all.
        if any(
            int(ring.mon_vals[:, t, MON_FETCHES].sum()) for t in range(T)
        ):
            lines.append("# TYPE mlops_tpu_feature_drift_mean gauge")
            for t, tenant in enumerate(tenants):
                if not int(ring.mon_vals[:, t, MON_FETCHES].sum()):
                    continue
                if R == 1:
                    mean = ring.mon_drift_mean[0, t]
                else:
                    batches = float(ring.mon_vals[:, t, MON_BATCHES].sum())
                    mean = (
                        ring.mon_drift_sum[:, t, :].sum(axis=0)
                        / max(batches, 1.0)
                    ).round(6)
                for feature, score in zip(SCHEMA.feature_names, mean):
                    lines.append(
                        f'mlops_tpu_feature_drift_mean{{feature="{feature}",'
                        f'tenant="{tenant}"}} {float(score)}'
                    )
    fetches = int(ring.mon_vals[:, :, MON_FETCHES].sum())
    if fetches:
        lines.append("# TYPE mlops_tpu_monitor_fetches_total counter")
        lines.append(f"mlops_tpu_monitor_fetches_total {fetches}")
        lines.append("# TYPE mlops_tpu_monitor_batches_total counter")
        for t, tenant in enumerate(tenants):
            lines.append(
                f'mlops_tpu_monitor_batches_total{{tenant="{tenant}"}} '
                f"{int(ring.mon_vals[:, t, MON_BATCHES].sum())}"
            )
        # The age is the OLDEST fetched (replica, tenant) row's (min
        # over fetched stamps): this gauge is the documented staleness
        # ALARM, and a max would let any one healthy row's fresh fetch
        # mask another row's stuck monitor indefinitely.
        fetched = [
            float(ring.mon_vals[r, t, MON_FETCHED_AT])
            for r in range(R)
            for t in range(T)
            if float(ring.mon_vals[r, t, MON_FETCHED_AT]) > 0
        ]
        if fetched:
            age = time.monotonic() - min(fetched)
            lines.append(
                "# TYPE mlops_tpu_monitor_fetch_age_seconds gauge"
            )
            lines.append(
                f"mlops_tpu_monitor_fetch_age_seconds {age:.3f}"
            )
    # Robustness counters, same series names as the single-process plane:
    # front-end dead-work sheds (per-worker single-writer cells) plus the
    # engine-side expired completions and degraded dispatches, summed
    # over the replica rows.
    lines.extend(
        ServingMetrics.robustness_lines(
            int(ring.expired.sum())
            + int(ring.rob_vals[:, ROB_EXPIRED_ENGINE].sum()),
            int(ring.rob_vals[:, ROB_DEGRADED].sum()),
            int(ring.trace_dropped.sum()),
            sum(int(x) for x in getattr(ring, "flight_dumps", ())),
        )
    )
    # Event-loop lag, one cell per front-end worker (single-writer shm
    # gauge each front end's sanitizer publishes) — same shared formatter
    # and 0.0 baseline as the single-process render's worker="0" cell.
    lines.extend(
        ServingMetrics.loop_lag_lines(
            [
                (str(w), float(lag))
                for w, lag in enumerate(getattr(ring, "loop_lag_ms", ()))
            ]
            or [("0", 0.0)]
        )
    )
    # Engine-survivability block (ISSUE 11): per-replica rows summed
    # into plane totals plus the per-worker parking/brownout cells —
    # identical series names to the single-process render's zero
    # baseline (and numerically identical to pre-replica planes at E=1).
    lines.extend(
        ServingMetrics.survivability_lines(
            int(ring.eng_vals[:, ENG_RESPAWNS].sum()),
            int(ring.eng_vals[:, ENG_REPLAYED].sum()),
            float(ring.eng_vals[:, ENG_ROWS_LOST].sum()),
            int(ring.parked.sum()),
            int(ring.brownout_shed.sum()),
            incarnation=int(ring.eng_vals[:, ENG_INCARNATION].sum()),
        )
    )
    # SLO tier-routing block (ISSUE 19): tier request counts are
    # engine-writer per-replica rows (summed into plane totals),
    # demotions per-worker single-writer admission cells. Same shared
    # formatter (and zero baseline) as the single-process render.
    tier_vals = getattr(ring, "tier_counts", None)
    demote = getattr(ring, "tier_demote", None)
    bdemote = getattr(ring, "brownout_demote", None)
    lines.extend(
        ServingMetrics.tier_lines(
            {
                tier: int(tier_vals[:, i].sum())
                for i, tier in enumerate(TIERS)
            }
            if tier_vals is not None
            else None,
            int(demote.sum()) if demote is not None else 0,
            int(bdemote.sum()) if bdemote is not None else 0,
        )
    )
    # Per-replica fleet block (ISSUE 13). EVERY configured replica gets
    # EVERY series on EVERY scrape — a never-dispatched replica exports
    # zeros, because "no series" is indistinguishable from "dead
    # replica" on a dashboard (the same always-emit contract PR 6 pinned
    # for the per-worker depth/shed series).
    lines.append("# TYPE mlops_tpu_replica_ready gauge")
    for r in range(R):
        lines.append(
            f'mlops_tpu_replica_ready{{replica="{r}"}} '
            f"{1 if ring.rep_ready[r] else 0}"
        )
    lines.append("# TYPE mlops_tpu_replica_ring_depth gauge")
    for r in range(R):
        lines.append(
            f'mlops_tpu_replica_ring_depth{{replica="{r}"}} '
            f"{int(ring.rep_inflight[:, r].sum())}"
        )
    lines.append("# TYPE mlops_tpu_replica_incarnation gauge")
    for r in range(R):
        lines.append(
            f'mlops_tpu_replica_incarnation{{replica="{r}"}} '
            f"{int(ring.eng_vals[r, ENG_INCARNATION])}"
        )
    lines.append("# TYPE mlops_tpu_replica_respawn_total counter")
    for r in range(R):
        lines.append(
            f'mlops_tpu_replica_respawn_total{{replica="{r}"}} '
            f"{int(ring.eng_vals[r, ENG_RESPAWNS])}"
        )
    lines.append("# TYPE mlops_tpu_replica_replayed_slots_total counter")
    for r in range(R):
        lines.append(
            f'mlops_tpu_replica_replayed_slots_total{{replica="{r}"}} '
            f"{int(ring.eng_vals[r, ENG_REPLAYED])}"
        )
    # Per-replica goodput: rows this replica scored (its monitor rows
    # summed over tenants) — with replica_ring_depth, the router's two
    # observables and the scaling-efficiency denominators.
    lines.append("# TYPE mlops_tpu_replica_rows_scored_total counter")
    for r in range(R):
        lines.append(
            f'mlops_tpu_replica_rows_scored_total{{replica="{r}"}} '
            f"{int(ring.mon_vals[r, :, MON_ROWS].sum())}"
        )
    metas = [float(ring.shape_meta[r]) for r in range(R)]
    if any(m > 0 for m in metas):
        # tracewire shape histograms, mirrored from each replica's
        # ShapeStats by its telemetry loop (shape_meta[r] = that stats'
        # armed-at monotonic time) — MERGED by entry key (replicas warm
        # identical grids) into the same series names as the
        # single-process render (trace/shapes.py `_lines` is the one
        # formatter); the rate base is the oldest armed clock.
        from mlops_tpu.trace.shapes import (
            merge_entries,
            read_table,
            render_entries_lines,
        )

        armed = [r for r in range(R) if metas[r] > 0]
        entries = merge_entries(
            [
                read_table(ring.shape_keys[r], ring.shape_vals[r])
                for r in armed
            ]
        )
        elapsed = time.monotonic() - min(metas[r] for r in armed)
        # Eviction fold: per-replica mirror rows are independent tables,
        # so the fleet total is the plain sum (each row is already
        # respawn-monotone — max()'d at write time).
        evicted = getattr(ring, "shape_evicted", None)
        evicted_total = (
            int(sum(float(evicted[r]) for r in armed))
            if evicted is not None
            else 0
        )
        lines.extend(
            render_entries_lines(entries, elapsed, evicted=evicted_total)
        )
    if getattr(ring, "slo_armed", False):
        # sloscope (mlops_tpu/slo/): the SLO/alert block the LEAD engine
        # replica's telemetry loop last mirrored into shm — rendered by
        # ANY front end, so during a full engine outage the gauges serve
        # last-known values (rows never written render the zero
        # baseline) and the scrape NEVER errors. ``engine_down`` is
        # computed HERE, by whoever answers the scrape: a dead engine
        # cannot raise its own alert.
        from mlops_tpu.slo.engine import read_slo_view, render_slo_lines

        engine_down = not ring.engine_ready and bool(
            (ring.eng_vals[:, ENG_DOWN_SINCE] > 0).any()
        )
        view = read_slo_view(
            ring.slo_vals,
            ring.alert_vals,
            tenants,
            tuple(float(x) for x in ring.slo_meta[:4]),
        )
        lines.extend(render_slo_lines(view, engine_down=engine_down))
    led_metas = [float(m) for m in getattr(ring, "ledger_meta", [])]
    if any(m > 0 for m in led_metas):
        # Device-time cost ledger (slo/ledger.py), mirrored per replica
        # by the telemetry loop and MERGED by entry key at render — the
        # same series names the single-process render emits from its
        # in-process ledger.
        from mlops_tpu.slo.ledger import (
            merge_entries as merge_ledger_entries,
            read_table as read_ledger_table,
            render_entry_lines,
        )

        entries = merge_ledger_entries(
            [
                read_ledger_table(ring.ledger_keys[r], ring.ledger_vals[r])
                for r in range(R)
                if led_metas[r] > 0
            ]
        )
        lines.extend(render_entry_lines(entries))
    auto_vals = getattr(ring, "auto_vals", None)
    auto_armed = (
        [r for r in range(R) if float(auto_vals[r, AUTO_HAS])]
        if auto_vals is not None
        else []
    )
    if auto_armed:
        # gridtuner block, rebuilt as a snapshot dict so the SAME
        # formatter emits it (identical series names across planes).
        # grid_generation folds to the MIN over armed replicas — the
        # fleet's adopted floor: the gauge moves only once every sibling
        # has adopted the lead's plan, which is the convergence signal a
        # regrid runbook watches. Plan counters sum across replicas; the
        # gain audit gauges come from the LEAD (lowest armed) replica —
        # the one that fit the model and searched the plan.
        lead = auto_armed[0]
        lines.extend(
            ServingMetrics.autotune_lines(
                {
                    "grid_generation": int(
                        min(
                            ring.auto_vals[r, AUTO_GRID_GEN]
                            for r in auto_armed
                        )
                    ),
                    "plans": {
                        outcome: int(
                            sum(
                                ring.auto_plans[r, i] for r in auto_armed
                            )
                        )
                        for i, outcome in enumerate(AUTOTUNE_OUTCOMES)
                    },
                    "predicted_gain_pct": (
                        float(ring.auto_vals[lead, AUTO_PRED_GAIN])
                        if ring.auto_vals[lead, AUTO_HAS_PRED]
                        else None
                    ),
                    "measured_gain_pct": (
                        float(ring.auto_vals[lead, AUTO_MEAS_GAIN])
                        if ring.auto_vals[lead, AUTO_HAS_MEAS]
                        else None
                    ),
                }
            )
        )
    for t, tenant in enumerate(tenants):
        if not ring.life_vals[t, LIFE_HAS]:
            continue
        # Lifecycle block, rebuilt as a snapshot dict so the SAME
        # formatter emits it (identical series names across planes; any
        # front end renders the engine process's per-tenant loop state
        # from shm).
        lines.extend(
            ServingMetrics.lifecycle_lines(
                {
                    "generation": int(ring.life_vals[t, LIFE_GENERATION]),
                    "drift_triggers": int(ring.life_vals[t, LIFE_TRIGGERS]),
                    "shadow_auc_delta": (
                        float(ring.life_vals[t, LIFE_AUC_DELTA])
                        if ring.life_vals[t, LIFE_HAS_DELTA]
                        else None
                    ),
                    "promotions": {
                        outcome: int(ring.life_promos[t, i])
                        for i, outcome in enumerate(LIFE_OUTCOMES)
                    },
                    "reservoir_rows": int(ring.life_vals[t, LIFE_RESERVOIR]),
                    "breaker_open": bool(
                        ring.life_vals[t, LIFE_BREAKER_OPEN]
                    ),
                    "breaker_trips": int(
                        ring.life_vals[t, LIFE_BREAKER_TRIPS]
                    ),
                },
                tenant,
            )
        )
    return "\n".join(lines) + "\n"
