"""The serving wire contract, jax-free: group geometry + response shape.

Both halves of the multi-worker plane need these without importing the
engine (whose module pulls jax): the HTTP front-end processes
(`serve/frontend.py`) size ring slabs and coalescing classes from the
group geometry and format responses from raw arrays; the engine process
uses the same constants to pick compiled shapes and the same formatter
for its in-process fetch — which is what makes the two planes
bit-identical by construction. `serve/engine.py` re-exports everything
here, so historical imports keep working.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from mlops_tpu.schema import SCHEMA

# Micro-batching shape grid: concurrent requests coalesce into [R, B, ...]
# stacks — R request-slots (padded up to a slot bucket), each padded to B
# rows. Only small requests coalesce; big ones already fill the MXU alone.
# Slot buckets go to 64: on a remote-attached chip every dispatch pays a
# flat transport round trip (measured ~70-90 ms through this harness's
# tunnel), so request throughput scales with requests-per-dispatch — 64
# batch-1 requests in one vmapped program cost the same wall time as one.
# Row buckets are (1, 8): batch-1 is the dominant serving shape and
# padding it to 8 rows made every grouped dispatch compute 8x the rows it
# returned — on CPU backends (serial compute) that padding was the
# throughput ceiling. An all-batch-1 group now rides the [R, 1, ...]
# family; mixed small sizes pad to 8 as before.
GROUP_SLOT_BUCKETS = (2, 4, 8, 16, 32, 64)
GROUP_ROW_BUCKETS = (1, 8)
GROUP_ROW_BUCKET = GROUP_ROW_BUCKETS[-1]

# Ring completion statuses (serve/ipc.py resp_status): the engine answers
# every accepted descriptor with exactly one of these. EXPIRED is the
# dead-work-shedding path — a descriptor whose deadline budget ran out
# before dispatch is completed WITHOUT touching the device, and the front
# end answers 504 (docs/operations.md "Failure domains & degraded modes").
RESP_OK, RESP_ERROR, RESP_EXPIRED = 0, 1, 2


class DeadlineExceeded(Exception):
    """A request's deadline budget (``x-request-deadline-ms``, or
    ``serve.request_timeout_s``) ran out before its work dispatched —
    raised engine-side (the micro-batcher's claim-time purge) so the
    handler answers the documented 504 without the device ever seeing
    the dead request. Jax-free by design: both planes' HTTP layers and
    the batcher share it without an engine import."""



def format_response(
    predictions: np.ndarray, outliers: np.ndarray, drift: np.ndarray
) -> dict[str, Any]:
    """Raw response arrays -> the reference response dict.

    THE one formatting rule for every serving path: the in-process fetch
    (`InferenceEngine.fetch_arrays`/`fetch_group`) and the multi-worker
    front ends (which read the same f64 arrays back out of the
    shared-memory ring) both format through here, so the two planes are
    bit-identical by construction — the parity suite pins it
    (tests/test_frontend.py). Inputs are the engine's raw-fetch contract:
    f64 predictions/outliers of the request's row count and the f64 drift
    vector already rounded to 6 places."""
    return {
        "predictions": predictions.tolist(),
        "outliers": outliers.tolist(),
        "feature_drift_batch": dict(zip(SCHEMA.feature_names, drift.tolist())),
    }


def empty_response() -> dict[str, Any]:
    """The zero-row response (no device work, no drift signal) — shared by
    `predict_arrays` and the front ends' local empty-request fast path."""
    return {
        "predictions": [],
        "outliers": [],
        "feature_drift_batch": dict.fromkeys(SCHEMA.feature_names, 0.0),
    }


# Pre-encoded response scaffolding (ISSUE 18 satellite — the encode-bound
# HTTP residue): the response's entire static skeleton — braces, key
# names, the 20+ drift feature keys with their quoting/escaping — is
# identical on every response, yet `json.dumps` of the formatted dict
# rebuilt the dict AND re-serialized the skeleton per request (on the
# single-process plane's event loop — its bottleneck thread at high
# concurrency). `encode_response` serializes ONLY the floats, in one C
# `json.dumps` call over the three flat lists, and splices the baked
# skeleton around them. Because every float goes through the SAME C
# encoder the dict path used, the wire bytes are EXACTLY what
# `json.dumps(format_response(...), separators=(",", ":"))` produced —
# for every input, non-finite included (NaN/Infinity render identically;
# no fallback needed). The parity suite pins it
# (tests/test_wire_encode.py), and the encode runs wherever the caller
# already holds the arrays (the engine's executor thread, the ring front
# end's handler) — cheaper in total CPU than dict-build + dumps, not
# just moved off the loop.
_DRIFT_KEYS = tuple(
    json.dumps(name) + ":" for name in SCHEMA.feature_names
)


def encode_response(
    predictions: np.ndarray, outliers: np.ndarray, drift: np.ndarray
) -> bytes:
    """Raw response arrays -> pre-encoded wire bytes, byte-identical to
    ``json.dumps(format_response(...), separators=(",", ":")).encode()``
    for every input (pinned by tests/test_wire_encode.py)."""
    # One C-encoder pass over all the floats. The "],[" delimiter can
    # never occur inside a rendered float (digits, sign, dot, eE,
    # NaN/Infinity letters only), so the three segments split back out
    # exactly — including the empty-list edges.
    floats = json.dumps(
        [
            np.asarray(predictions).tolist(),
            np.asarray(outliers).tolist(),
            np.asarray(drift).tolist(),
        ],
        separators=(",", ":"),
    )
    preds, outs, drifts = floats[2:-2].split("],[")
    return (
        '{"predictions":['
        + preds
        + '],"outliers":['
        + outs
        + '],"feature_drift_batch":{'
        + ",".join(map(str.__add__, _DRIFT_KEYS, drifts.split(",")))
        + "}}"
    ).encode()


# The zero-row fast path's cached bytes (the dict is static, so the
# encode is too).
EMPTY_RESPONSE_BYTES = json.dumps(
    empty_response(), separators=(",", ":")
).encode()
