"""Engine-free HTTP/1.1 core: protocol, routing skeleton, wire helpers.

The dependency-free asyncio protocol that used to live inside
``serve/server.py``'s engine-coupled server, extracted so a process can
parse, validate, and answer HTTP without an engine (or jax) anywhere in
sight: the single-process ``HttpServer`` subclasses ``HttpProtocol``
against a live ``InferenceEngine``, and the multi-worker front ends
(``serve/frontend.py``) subclass it against the shared-memory request
ring (``serve/ipc.py``).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
import re
import uuid
from typing import Any

import pydantic

from mlops_tpu.config import ServeConfig
from mlops_tpu.schema import LoanApplicant
from mlops_tpu.serve.tierroute import (  # jax-free
    SLO_DEFAULT,
    resolve_slo_class,
)
from mlops_tpu.tenancy.router import TenantRouter  # jax-free
from mlops_tpu.trace.span import Span  # jax-free; front ends import this too

logger = logging.getLogger("mlops_tpu.serve")

# Compact separators: the default ", "/": " pads every response body (and
# both structured log events) with bytes pure of whitespace — on the c128
# throughput path serialization is measurable hot-path CPU.
def _json_default(obj):
    # Wire-mode responses are pre-encoded json bytes (serve/wire.py
    # encode_response); a sampled ModelOutput log event embeds one as its
    # "data" field, and re-parsing here — only when the sampler actually
    # fires — keeps the logged JSON identical to the dict-mode event.
    if isinstance(obj, (bytes, bytearray)):
        return json.loads(obj)
    raise TypeError(
        f"Object of type {type(obj).__name__} is not JSON serializable"
    )


def _dumps(payload) -> str:
    return json.dumps(payload, separators=(",", ":"), default=_json_default)


class _LazyJson:
    """Defer json.dumps of a log payload to %s-formatting time: the dumps
    runs only when a handler actually emits the record, so a deployment
    that filters (not just disables) INFO never pays per-request
    serialization of full request/response bodies."""

    __slots__ = ("_payload",)

    def __init__(self, payload):
        self._payload = payload

    def __str__(self) -> str:
        return _dumps(self._payload)


_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            409: "Conflict", 413: "Payload Too Large",
            422: "Unprocessable Entity", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout"}
# (status, content_type) -> precomputed immutable head prefix. Statuses and
# content types form a tiny closed set, so the f-string formatting + encode
# of the static head runs once per pair instead of once per response.
_HEAD_PREFIXES: dict[tuple[int, str], bytes] = {}
_KEEP_ALIVE_TAIL = b"connection: keep-alive\r\n\r\n"
_CLOSE_TAIL = b"connection: close\r\n\r\n"


def deadline_response(detail: str = "request deadline exceeded") -> tuple:
    """THE deadline-exceeded wire shape, shared by every plane: a
    documented ``504`` (distinct from the shed path's 503+Retry-After —
    a 504'd request may or may not have been scored; a shed 503 never
    was, and only the 503 invites a retry)."""
    return 504, {"detail": detail}, "application/json"


def profile_payload(
    status: int, action: str, profile_dir: str, err: str | None = None
) -> tuple:
    """THE /debug/profile wire shapes, shared by both planes: the
    single-process server answers from its in-process `jax.profiler`
    state, the ring front ends from the engine process's acknowledgement
    word (serve/ipc.py) — same status, same body either way."""
    if status == 200:
        state = "tracing" if action == "start" else "stopped"
        return 200, {"status": state, "dir": profile_dir}, "application/json"
    if status == 409:
        detail = (
            "trace already running" if action == "start"
            else "no trace running"
        )
        return 409, {"detail": detail}, "application/json"
    if status == 404:
        return 404, {"detail": "profiling disabled"}, "application/json"
    if status == 504:
        return 504, {
            "detail": "engine did not acknowledge the profile request"
        }, "application/json"
    detail = f"profiler {action} failed"
    if err:
        detail = f"{detail}: {err}"
    return 500, {"detail": detail}, "application/json"


def _head_prefix(status: int, content_type: str) -> bytes:
    prefix = _HEAD_PREFIXES.get((status, content_type))
    if prefix is None:
        reason = _REASONS.get(status, "OK")
        prefix = _HEAD_PREFIXES[(status, content_type)] = (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"content-type: {content_type}\r\n"
        ).encode()
    return prefix

_DOCS_HTML = """<!doctype html>
<html><head><title>{title}</title></head>
<body style="font-family: sans-serif; max-width: 42rem; margin: 2rem auto">
<h1>{title}</h1>
<p>TPU-native credit-default inference service.</p>
<ul>
<li><code>POST /predict</code> — body: JSON list of loan-applicant records;
returns <code>{{"predictions": [...], "outliers": [...],
"feature_drift_batch": {{...}}}}</code></li>
<li><code>GET /healthz/live</code> — liveness probe</li>
<li><code>GET /healthz/ready</code> — readiness probe (model loaded + jit warm)</li>
<li><code>GET /metrics</code> — Prometheus metrics</li>
<li><code>POST /debug/profile/start</code>, <code>POST /debug/profile/stop</code>
— capture a <code>jax.profiler</code> device trace (view in TensorBoard)</li>
</ul>
</body></html>"""


class HttpProtocol:
    """Engine-free HTTP/1.1 layer: connection handling, head parsing,
    response encoding, request-id plumbing, docs/openapi routes, and the
    drain bookkeeping — everything a front-end PROCESS needs without an
    engine in sight (serve/frontend.py subclasses this against the
    shared-memory ring; HttpServer below subclasses it against a live
    InferenceEngine). Subclasses implement `_predict`, `_ready`,
    `_metrics_endpoint`, `_profile`, and set `self.metrics` (anything
    with ``observe_request(route, status, latency_ms)``)."""

    MAX_BODY_BYTES = 16 * 1024 * 1024
    MAX_HEADERS = 100

    def __init__(self, config: ServeConfig):
        self.config = config
        self.metrics: Any = None  # subclass responsibility
        self._applicant_list = pydantic.TypeAdapter(list[LoanApplicant])
        # Request-size cap for the 413 gate; subclasses tighten it (the
        # single-process server clamps to the engine's largest warmed
        # bucket, front ends to the ring's slab capacity).
        self.max_batch = config.max_batch
        self._openapi: dict | None = None  # built lazily, served cached
        # Drain bookkeeping: open client transports and the subset with an
        # exchange currently in flight (between request read and response
        # write). SIGTERM closes idle transports immediately and lets busy
        # ones finish their current response (serve/server.py::_serve).
        # Concurrency note (tpulint Layer 3): every mutable field here is
        # EVENT-LOOP CONFINED — touched only from coroutines on the one
        # asyncio thread — which is why none of them carries a lock.
        self.draining = False
        self._connections: set[asyncio.StreamWriter] = set()
        self._busy: set[asyncio.StreamWriter] = set()
        # tracewire (mlops_tpu/trace/): a TraceRecorder when the trace
        # config section arms it, else None — the disarmed hot path pays
        # one is-None check per request. Subclasses set the plane/worker
        # labels their spans carry.
        self.tracer: Any = None
        self.trace_plane = "single"
        self.trace_worker = 0
        # sloscope (mlops_tpu/slo/): the flight recorder, when the slo
        # config section arms it, else None — one is-None check per
        # request disarmed (the faultline discipline). Subclasses also
        # override `_slo_view`/`_engine_down` so /healthz and the
        # request hooks render their plane's fleet verdict.
        self.flightrec: Any = None
        # Loop-lag sanitizer (analysis/loopcheck.py): armed by the plane
        # runner when ``serve.loop_lag_monitor`` is on, else None — the
        # mlops_tpu_event_loop_lag_ms gauge drains its window max on each
        # /metrics scrape (single plane) or watchdog pass (ring plane).
        self.loop_monitor: Any = None
        # Tenant routing (mlops_tpu/tenancy/): the ``x-tenant`` header
        # resolves to a tenant index through this router; subclasses
        # serving a multi-tenant fleet install their own. The default is
        # the degenerate single-tenant fleet ("default"), under which
        # untagged traffic behaves exactly like the pre-tenancy plane.
        self.tenants = TenantRouter(())
        # SLO tier routing (ISSUE 19, serve/tierroute.py): armed when the
        # config turns it on AND the serving side committed more than one
        # tier. Disarmed (the default) the class resolution short-circuits
        # to DEFAULT — one boolean check per request, no header parsing.
        self.slo_routing = bool(getattr(config, "tier_routing", False))

    # ------------------------------------------------------ subclass hooks
    async def _predict(
        self,
        body: bytes,
        request_id: str | None = None,
        deadline: float | None = None,
        span=None,
        tenant_raw: str = "",
        slo: int = SLO_DEFAULT,
    ):
        """The reference's `predict()` endpoint (`app/main.py:42-86`):
        validate -> log InferenceData -> score -> log ModelOutput ->
        respond. The SHELL — validation, the 422/413/504 contracts, and
        the two-event structured logging — is shared verbatim by every
        plane; subclasses provide only `_score` (engine call or ring
        round trip), which returns the response dict — or its
        pre-encoded wire bytes (serve/wire.py `encode_response`), which
        `_write_response` sends as-is — or a pre-built
        (status, payload, content_type[, headers]) tuple for its error
        paths (deadline 504, shed 503, failure 500).

        ``deadline`` is the request's absolute deadline on the event
        loop's clock (parsed from ``x-request-deadline-ms`` at admission
        — `_request_deadline`), decremented implicitly as the request
        moves through validation -> encode -> ring wait -> dispatch:
        every stage that is about to start expensive work checks the
        REMAINING budget and answers the documented ``504`` instead of
        doing dead work the client will never read.

        ``tenant_raw`` is the request's ``x-tenant`` header value:
        resolved FIRST (before validation pays pydantic) — an unknown
        tenant answers 404 rather than silently billing the default
        tenant's quota and monitors for a stranger's traffic.

        ``slo`` is the request's SLO class (serve/tierroute.py — explicit
        ``x-slo-class`` header or defaulted from the deadline budget),
        resolved at admission and carried down to `_score`, where each
        plane maps it to a serving tier."""
        tenant = self.tenants.resolve(tenant_raw)
        if tenant is None:
            return (
                404,
                {"detail": f"unknown tenant {tenant_raw[:64]!r}"},
                "application/json",
            )
        try:
            records = self._applicant_list.validate_json(body)
        except pydantic.ValidationError as err:
            return 422, {"detail": json.loads(err.json())}, "application/json"
        if len(records) > self.max_batch:
            # Cap guards the compiled-shape grid: anything beyond the
            # largest warmed bucket would trigger an exact-shape compile
            # per novel size. Offline scoring of big files goes through
            # predict-file.
            return (
                413,
                {
                    "detail": f"batch of {len(records)} exceeds "
                    f"max_batch={self.max_batch}"
                },
                "application/json",
            )
        if deadline is not None and asyncio.get_running_loop().time() >= deadline:
            # Already expired at admission (a slow body read, a queued
            # accept): no encode, no slot, no dispatch — the cheapest
            # possible dead-work shed.
            self._count_deadline_expired()
            return deadline_response()
        request_id = request_id or uuid.uuid4().hex
        record_dicts = [r.model_dump() for r in records]
        if span is not None:
            # Admission ends here: head + body read, pydantic validation,
            # and the 413/deadline gates all behind us.
            span.rows = len(record_dicts)
            span.stamp("admission")
        # Three layers keep log formatting off the hot path: isEnabledFor
        # skips everything when the deployment silences INFO, _LazyJson
        # defers the dumps of the full payload to record-emit time, and
        # serve.log_sample_rate (< 1.0) SAMPLES the two-event pair under
        # overload — while non-200 outcomes are ALWAYS logged: an
        # unsampled request that sheds/fails emits its InferenceData
        # event post-hoc, so at rate 0.01 a shed burst still logs every
        # 503 (errors are never sampled out of the evidence stream).
        info_enabled = logger.isEnabledFor(logging.INFO)
        rate = self.config.log_sample_rate
        sampled = rate >= 1.0 or random.random() < rate
        request_event = None
        if info_enabled:
            request_event = {
                "service_name": self.config.service_name,
                "type": "InferenceData",
                "request_id": request_id,
                "data": record_dicts,
            }
            if sampled:
                logger.info("%s", _LazyJson(request_event))
        response = await self._score(
            record_dicts, request_id, deadline, span, tenant, slo
        )
        if isinstance(response, tuple):
            # Subclass error path (shed 503 / deadline 504 / failure
            # 500), already wire-shaped: an unsampled request's evidence
            # event is emitted NOW — non-200s always log.
            if info_enabled and not sampled:
                logger.info("%s", _LazyJson(request_event))
            return response
        if info_enabled and sampled:
            logger.info(
                "%s",
                _LazyJson(
                    {
                        "service_name": self.config.service_name,
                        "type": "ModelOutput",
                        "request_id": request_id,
                        "data": response,
                    }
                ),
            )
        return 200, response, "application/json"

    async def _score(
        self,
        record_dicts: list[dict],
        request_id: str,
        deadline: float | None = None,
        span=None,
        tenant: int = 0,
        slo: int = SLO_DEFAULT,
    ):
        raise NotImplementedError

    def _count_deadline_expired(self) -> None:
        """Record one dead-work shed (a request answered 504 WITHOUT its
        work running) on whatever metrics sink the subclass installed."""
        count = getattr(self.metrics, "count_deadline_expired", None)
        if count is not None:
            count()

    def _ready(self) -> bool:
        raise NotImplementedError

    def _slo_view(self):
        """The sloscope view dict for /healthz (`slo/engine` view shape):
        the single-process server reads its in-process SLOEngine, ring
        front ends read the shm mirror; None = sloscope disarmed (the
        verdict then derives from readiness alone)."""
        return None

    def _engine_down(self) -> bool:
        """True during a FULL engine outage (ring plane: every replica
        down with the outage supervisor-stamped). The single-process
        server's engine lives in-process — never down while answering."""
        return False

    async def _healthz(self):
        """`GET /healthz` — the sloscope VERDICT endpoint (distinct from
        the liveness/readiness probes): ok / degraded (an alert is
        active; the body names them) / down (503). One wire shape for
        both planes (`slo/engine.health_verdict`)."""
        from mlops_tpu.slo.engine import health_verdict

        return health_verdict(
            self._slo_view(), self._ready(), engine_down=self._engine_down()
        )

    async def _metrics_endpoint(self):
        raise NotImplementedError

    async def _profile(self, action: str):
        # Profiling captures a device trace — only the engine-owning
        # process can serve it; subclasses without a route to the engine
        # report it unavailable. Async so the ring plane's forward can
        # await the engine's acknowledgement without blocking the loop.
        return 404, {"detail": "profiling disabled"}, "application/json"

    # ----------------------------------------------------------- HTTP layer
    async def handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._connections.add(writer)
        try:
            while True:
                # Line-by-line head read. This is NOT an event-loop cost:
                # readline() on already-buffered bytes returns without
                # suspending, so a whole head arriving in one TCP segment
                # (the normal case) costs one suspension total. It also
                # keeps the old tolerance for bare-LF request heads, which
                # a single readuntil(b"\r\n\r\n") would hang on.
                request_line = await reader.readline()
                if not request_line:
                    break
                # tracewire span clock zero: the request head is in hand;
                # everything from here to the socket write lands in a
                # stage. One time() call per request, only when armed.
                t_recv = time.monotonic() if self.tracer is not None else 0.0
                try:
                    method, path, _ = request_line.decode("latin1").split(" ", 2)
                except ValueError:
                    await self._write_response(writer, 400, {"detail": "bad request"})
                    break
                headers = {}
                header_error = None
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    if len(headers) >= self.MAX_HEADERS:
                        header_error = "too many headers"
                        break
                    name, _, value = line.decode("latin1").partition(":")
                    name = name.strip().lower()
                    if name == "content-length" and name in headers:
                        # Duplicate Content-Length lines would collapse
                        # last-wins in the dict while a conformant
                        # intermediary rejects or picks another value
                        # (RFC 9110 §8.6) — the desync is a smuggling
                        # vector, so reject instead.
                        header_error = "duplicate content-length"
                        break
                    headers[name] = value.strip()
                if header_error:
                    await self._write_response(
                        writer, 400, {"detail": header_error}
                    )
                    break
                if "transfer-encoding" in headers:
                    # No TE support: reading the chunk framing as the
                    # next pipelined request would desync the connection
                    # (RFC 9112 §6.1 smuggling vector) — reject and
                    # CLOSE rather than guess at the body length.
                    await self._write_response(
                        writer, 400,
                        {"detail": "transfer-encoding not supported"},
                        keep_alive=False,
                    )
                    break
                # Deadline budget admission: stamped when the HEAD is in
                # hand — a slow (or slowloris) body spends the client's
                # budget, so the expiry check after the body read sheds
                # it without any downstream work.
                deadline = self._request_deadline(headers)
                slo = self._request_slo(headers)
                body = b""
                # RFC 9110: Content-Length is 1*DIGIT. Bare int() also
                # accepts '+5', '-1', '1_0', and unicode digits — parser
                # disagreement with conformant intermediaries (request
                # smuggling class), so gate on ASCII digits explicitly.
                raw_length = headers.get("content-length", "")
                if raw_length.isascii() and raw_length.isdigit():
                    length = int(raw_length)
                elif not raw_length:
                    length = 0
                else:
                    await self._write_response(
                        writer, 400, {"detail": "bad content-length"}
                    )
                    break
                if length > self.MAX_BODY_BYTES:
                    await self._write_response(
                        writer,
                        413,
                        {"detail": f"body exceeds {self.MAX_BODY_BYTES} bytes"},
                    )
                    break
                if length:
                    body = await reader.readexactly(length)

                # A draining server finishes the current exchange but
                # advertises connection: close and stops looping.
                keep_alive = (
                    headers.get("connection", "keep-alive") != "close"
                    and not self.draining
                )
                self._busy.add(writer)
                try:
                    start = time.perf_counter()
                    request_id = self._request_id(headers)
                    route_path = path.split("?", 1)[0]
                    # The tenant tag rides the request (mlops_tpu/tenancy/):
                    # resolved to a BOUNDED label here (known name,
                    # default, or the closed unknown marker) for the
                    # span dimension; the predict shell resolves the
                    # index (unknown -> 404) before any scoring work.
                    # Metrics bill strangers' 404s to the DEFAULT
                    # tenant's row (bill_label) — the ring plane's shm
                    # counters have one fixed row per declared tenant,
                    # and both planes must emit identical series.
                    tenant_raw = headers.get("x-tenant", "")
                    tenant_label = self.tenants.label(tenant_raw)
                    tenant_bill = self.tenants.bill_label(tenant_raw)
                    span = None
                    if (
                        self.tracer is not None
                        and route_path == "/predict"
                        and method == "POST"
                    ):
                        # The request id IS the trace id (inbound
                        # x-request-id honored, echoed on the response and
                        # both log events) — one identifier correlates the
                        # logs, the span record, and the client's retry.
                        span = Span(
                            trace_id=request_id,
                            plane=self.trace_plane,
                            worker=self.trace_worker,
                            route=route_path,
                            t0=t_recv,
                            tenant=tenant_label,
                        )
                    # Routes return (status, payload, content_type) with an
                    # optional 4th element of extra header lines (the shed
                    # path's Retry-After).
                    result = await self._route(
                        method, route_path, body, request_id, deadline,
                        span, tenant_raw, slo,
                    )
                    status, payload, content_type = result[:3]
                    extra_headers = result[3] if len(result) > 3 else None
                    latency_ms = (time.perf_counter() - start) * 1e3
                    self.metrics.observe_request(
                        route_path, status, latency_ms, tenant=tenant_bill
                    )
                    if self.flightrec is not None:
                        # Flight-recorder evidence ring (mlops_tpu/slo/):
                        # one bounded append per request; 5xx feed its
                        # spike trigger.
                        self.flightrec.observe_request(
                            route_path, status, latency_ms,
                            tenant=tenant_bill, request_id=request_id,
                        )
                    keep_alive = keep_alive and not self.draining
                    await self._write_response(
                        writer, status, payload, content_type, keep_alive,
                        request_id=request_id, extra_headers=extra_headers,
                    )
                    if span is not None and not span.abandoned:
                        # Respond ends once the bytes are drained to the
                        # socket — the span's wall clock is the client's
                        # observed latency minus only kernel delivery.
                        # Abandoned spans (a timed-out engine call may
                        # still be stamping from its executor thread) are
                        # dropped, never finished: finish() must not race
                        # a concurrent stamp.
                        span.stamp("respond")
                        record = span.finish(status)
                        self.tracer.record(record)
                        if self.flightrec is not None:
                            # With tracewire armed too, the dump's
                            # timeline carries the offending spans, not
                            # just their statuses.
                            self.flightrec.note_span(record)
                finally:
                    self._busy.discard(writer)
                if not keep_alive:
                    break
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
        ):
            pass
        finally:
            self._connections.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    _REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")

    def _request_deadline(self, headers: dict) -> float | None:
        """Absolute event-loop deadline from a well-formed
        ``x-request-deadline-ms`` header (positive ASCII digits), or None.
        Malformed values are IGNORED, not 400'd — the header is an
        optional optimization hint (dead-work shedding), and a client
        bug in a hint must not turn scored traffic into errors. The
        loop clock is ``time.monotonic`` on every supported platform, so
        the multi-worker plane's engine process can compare the same
        value (serve/ipc.py slot deadlines)."""
        raw = headers.get("x-request-deadline-ms", "")
        if raw and raw.isascii() and raw.isdigit() and int(raw) > 0:
            return asyncio.get_running_loop().time() + int(raw) / 1e3
        return None

    def _request_slo(self, headers: dict) -> int:
        """SLO class at admission (serve/tierroute.py): an explicit
        well-formed ``x-slo-class`` header wins; otherwise a tight
        ``x-request-deadline-ms`` budget (at or under
        serve.slo_cheap_deadline_ms) routes CHEAP. Malformed values are
        IGNORED like the deadline header — routing hints must never turn
        scored traffic into errors. Disarmed (the default) this is one
        boolean check."""
        if not self.slo_routing:
            return SLO_DEFAULT
        raw = headers.get("x-request-deadline-ms", "")
        deadline_ms = (
            float(raw)
            if raw and raw.isascii() and raw.isdigit() and int(raw) > 0
            else None
        )
        return resolve_slo_class(
            headers.get("x-slo-class", ""),
            deadline_ms,
            getattr(self.config, "slo_cheap_deadline_ms", 0.0),
        )

    def _request_id(self, headers: dict) -> str:
        """Honor a well-formed inbound ``x-request-id`` (so the caller's
        trace id correlates the two log events end to end — the reference
        only ever generates its own, `app/main.py:57`); mint one otherwise.
        The charset/length gate keeps log-injection text out of the
        structured stream."""
        inbound = headers.get("x-request-id", "")
        if inbound and self._REQUEST_ID_RE.match(inbound):
            return inbound
        return uuid.uuid4().hex

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        status: int,
        payload,
        content_type: str = "application/json",
        keep_alive: bool = True,
        request_id: str | None = None,
        extra_headers: dict[str, str] | None = None,
    ) -> None:
        if isinstance(payload, (dict, list)):
            body = _dumps(payload).encode()
        elif isinstance(payload, str):
            body = payload.encode()
        else:
            body = payload
        # Static head parts are precomputed bytes (_head_prefix); only the
        # per-response fields (length, request id) format here.
        head = [
            _head_prefix(status, content_type),
            b"content-length: %d\r\n" % len(body),
        ]
        if request_id:
            head.append(b"x-request-id: " + request_id.encode() + b"\r\n")
        if extra_headers:
            for name, value in extra_headers.items():
                head.append(f"{name}: {value}\r\n".encode())
        head.append(_KEEP_ALIVE_TAIL if keep_alive else _CLOSE_TAIL)
        head.append(body)
        writer.write(b"".join(head))
        await writer.drain()

    # -------------------------------------------------------------- routing
    async def _route(
        self,
        method: str,
        path: str,
        body: bytes,
        request_id: str | None = None,
        deadline: float | None = None,
        span=None,
        tenant_raw: str = "",
        slo: int = SLO_DEFAULT,
    ):
        if path == "/predict" and method == "POST":
            return await self._predict(
                body, request_id, deadline, span, tenant_raw, slo
            )
        if path.startswith("/debug/profile/") and method == "POST":
            return await self._profile(path.removeprefix("/debug/profile/"))
        if method == "GET":
            if path == "/":
                # Interactive Swagger UI (reference parity: FastAPI serves
                # its docs at `/`, `app/main.py:37`).
                from mlops_tpu.serve.openapi import SWAGGER_HTML

                return (
                    200,
                    SWAGGER_HTML.format(title=self.config.service_name),
                    "text/html",
                )
            if path == "/docs/plain":
                return 200, _DOCS_HTML.format(title=self.config.service_name), "text/html"
            if path == "/openapi.json":
                from mlops_tpu.serve.openapi import build_openapi

                if self._openapi is None:
                    self._openapi = build_openapi(self.config.service_name)
                return 200, self._openapi, "application/json"
            if path == "/healthz":
                return await self._healthz()
            if path == "/healthz/live":
                return 200, {"status": "alive"}, "application/json"
            if path == "/healthz/ready":
                if self._ready():
                    return 200, {"status": "ready"}, "application/json"
                return 503, {"status": "warming"}, "application/json"
            if path == "/metrics":
                return await self._metrics_endpoint()
        return 404, {"detail": "not found"}, "application/json"

