"""OpenAPI document generated from the pydantic wire models.

The reference gets interactive API docs for free from FastAPI — Swagger
UI served at `/` (`app/main.py:37`, ``docs_url="/"``). This framework's
server is hand-rolled, so the document is built here from the SAME
schema-generated pydantic models the validator uses: one source of truth
for validation, docs, and client generation.
"""

from __future__ import annotations

from typing import Any

import pydantic

from mlops_tpu.schema import LoanApplicant, ModelOutput
from mlops_tpu.version import __version__


def build_openapi(service_name: str) -> dict[str, Any]:
    """OpenAPI 3.1 document for the serving API."""
    request_schema = pydantic.TypeAdapter(list[LoanApplicant]).json_schema(
        ref_template="#/components/schemas/{model}"
    )
    response_schema = pydantic.TypeAdapter(ModelOutput).json_schema(
        ref_template="#/components/schemas/{model}"
    )
    components: dict[str, Any] = {}
    for schema in (request_schema, response_schema):
        components.update(schema.pop("$defs", {}))

    return {
        "openapi": "3.1.0",
        "info": {
            "title": service_name,
            "version": __version__,
            "description": (
                "TPU-native credit-default inference service: classifier "
                "+ drift monitor + outlier detector fused into one device "
                "dispatch per request batch."
            ),
        },
        "paths": {
            "/predict": {
                "post": {
                    "summary": "Score loan applicants",
                    "operationId": "predict",
                    "requestBody": {
                        "required": True,
                        "content": {
                            "application/json": {"schema": request_schema}
                        },
                    },
                    "responses": {
                        "200": {
                            "description": "Predictions, outlier flags, and per-feature batch drift",
                            "content": {
                                "application/json": {"schema": response_schema}
                            },
                        },
                        "422": {"description": "Request body failed validation"},
                        "413": {"description": "Batch exceeds the serving cap"},
                        "503": {
                            "description": (
                                "Load shed or deadline. Overload: the "
                                "admission queue for the request's bucket "
                                "class is full; the response carries a "
                                "Retry-After header (seconds) and the "
                                "request was NOT scored — retry after the "
                                "advertised delay. Deadline: the predict "
                                "exceeded serve.request_timeout_s "
                                "(no Retry-After header)."
                            ),
                            "headers": {
                                "Retry-After": {
                                    "description": (
                                        "Seconds to wait before retrying "
                                        "(present only on overload sheds)"
                                    ),
                                    "schema": {"type": "integer"},
                                }
                            },
                        },
                    },
                }
            },
            "/healthz/live": {
                "get": {
                    "summary": "Liveness probe",
                    "responses": {"200": {"description": "Process is up"}},
                }
            },
            "/healthz/ready": {
                "get": {
                    "summary": "Readiness probe (bundle loaded + jit warm)",
                    "responses": {
                        "200": {"description": "Ready"},
                        "503": {"description": "Still warming"},
                    },
                }
            },
            "/metrics": {
                "get": {
                    "summary": "Prometheus metrics",
                    "responses": {"200": {"description": "Metrics exposition"}},
                }
            },
        },
        "components": {"schemas": components},
    }


# Self-contained Swagger UI page (assets from the standard CDN — same
# approach FastAPI's bundled docs page uses).
SWAGGER_HTML = """<!doctype html>
<html>
<head>
  <title>{title}</title>
  <meta charset="utf-8"/>
  <link rel="stylesheet"
        href="https://cdn.jsdelivr.net/npm/swagger-ui-dist@5/swagger-ui.css">
</head>
<body>
<div id="swagger-ui"></div>
<script src="https://cdn.jsdelivr.net/npm/swagger-ui-dist@5/swagger-ui-bundle.js"></script>
<script>
  SwaggerUIBundle({{url: "/openapi.json", dom_id: "#swagger-ui"}});
</script>
</body>
</html>"""
