"""OpenAPI document generated from the pydantic wire models.

The reference gets interactive API docs for free from FastAPI — Swagger
UI served at `/` (`app/main.py:37`, ``docs_url="/"``). This framework's
server is hand-rolled, so the document is built here from the SAME
schema-generated pydantic models the validator uses: one source of truth
for validation, docs, and client generation.
"""

from __future__ import annotations

from typing import Any

import pydantic

from mlops_tpu.schema import LoanApplicant, ModelOutput
from mlops_tpu.version import __version__


def build_openapi(service_name: str) -> dict[str, Any]:
    """OpenAPI 3.1 document for the serving API."""
    request_schema = pydantic.TypeAdapter(list[LoanApplicant]).json_schema(
        ref_template="#/components/schemas/{model}"
    )
    response_schema = pydantic.TypeAdapter(ModelOutput).json_schema(
        ref_template="#/components/schemas/{model}"
    )
    components: dict[str, Any] = {}
    for schema in (request_schema, response_schema):
        components.update(schema.pop("$defs", {}))

    return {
        "openapi": "3.1.0",
        "info": {
            "title": service_name,
            "version": __version__,
            "description": (
                "TPU-native credit-default inference service: classifier "
                "+ drift monitor + outlier detector fused into one device "
                "dispatch per request batch."
            ),
        },
        "paths": {
            "/predict": {
                "post": {
                    "summary": "Score loan applicants",
                    "operationId": "predict",
                    "parameters": [
                        {
                            "name": "x-request-deadline-ms",
                            "in": "header",
                            "required": False,
                            "schema": {"type": "integer", "minimum": 1},
                            "description": (
                                "Optional per-request deadline budget in "
                                "milliseconds, measured from request "
                                "admission. The budget decrements across "
                                "admission -> encode -> queue wait -> "
                                "dispatch; any stage finding it spent "
                                "answers 504 WITHOUT doing the remaining "
                                "work (dead-work shedding), and it "
                                "tightens serve.request_timeout_s for "
                                "this request. Malformed values are "
                                "ignored (the header is a hint, not a "
                                "contract)."
                            ),
                        },
                        {
                            "name": "x-request-id",
                            "in": "header",
                            "required": False,
                            "schema": {
                                "type": "string",
                                "pattern": "^[A-Za-z0-9_-]{1,64}$",
                            },
                            "description": (
                                "Caller trace id, echoed on the response "
                                "and in both structured log events; "
                                "minted server-side when absent or "
                                "malformed."
                            ),
                        },
                        {
                            "name": "x-tenant",
                            "in": "header",
                            "required": False,
                            "schema": {
                                "type": "string",
                                "pattern": "^[A-Za-z0-9_-]{1,64}$",
                            },
                            "description": (
                                "Tenant name on a multi-tenant plane "
                                "(serve --tenants tenants.toml): routes "
                                "the request to that tenant's bundle, "
                                "bills its admission quota, and labels "
                                "its metrics/span records. Absent/empty "
                                "= the config-declared default tenant; "
                                "an UNKNOWN name answers 404 (never "
                                "silently billed to the default "
                                "tenant)."
                            ),
                        },
                    ],
                    "requestBody": {
                        "required": True,
                        "content": {
                            "application/json": {"schema": request_schema}
                        },
                    },
                    "responses": {
                        "200": {
                            "description": "Predictions, outlier flags, and per-feature batch drift",
                            "content": {
                                "application/json": {"schema": response_schema}
                            },
                        },
                        "404": {
                            "description": (
                                "Unknown tenant: the x-tenant header "
                                "names no declared tenant. Answered "
                                "before validation or any scoring work "
                                "— nothing was billed to any tenant's "
                                "quota or monitors."
                            )
                        },
                        "422": {"description": "Request body failed validation"},
                        "413": {"description": "Batch exceeds the serving cap"},
                        "503": {
                            "description": (
                                "Load shed (overload): the admission "
                                "queue for the request's bucket class is "
                                "full; the response carries a Retry-After "
                                "header (seconds) and the request was NOT "
                                "scored — retry after the advertised "
                                "delay. During an engine respawn "
                                "(brownout) the same contract applies "
                                "with Retry-After advertising the "
                                "respawn ETA. Deadline exhaustion is a "
                                "504, never a 503."
                            ),
                            "headers": {
                                "Retry-After": {
                                    "description": (
                                        "Seconds to wait before retrying "
                                        "(always present on sheds)"
                                    ),
                                    "schema": {"type": "integer"},
                                }
                            },
                        },
                        "504": {
                            "description": (
                                "Deadline exceeded: the request's "
                                "x-request-deadline-ms budget (or "
                                "serve.request_timeout_s) ran out. "
                                "Distinct from the shed 503: a 504'd "
                                "request MAY have been partially or "
                                "fully scored (the response was simply "
                                "late), so blind retries are not "
                                "idempotency-safe for side-effectful "
                                "callers; no Retry-After is advertised. "
                                "Requests whose budget expired before "
                                "dispatch are shed without device work "
                                "and counted in "
                                "mlops_tpu_deadline_expired_total."
                            )
                        },
                    },
                }
            },
            "/healthz": {
                "get": {
                    "summary": "SLO verdict (sloscope)",
                    "responses": {
                        "200": {
                            "description": (
                                "Serving: verdict 'ok', or 'degraded' "
                                "with the active alerts named (a "
                                "burning error budget means look, not "
                                "pull the instance)."
                            )
                        },
                        "503": {
                            "description": (
                                "verdict 'down': full engine outage or "
                                "never-ready."
                            )
                        },
                    },
                }
            },
            "/healthz/live": {
                "get": {
                    "summary": "Liveness probe",
                    "responses": {"200": {"description": "Process is up"}},
                }
            },
            "/healthz/ready": {
                "get": {
                    "summary": "Readiness probe (bundle loaded + jit warm)",
                    "responses": {
                        "200": {"description": "Ready"},
                        "503": {"description": "Still warming"},
                    },
                }
            },
            "/metrics": {
                "get": {
                    "summary": "Prometheus metrics",
                    "responses": {"200": {"description": "Metrics exposition"}},
                }
            },
        },
        "components": {"schemas": components},
    }


# Self-contained Swagger UI page (assets from the standard CDN — same
# approach FastAPI's bundled docs page uses).
SWAGGER_HTML = """<!doctype html>
<html>
<head>
  <title>{title}</title>
  <meta charset="utf-8"/>
  <link rel="stylesheet"
        href="https://cdn.jsdelivr.net/npm/swagger-ui-dist@5/swagger-ui.css">
</head>
<body>
<div id="swagger-ui"></div>
<script src="https://cdn.jsdelivr.net/npm/swagger-ui-dist@5/swagger-ui-bundle.js"></script>
<script>
  SwaggerUIBundle({{url: "/openapi.json", dom_id: "#swagger-ui"}});
</script>
</body>
</html>"""
