"""Inference engine: bundle -> warmed, bucketed, fused predict.

TPU serving mechanics (SURVEY.md SS7 "hard parts" — batch-1 latency):

- ONE compiled program per batch bucket (1, 8, 64, 256 by default): requests
  are padded up to the nearest bucket with a validity mask, so XLA never
  recompiles in steady state and drift/outlier statistics ignore padding.
- warmup compiles every bucket at startup (readiness gate — the reference
  has no readiness probe at all, `kubernetes/manifest.yml:1-54`).
- host work is minimal: string->id lookups and one float array build per
  request; everything else (classifier + monitors) is a single device
  dispatch.
- the device->host surface is ONE packed f32 buffer per request
  (predictions ‖ outlier flags ‖ drift — `ops/predict.py
  make_packed_predict_base`), its host copy started asynchronously at
  dispatch time, and the running monitor aggregate stays ON DEVICE
  (`monitor/state.py MonitorAccumulator`), read off the request path by
  `monitor_snapshot`.
"""

from __future__ import annotations

import bisect
import json
import logging
import threading
import time
from typing import Any

import jax
import numpy as np

from mlops_tpu import faults
from mlops_tpu.bundle.bundle import Bundle
from mlops_tpu.ops.gbm_tensor import (
    extract_gbm,
    make_gbm_grouped_base,
    make_gbm_packed_base,
    supports_gbm_tensorization,
    trace_context,
    x64_context,
)
from mlops_tpu.ops.predict import (
    _acc_donation,
    make_hybrid_predict_fn,
    make_packed_grouped_base,
    make_packed_predict_base,
    packed_layout,
)
from mlops_tpu.schema import SCHEMA, records_to_columns
from mlops_tpu.serve.tierroute import TIERS, tier_for_class

# Declared lock order, OUTERMOST FIRST — the single source of truth for
# both halves of tpulint Layer 3: the static analyzer
# (analysis/concurrency.py TPU401) checks every lexically nested
# acquisition against it, and the runtime sanitizer
# (analysis/lockcheck.py) asserts it on live thread schedules in the
# stress tests. ``_compile_lock`` may be held while the others are taken,
# never the reverse — the lifecycle hot swap (`swap_bundle`/`rollback`)
# nests ``_acc_lock`` under ``_compile_lock`` in exactly that order, and
# its critical section is pure ref assignment. ``_acc_lock`` and
# ``_totals_lock`` stay leaves below that: a blocking XLA compile or
# device fetch nested under the accumulator lock is exactly the PR 4
# stall this manifest exists to prevent.
TPULINT_LOCK_ORDER = {
    "InferenceEngine": ("_compile_lock", "_acc_lock", "_totals_lock")
}

logger = logging.getLogger("mlops_tpu.serve")


def _start_copy(tree: Any) -> None:
    """Begin the device->host copy of every array in ``tree`` WITHOUT
    blocking (``copy_to_host_async`` where the backend provides it): by
    the time the response path blocks in ``np.asarray`` the bytes are
    already moving — on a remote-attached chip this overlaps the transfer
    round trip with the host-side Python between dispatch and fetch."""

    def one(x):
        try:
            x.copy_to_host_async()
        except AttributeError:
            pass

    jax.tree_util.tree_map(one, tree)


def _pad_rows(
    cat: np.ndarray, num: np.ndarray, n: int, rows: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad ``n`` encoded rows up to ``rows`` with a validity mask — the
    one padding rule the target-bucket and degraded-bucket dispatches
    share (identical masking = identical statistics either way)."""
    pad = rows - n
    if pad:
        cat = np.pad(cat, ((0, pad), (0, 0)))
        num = np.pad(num, ((0, pad), (0, 0)))
    return cat, num, np.arange(rows) < n


def _key_tier(key: tuple) -> str | None:
    """Tier suffix of an exec-table key, None for the default tier:
    ``("bucket", rows[, tier])`` / ``("group", slots, rows[, tier])`` —
    the degraded-mode scans filter on it so a fallback never crosses
    tiers (a demoted request must pay padding, never different bits)."""
    n = 3 if key[0] == "group" else 2
    return key[n] if len(key) > n else None


def _entry_name(base: str, tier: str | None) -> str:
    """Telemetry entry label for a dispatch: the geometry, suffixed with
    the tier for NON-default tiers only — default-tier labels stay
    byte-identical to every earlier release's series."""
    return base if tier is None else f"{base}@{tier}"


class _ArraysHandle:
    """In-flight padded dispatch: the device output plus everything the
    fetch side needs to slice the packed buffer back into the response."""

    __slots__ = ("out", "n", "rows", "packed", "t0", "tier")

    def __init__(
        self, out: Any, n: int, rows: int, packed: bool, t0: float = 0.0,
        tier: str | None = None,
    ):
        self.out = out
        self.n = n
        self.rows = rows  # padded row count (bucket, or n at exact shape)
        self.packed = packed
        # Cost-ledger dispatch stamp (slo/ledger.py): perf_counter at
        # device enqueue, 0.0 when the ledger is disarmed — the fetch
        # side differences it into the entry's device-path seconds.
        self.t0 = t0
        self.tier = tier  # non-default serving tier, None = default

    def start_copy(self) -> None:
        _start_copy(self.out)


class _GroupHandle:
    """In-flight grouped dispatch (or the degenerate solo-path result)."""

    __slots__ = ("out", "sizes", "rows", "responses", "slots", "entry",
                 "t0", "tier")

    def __init__(self, out=None, sizes=None, rows=0, responses=None,
                 slots=0, t0=0.0, tier=None):
        self.out = out
        self.sizes = sizes
        self.rows = rows
        self.responses = responses  # set = degenerate path, already done
        self.slots = slots  # slot-bucket geometry actually dispatched
        self.t0 = t0  # cost-ledger dispatch stamp (see _ArraysHandle)
        self.tier = tier  # non-default serving tier, None = default
        # tracewire compiled-entry key, derived ONCE from the ints the
        # engine chose (degraded fallback included) — consumers carry the
        # ints (serve/ipc.py) or this string (the batcher's span entry),
        # never re-parse it.
        self.entry = (
            _entry_name(f"group_{slots}x{rows}", tier) if slots else None
        )

    def start_copy(self) -> None:
        if self.out is not None:
            _start_copy(self.out)

# Group geometry + response formatting live in the jax-free wire-contract
# module (serve/wire.py) so front-end processes can share them without
# this module's jax import; re-exported here because the batcher, the
# tests, and the compile-cache warmers have always imported them from the
# engine.
from mlops_tpu.serve.wire import (  # noqa: E402, F401  (re-exports)
    EMPTY_RESPONSE_BYTES,
    GROUP_ROW_BUCKET,
    GROUP_ROW_BUCKETS,
    GROUP_SLOT_BUCKETS,
    empty_response,
    encode_response,
    format_response,
)


class InferenceEngine:
    def __init__(
        self,
        bundle: Bundle,
        buckets: tuple[int, ...] = (1, 8, 64, 256),
        service_name: str = "credit-default-api",
        enable_grouping: bool = True,
        compile_cache=None,
        warmup_workers: int = 0,
        model_shards: int = 1,
        device_index: int | None = None,
        serve_tier: str = "exact",
        tier_routing: bool = False,
    ):
        self.bundle = bundle
        # Bundle turnover (mlops_tpu/lifecycle/): the generation counts
        # hot swaps (swap_bundle / rollback), starting at 1 for the
        # construction-time bundle; `_retired` is the one-deep history a
        # rollback restores. `_tee` is the lifecycle observation hook
        # (set_lifecycle_tee): called with each request's PRE-PADDING
        # encoded arrays on the dispatch path, feeding the sample
        # reservoir and the shadow mirror — it must never block and never
        # raise (the tee guards itself; a mirror bug must not 500 live
        # traffic).
        self.bundle_generation = 1
        # Grid turnover (mlops_tpu/autotune/): counts hot REGRIDS — swaps
        # (or rollbacks) whose candidate carried a different bucket set.
        # A plain promotion (same grid, new params) leaves it untouched,
        # so `mlops_tpu_grid_generation` moves only when the autotuner
        # (or an operator) actually re-gridded the plane.
        self.grid_generation = 1
        self._retired: tuple | None = None
        self._tee = None
        # tracewire shape telemetry (mlops_tpu/trace/shapes.py), armed by
        # `set_shape_stats` when trace.enabled: every dispatch records
        # (compiled entry, requested rows, padded rows). Disarmed = None =
        # one branch on the hot path (the faultline overhead discipline).
        self.shape_stats = None
        # Device-time cost ledger (mlops_tpu/slo/ledger.py), armed by
        # `set_cost_ledger`: per-entry dispatch->fetch seconds keyed by
        # entry + model fingerprint. Disarmed = None = one branch on the
        # dispatch path, one on the fetch path.
        self.cost_ledger = None
        self._cost_tag = ""
        if bundle.flavor == "doc":
            raise ValueError(
                "doc bundles score record HISTORIES, not single records — "
                "the HTTP predict contract does not apply; score offline "
                "via `predict-file data.train_path=<history csv>`"
            )
        self.buckets = sorted(buckets)
        self.max_bucket = self.buckets[-1]
        self.service_name = service_name
        # Persistent AOT executable cache (compilecache/): warmup probes it
        # before compiling, so a second process on the same box (deploy,
        # rollout, autoscale replica) deserializes in seconds instead of
        # recompiling for a minute. None = compile-only warmup.
        self.compile_cache = compile_cache
        self.warmup_workers = warmup_workers
        self.warmup_stats: dict[str, Any] = {}
        # AOT dispatch table: ("bucket", b) / ("group", slots, rows) ->
        # compiled executable for exactly that shape (filled by warmup).
        # Misses fall back to the bound jitted programs below, which
        # compile on demand — exactly the pre-cache behavior.
        self._exec: dict[tuple, Any] = {}
        temperature = bundle.temperature  # calibration (train/calibrate.py)
        # Defaults shared by every flavor (the flax branch below builds
        # the real mesh when model_shards > 1; sklearn has no device
        # params to shard and ignores the knobs). ``device_index`` is
        # the engine replica set's in-process placement (ISSUE 13):
        # when one engine process's visibility spans the whole fleet's
        # devices (a dev box, the forced-host-device sim), replica r
        # pins its state to ITS device slice instead of everyone
        # sharing device 0 — production multi-chip deployments scope
        # visibility per process instead (each replica's device 0 IS
        # its chip) and leave this None.
        self.model_shards = max(1, int(model_shards))
        self.device_index = device_index
        self._mesh = None
        self._replicated = None
        self._placement = None
        # Serving tier (ISSUE 17): the quantized student
        # (`ops/quant_kernel.py` — int8/bf16 params, Pallas-fused on TPU)
        # is a different (program, params, temperature) TRIPLE behind the
        # SAME dispatch machinery: the 7-arg packed signature, the AOT
        # table, the accumulator chain, degraded mode, and the lifecycle
        # locks are all tier-blind. "quant" demands the tier (raises when
        # the bundle lacks a GATED one — an explicit ask must never be
        # silently downgraded); "auto" takes it when admissible and logs
        # the fallback otherwise. Single-device by contract: the quant
        # params are a flat dict the partition rules don't cover.
        self.serve_tier = self._resolve_tier(serve_tier, bundle)
        # Per-request tier routing (ISSUE 19, serve/tierroute.py): the
        # DEFAULT tier keeps the historical attribute slots
        # (`_variables` / `_temperature` / the base jits / plain exec
        # keys); every OTHER gated tier this engine holds lives in
        # ``_tier_extra`` as a (variables, temperature, solo jit, group
        # jit) quadruple and dispatches through the SAME exec table
        # under tier-suffixed keys — one accumulator, one lock
        # discipline, one degraded-mode policy across all tiers.
        self.tier_routing = bool(tier_routing)
        self.default_tier = self.serve_tier
        self._tier_extra: dict[str, tuple] = {}
        self.gbm_geometry = None
        if bundle.flavor == "sklearn" and not supports_gbm_tensorization(
            bundle.estimator
        ):
            # CPU tree-ensemble floor (the rf family — unbinned deep
            # forests don't tensorize): host classifier + device
            # monitors. No grouped path — trees run on host threads
            # anyway (and no AOT table: the classifier is not an XLA
            # program). No device accumulator either: the server keeps
            # the seed's host-side metric fold for this flavor.
            self._predict = make_hybrid_predict_fn(
                bundle.estimator, bundle.monitor, temperature
            )
            self._predict_group = None
            self._accumulate = False
        elif bundle.flavor == "sklearn":
            # gbm-tensor tier (ISSUE 19, ops/gbm_tensor.py): the fitted
            # HistGBM ensemble lowers Hummingbird-style to padded
            # gather/compare tensor programs in the SAME packed 7-arg
            # contract as every flax family — so the sklearn floor rides
            # the AOT table, the device accumulator, grouping, degraded
            # mode, and the compile cache instead of host threads.
            # Single-device by construction (sklearn has no partition
            # rules; model_shards is ignored exactly as before).
            gbm_variables, self.gbm_geometry = extract_gbm(bundle.estimator)
            self.default_tier = "gbm"
            if device_index is not None:
                from jax.sharding import SingleDeviceSharding

                self._placement = SingleDeviceSharding(
                    jax.devices()[device_index]
                )
            with x64_context():
                # The tree tensors are f64 by the bit-parity contract —
                # committed under the x64 context or device_put would
                # silently narrow them (jax 0.4.x semantics).
                self._variables = (
                    jax.device_put(gbm_variables, self._placement)
                    if self._placement is not None
                    else jax.device_put(gbm_variables)
                )
            if self._placement is not None:
                self._monitor = jax.device_put(
                    bundle.monitor, self._placement
                )
            else:
                self._monitor = jax.device_put(bundle.monitor)
            with x64_context():
                # f64 temperature, unlike every other tier's f32: the
                # host hybrid divides logits by the FULL python float
                # (train/calibrate.py apply_temperature), and an f32
                # rounding of T shifts ~1/3 of tempered probabilities by
                # one ulp — bit-parity pins would fail.
                self._temperature = (
                    jax.device_put(np.float64(temperature), self._placement)
                    if self._placement is not None
                    else jax.device_put(np.float64(temperature))
                )
            donate = _acc_donation()
            depth = self.gbm_geometry.depth
            self._predict = jax.jit(  # tpulint: disable=TPU203
                make_gbm_packed_base(depth), donate_argnums=donate
            )
            self._predict_group = (
                jax.jit(  # tpulint: disable=TPU203
                    make_gbm_grouped_base(depth), donate_argnums=donate
                )
                if enable_grouping
                else None
            )
            self._accumulate = True
        else:
            # Partition-rule model sharding (ISSUE 13,
            # parallel/sharding.py): model_shards > 1 lays the params
            # out over a ('model',) mesh via the same regex rules the
            # TP train step uses — large families (moe experts,
            # transformer projections) SHARD instead of replicating,
            # while monitor/accumulator/temperature and the batch
            # inputs replicate. The packed programs are unchanged: jit
            # follows the committed shardings, and warmup bakes them
            # into the AOT artifacts (keyed by mesh shape, so sharded
            # and unsharded executables can never mix).
            quant = self.serve_tier == "quant"
            if quant:
                # The quant triple: int8/bf16 params + the tier's own
                # refit temperature (quantization shifts the logit scale;
                # `train/distill.py distill_quant_student`).
                serve_variables = bundle.quant_params
                temperature = bundle.quant_temperature
            else:
                serve_variables = bundle.variables
            if self.model_shards > 1:
                from mlops_tpu.parallel.sharding import (
                    param_shardings,
                    replicated,
                    serve_mesh,
                )

                self._mesh = serve_mesh(
                    self.model_shards, offset=device_index or 0
                )
                self._replicated = replicated(self._mesh)
                self._variables = jax.device_put(
                    bundle.variables,
                    param_shardings(self._mesh, bundle.variables),
                )
                self._monitor = jax.device_put(
                    bundle.monitor, self._replicated
                )
                self._temperature = jax.device_put(
                    np.float32(temperature), self._replicated
                )
            elif device_index is not None:
                # Unsharded but PINNED: the whole serving state lives on
                # this replica's own device (committed placement — jit
                # and the AOT artifacts follow it).
                from jax.sharding import SingleDeviceSharding

                self._placement = SingleDeviceSharding(
                    jax.devices()[device_index]
                )
                self._variables = jax.device_put(
                    serve_variables, self._placement
                )
                self._monitor = jax.device_put(
                    bundle.monitor, self._placement
                )
                self._temperature = jax.device_put(
                    np.float32(temperature), self._placement
                )
            else:
                # device_put ONCE: params/monitor/temperature are
                # per-call ARGUMENTS of the cached programs — host numpy
                # trees would re-pay the full host->device param
                # transfer on every request; committed device arrays
                # pass by reference.
                self._variables = jax.device_put(serve_variables)
                self._monitor = jax.device_put(bundle.monitor)
                self._temperature = jax.device_put(np.float32(temperature))
            # Base-form packed programs, jitted with the same 7-arg
            # convention as the AOT table entries — `_dispatch_fused`
            # AOT-lowers these for any shape warmup missed.
            donate = _acc_donation()
            # Warmed shapes never touch these jits (warmup fills the AOT
            # table through compilecache); they exist only so
            # `_compile_novel` can AOT-lower a shape warmup missed. The
            # tier picks the program family here, ONCE — every dispatch
            # below is tier-blind.
            if quant:
                from mlops_tpu.ops.quant_kernel import (
                    make_quant_grouped_base,
                    make_quant_packed_base,
                )

                predict_base = make_quant_packed_base()
                grouped_base = make_quant_grouped_base()
            else:
                predict_base = make_packed_predict_base(bundle.model)
                grouped_base = make_packed_grouped_base(bundle.model)
            self._predict = jax.jit(  # tpulint: disable=TPU203
                predict_base, donate_argnums=donate
            )
            self._predict_group = (
                jax.jit(  # tpulint: disable=TPU203
                    grouped_base,
                    donate_argnums=donate,
                )
                if enable_grouping
                else None
            )
            if self.tier_routing:
                # Commit every OTHER gated tier alongside the default
                # one — per-request routing needs them resident before
                # traffic, not behind a first-request device_put.
                self._tier_extra = self._build_extra_tiers(
                    bundle, enable_grouping, donate
                )
            self._accumulate = True
        if self._accumulate:
            # The accumulating flavors' shared serving state (flax
            # families and the gbm-tensor tier). Device-resident monitor
            # aggregate, threaded through every fused dispatch
            # (monitor/state.py MonitorAccumulator): the lock serializes
            # only the dispatch-order/ref-swap — executions chain on
            # device through the data dependency, the host never blocks
            # here.
            from mlops_tpu.monitor.state import init_accumulator

            self._acc = self._place_replicated(init_accumulator())
            self._acc_lock = threading.Lock()
            # Novel-shape compiles serialize here, never on _acc_lock: a
            # synchronous XLA compile under the accumulator lock would
            # stall every in-flight request, not just the novel one.
            self._compile_lock = threading.Lock()
            # Exact host-side running totals, folded from each fetched
            # window by `monitor_snapshot` (fetch-and-reset): left to
            # grow on device, the f32 counters would silently saturate
            # at 2^24 rows (~2 h at the benched request rate) where the
            # seed's Python-int /metrics totals could not.
            d = SCHEMA.num_categorical + SCHEMA.num_numeric
            self._totals: dict[str, Any] = {
                "rows": 0.0,
                "outliers": 0.0,
                "batches": 0.0,
                "drift_sum": np.zeros(d, np.float64),
                "drift_last": np.zeros(d, np.float64),
            }
            self._totals_lock = threading.Lock()
            # Degraded-mode dispatch counter (`_dispatch_padded` /
            # `dispatch_group_arrays`): requests served through a
            # larger-than-target warmed shape after a compile/cache
            # failure — exported as mlops_tpu_degraded_dispatch_total.
            self._degraded = 0
        self.ready = False

    def _build_extra_tiers(
        self, bundle: Bundle, enable_grouping: bool, donate
    ) -> dict[str, tuple]:
        """Commit the non-default gated tiers (tier_routing=True, flax
        flavors): an exact-default engine with a GATED quant student adds
        "quant"; a quant-default engine always retains its "exact"
        teacher (the accurate-class escape hatch). Each extra tier is a
        full (params, temperature, solo jit, group jit) quadruple on the
        same committed placement — `_dispatch_fused` reads it under the
        same lock hold as the default refs, so tier choice never changes
        the consistency story."""
        extra: dict[str, tuple] = {}
        others: list[str] = []
        if self.serve_tier == "exact":
            if (
                bundle.has_quant
                and bundle.quant_gates_passed
                and self.model_shards == 1
            ):
                others.append("quant")
        else:
            others.append("exact")
        for tier in others:
            if tier == "quant":
                from mlops_tpu.ops.quant_kernel import (
                    make_quant_grouped_base,
                    make_quant_packed_base,
                )

                variables = self._place_replicated(bundle.quant_params)
                temperature = self._place_replicated(
                    np.float32(bundle.quant_temperature)
                )
                solo_base = make_quant_packed_base()
                group_base = make_quant_grouped_base()
            else:
                variables = self._place_replicated(bundle.variables)
                temperature = self._place_replicated(
                    np.float32(bundle.temperature)
                )
                solo_base = make_packed_predict_base(bundle.model)
                group_base = make_packed_grouped_base(bundle.model)
            extra[tier] = (
                variables,
                temperature,
                jax.jit(  # tpulint: disable=TPU203
                    solo_base, donate_argnums=donate
                ),
                jax.jit(  # tpulint: disable=TPU203
                    group_base, donate_argnums=donate
                )
                if enable_grouping
                else None,
            )
        return extra

    def _resolve_tier(self, serve_tier: str, bundle: Bundle) -> str:
        """Resolve the requested serving tier against what the bundle can
        admissibly serve. "quant" is a demand (raise rather than silently
        serve different bits than asked for); "auto" is a preference (take
        the quant tier when gated and single-device, log the fallback)."""
        if serve_tier not in ("exact", "quant", "auto"):
            raise ValueError(
                f"serve_tier must be 'exact', 'quant' or 'auto', "
                f"got {serve_tier!r}"
            )
        if serve_tier == "exact":
            return "exact"
        admissible, why = True, ""
        if bundle.flavor == "sklearn":
            admissible, why = False, "sklearn bundles have no quant tier"
        elif not bundle.has_quant:
            admissible, why = False, "bundle carries no quant params"
        elif not bundle.quant_gates_passed:
            admissible, why = False, (
                "quant tier failed (or was never graded by) the promotion "
                "gates — lifecycle/promote.py quant_tier_gates"
            )
        elif self.model_shards > 1:
            admissible, why = False, (
                "quant tier is single-device; model_shards > 1 shards the "
                "exact params only"
            )
        if admissible:
            return "quant"
        if serve_tier == "quant":
            raise ValueError(f"serve_tier='quant' refused: {why}")
        logger.info("serve_tier='auto' falling back to exact tier: %s", why)
        return "exact"

    def _place_replicated(self, tree: Any) -> Any:
        """Device-put a host tree onto the engine's committed placement:
        replicated over the serve mesh when sharding is on, this
        replica's pinned device when one was assigned (every fresh
        accumulator must land on the SAME device set as the committed
        params, or the fused dispatch would mix committed device sets),
        plain default-device placement otherwise."""
        sharding = getattr(self, "_replicated", None) or getattr(
            self, "_placement", None
        )
        if sharding is not None:
            return jax.device_put(tree, sharding)
        return jax.device_put(tree)

    @property
    def supports_grouping(self) -> bool:
        return self._predict_group is not None

    @property
    def available_tiers(self) -> tuple[str, ...]:
        """The gated tiers this engine can dispatch per-request, cheapest
        -> most accurate (`tierroute.TIERS` order restricted to what is
        committed). Single-tier engines return a 1-tuple — routing then
        collapses to the default tier for every class."""
        held = {self.default_tier, *self._tier_extra}
        return tuple(t for t in TIERS if t in held)

    def route_tier(self, slo_class: int) -> str | None:
        """SLO class -> the tier that serves it on THIS engine; None
        means the default tier (plain un-suffixed exec keys — the
        historical dispatch, bit-for-bit). The engine owns this mapping
        so the wire carries only the CLASS: front ends don't know which
        tiers a bundle gates, and the ring's crash replay re-derives the
        identical tier from the class tag in shm."""
        tier = tier_for_class(
            self.available_tiers, self.default_tier, slo_class
        )
        return None if tier == self.default_tier else tier

    @property
    def monitor_accumulating(self) -> bool:
        """True when the fused programs fold the monitor aggregate on
        device (`monitor_snapshot` is then the telemetry read path)."""
        return self._accumulate

    @property
    def degraded_dispatch_total(self) -> int:
        """Requests served through a degraded (larger-than-target warmed)
        shape after a compile/cache failure — the telemetry read for the
        mlops_tpu_degraded_dispatch_total counter."""
        if not self._accumulate:
            return 0
        with self._totals_lock:
            return self._degraded

    def _count_degraded(self) -> None:
        with self._totals_lock:
            self._degraded += 1

    # ------------------------------------------------------------- warmup
    def warmup(self) -> None:
        """Ready every bucket size (and group shape) before traffic.

        Flax flavors warm ahead-of-time through `compilecache/warmup.py`:
        probe the persistent cache -> deserialize hits, compile misses IN
        PARALLEL (XLA compilation releases the GIL; a small thread pool
        over shapes) -> persist -> execute each program once on zeros (pay
        first-dispatch allocation; fail loudly on an artifact that loads
        but cannot run). ``warmup_stats`` records the wall time plus the
        cache's hit/miss/bypass counts and per-program compile vs
        deserialize seconds.
        """
        import time

        t0 = time.perf_counter()
        if not self._accumulate:
            # Host-hybrid floor (rf): no AOT table — execute each bucket
            # once so the jitted monitors compile before traffic.
            for bucket in self.buckets:
                cat = np.zeros((bucket, SCHEMA.num_categorical), np.int32)
                num = np.zeros((bucket, SCHEMA.num_numeric), np.float32)
                mask = np.ones((bucket,), bool)
                jax.block_until_ready(self._predict(cat, num, mask)["outliers"])
            self.ready = True
            self.warmup_stats = {
                "warmup_s": round(time.perf_counter() - t0, 3),
                "programs": len(self.buckets),
                "cache": None,
            }
            return

        from mlops_tpu.compilecache.warmup import (
            default_workers,
            run_jobs,
            serve_gbm_group_jobs,
            serve_gbm_jobs,
            serve_group_jobs,
            serve_predict_jobs,
            serve_quant_group_jobs,
            serve_quant_jobs,
        )

        bundle = self.bundle
        # Replica placement rides into the AOT artifacts: lowered
        # layouts follow the committed shardings, and a pinned/offset
        # device assignment joins the CACHE KEY (device_tag) — an
        # executable compiled for replica 0's device must never be
        # deserialized against params committed to replica 1's.
        device_tag = (
            f"@dev{self.device_index}" if self.device_index is not None
            else ""
        )
        grid = [
            (slots, rows)
            for rows in GROUP_ROW_BUCKETS
            for slots in GROUP_SLOT_BUCKETS
        ]
        if self.default_tier == "gbm":
            # The gbm-tensor tier's own entry family (cache ids
            # serve-predict-gbm-*): the tree tensors are the params tree,
            # and lowering runs inside the x64 context (the job carries
            # an x64-wrapping jitted — compilecache/warmup.py).
            jobs = serve_gbm_jobs(
                self._variables,  # the committed f64 tree tensors
                self._monitor,
                tuple(self.buckets),
                geometry=self.gbm_geometry,
                temperature=bundle.temperature,
                placement=self._placement,
                device_tag=device_tag,
            )
            if self._predict_group is not None:
                jobs += serve_gbm_group_jobs(
                    self._variables,
                    self._monitor,
                    grid,
                    geometry=self.gbm_geometry,
                    temperature=bundle.temperature,
                    placement=self._placement,
                    device_tag=device_tag,
                )
        elif self.serve_tier == "quant":
            # The quant tier's own entry family (distinct cache ids:
            # serve-predict-quant-*): same shapes, same dispatch-table
            # keys, different programs + params tree.
            jobs = serve_quant_jobs(
                self._variables,  # the committed quant tree
                self._monitor,
                tuple(self.buckets),
                temperature=bundle.quant_temperature,
                placement=self._placement,
                device_tag=device_tag,
            )
            if self._predict_group is not None:
                jobs += serve_quant_group_jobs(
                    self._variables,
                    self._monitor,
                    grid,
                    temperature=bundle.quant_temperature,
                    placement=self._placement,
                    device_tag=device_tag,
                )
        else:
            jobs = serve_predict_jobs(
                bundle.model,
                bundle.model_config,
                self._variables,  # device-resident (init): avals identical,
                self._monitor,  # and the execute-once pass skips a transfer
                tuple(self.buckets),
                temperature=bundle.temperature,
                mesh=self._mesh,  # sharded layouts bake into the artifacts
                placement=self._placement,
                device_tag=device_tag,
            )
            if self._predict_group is not None:
                jobs += serve_group_jobs(
                    bundle.model,
                    bundle.model_config,
                    self._variables,
                    self._monitor,
                    grid,
                    temperature=bundle.temperature,
                    mesh=self._mesh,
                    placement=self._placement,
                    device_tag=device_tag,
                )
        # Extra-tier warmup (tier_routing): every non-default gated tier
        # warms its OWN job family into the same table under
        # tier-suffixed keys — per-request routing must never pay a
        # first-request compile for a tier the config promised.
        for tier, (variables, _, _, group_jit) in self._tier_extra.items():
            if tier == "quant":
                extra = serve_quant_jobs(
                    variables, self._monitor, tuple(self.buckets),
                    temperature=bundle.quant_temperature,
                    placement=self._placement, device_tag=device_tag,
                )
                if group_jit is not None:
                    extra += serve_quant_group_jobs(
                        variables, self._monitor, grid,
                        temperature=bundle.quant_temperature,
                        placement=self._placement, device_tag=device_tag,
                    )
            else:
                extra = serve_predict_jobs(
                    bundle.model, bundle.model_config, variables,
                    self._monitor, tuple(self.buckets),
                    temperature=bundle.temperature,
                    placement=self._placement, device_tag=device_tag,
                )
                if group_jit is not None:
                    extra += serve_group_jobs(
                        bundle.model, bundle.model_config, variables,
                        self._monitor, grid,
                        temperature=bundle.temperature,
                        placement=self._placement, device_tag=device_tag,
                    )
            for job in extra:
                job.meta["tier"] = tier
            jobs += extra
        for job, fn in run_jobs(
            jobs, cache=self.compile_cache, workers=self.warmup_workers
        ):
            if "bucket" in job.meta:
                key = ("bucket", job.meta["bucket"])
            else:
                key = ("group", job.meta["slots"], job.meta["rows"])
            if job.meta.get("tier"):
                key = key + (job.meta["tier"],)
            # Under _compile_lock (tpulint TPU402): the server binds its
            # socket FIRST and warms concurrently (serve/server.py _serve),
            # so live requests can race this loop — an unlocked table
            # write could interleave with `_compile_novel` double-compiling
            # the same key it is about to install. Taken per write, never
            # across run_jobs: holding it for the whole warmup would stall
            # a novel-shape request until every program compiled.
            with self._compile_lock:
                self._exec[key] = fn
        self.ready = True
        self.warmup_stats = {
            "warmup_s": round(time.perf_counter() - t0, 3),
            "programs": len(jobs),
            "workers": default_workers(len(jobs), self.warmup_workers),
            "cache": (
                self.compile_cache.stats()
                if self.compile_cache is not None
                else None
            ),
        }

    def _dispatch_fused(self, key: tuple, *batch, tier: str | None = None):
        """Dispatch one fused packed call and thread the monitor
        accumulator through it — the ONE critical section shared by the
        solo and grouped paths.

        Warmed shapes dispatch through the AOT table; a novel shape
        (oversized request, unwarmed group geometry) is AOT-compiled into
        the table FIRST, outside the accumulator lock, so warmed traffic
        keeps flowing while it compiles.

        The lock covers only the (read exec entry + serving refs ->
        dispatch -> swap new acc ref) window, which is an ASYNC enqueue —
        concurrent request threads serialize the accumulator chain's
        ORDER here while the executions overlap on device exactly as
        before (the chain is a data dependency, not a host wait).

        BIT-STABILITY across hot swaps (lifecycle/promote.py): the exec
        entry, params, monitor, and temperature are all read under the
        SAME ``_acc_lock`` hold that `swap_bundle` mutates them under, so
        a request in flight during a promotion computes its whole answer
        from exactly one bundle generation — never new params through an
        old program or vice versa. Returns the packed output array; the
        new accumulator stays device-resident.

        ``tier`` (None = default) selects which committed (params,
        temperature) pair feeds the program — ``key`` already carries the
        matching suffix. All tiers thread the ONE accumulator: the
        monitors are f32 on every tier by contract, so the fold chain is
        tier-blind."""
        while True:
            with self._acc_lock:
                fn = self._exec.get(key)
                if fn is not None:
                    if tier is None:
                        variables = self._variables
                        temperature = self._temperature
                    else:
                        variables, temperature = self._tier_extra[tier][:2]
                    acc = self._acc
                    out, new_acc = fn(
                        variables, self._monitor, acc, temperature, *batch,
                    )
                    self._acc = new_acc
                    return out
            # Miss: compile outside the accumulator lock, then retry the
            # consistent-snapshot dispatch (a swap may have replaced the
            # table meanwhile; the loop re-reads everything together).
            self._compile_novel(key, batch, tier=tier)

    def _compile_novel(self, key: tuple, batch, tier: str | None = None):
        """AOT-compile a shape warmup missed and cache it in the dispatch
        table. Double-checked under ONE shared lock: concurrent first
        requests for the same shape compile once, and warmed traffic
        never waits here — but concurrent DIFFERENT novel shapes do
        serialize on this lock (novel shapes are rare offline/oversized
        traffic; per-key locks aren't worth the bookkeeping). The base
        jitted program is looked up from ``self`` INSIDE the lock so the
        lowering, the params it lowers against, and the table it installs
        into all belong to one bundle generation (`swap_bundle` takes
        this lock first)."""
        from mlops_tpu.monitor.state import abstract_accumulator

        # Injection point (mlops_tpu/faults): a raise here models a
        # runtime compile/cache failure — callers degrade to the next
        # larger warmed shape instead of 500ing (`_dispatch_padded`).
        faults.fire("serve.engine.compile")
        with self._compile_lock:
            fn = self._exec.get(key)
            if fn is None:
                if tier is None:
                    jitted = (
                        self._predict if key[0] == "bucket"
                        else self._predict_group
                    )
                    variables = self._variables
                    temperature = self._temperature
                else:
                    variables, temperature, solo, group = (
                        self._tier_extra[tier]
                    )
                    jitted = solo if key[0] == "bucket" else group
                # The sync XLA compile DOES block this lock — that is the
                # design: _compile_lock exists precisely to serialize novel
                # compiles away from _acc_lock (where the same compile once
                # stalled every in-flight request). Warmed traffic never
                # touches this lock on its hot path. The lowering runs in
                # the serving tier's trace context (x64 for gbm-tensor —
                # thread-local, so concurrent f32 dispatches are untouched).
                with trace_context(tier or self.default_tier):
                    fn = jitted.lower(  # tpulint: disable=TPU403
                        variables,
                        self._monitor,
                        abstract_accumulator(),
                        temperature,
                        *batch,
                    ).compile()
                self._exec[key] = fn
        return fn

    def adopt_executables(self, donor: "InferenceEngine") -> None:
        """Share a WARMED architecture-twin's compiled entries instead of
        warming (mlops_tpu/tenancy/registry.py): the packed serving
        programs take params/monitor/temperature as ARGUMENTS, so one
        executable serves any tenant whose bundle matches the donor's
        abstract signature — this engine keeps its OWN state refs
        (`_dispatch_fused` reads them per dispatch) while the exec table,
        the base jits, and crucially the donor's ``_compile_lock`` are
        adopted BY REFERENCE. Sharing the lock is load-bearing: twin
        tenants' concurrent novel-shape compiles must serialize on the
        one lock guarding the one shared table (separate locks over a
        shared dict would race `_compile_novel`'s double-check). A later
        `swap_bundle` on this tenant re-points only ITS refs at the
        candidate's table — the donor and every other twin keep serving
        the shared entries untouched (per-tenant lifecycle isolation)."""
        if not self._accumulate or not donor._accumulate:
            raise ValueError(
                "executable adoption requires device-accumulating engines "
                "on both sides — the host-hybrid flavor (rf) has no "
                "shareable compiled entries"
            )
        if not donor.ready:
            raise ValueError("donor engine is not warmed")
        # Adoption runs pre-traffic (registry warmup, starting thread),
        # but the refs it swaps are the same ones swap_bundle guards —
        # hold the declared _compile_lock -> _acc_lock order anyway so
        # every write site of these fields shares one discipline. The
        # lock handoff itself happens under the OLD lock (nobody else
        # can hold it before the fleet serves).
        with self._compile_lock:
            with self._acc_lock:
                self._exec = donor._exec
                self._predict = donor._predict
                self._predict_group = donor._predict_group
                self._compile_lock = donor._compile_lock
        self.ready = True
        self.warmup_stats = {
            "warmup_s": 0.0,
            "programs": len(donor._exec),
            "mode": "shared",
            "cache": None,
        }

    def set_shape_stats(self, stats) -> None:
        """Install (or clear, with None) the tracewire shape recorder: a
        `trace/shapes.ShapeStats` fed (entry, requested_rows, padded_rows)
        per dispatch. The recorder owns its cheapness (a leaf-lock counter
        add); the engine calls it bare on the dispatch path."""
        self.shape_stats = stats

    @staticmethod
    def _model_tag(bundle: Bundle) -> str:
        """The cost ledger's model dimension: the same model-config
        fingerprint the compile cache hashes into its keys
        (compilecache/keys.py), shortened for the label/shm-key budget.
        Two engines whose architectures match share compiled programs
        (tenancy adoption) and correctly share ledger entries; a
        promotion to a DIFFERENT architecture lands in fresh entries."""
        from mlops_tpu.compilecache.keys import model_fingerprint

        return model_fingerprint(bundle.model_config)[:8]

    def set_cost_ledger(self, ledger) -> None:
        """Install (or clear, with None) the device-time cost ledger
        (`slo/ledger.CostLedger`): every packed dispatch accounts
        (entry, requested rows, padded rows, dispatch->fetch seconds)
        under ``<entry>@<model-tag>``. Disarmed = None = one branch on
        the dispatch path and one on the fetch path (the faultline
        overhead discipline; bench key ``slo_overhead_pct``)."""
        if ledger is not None:
            self._cost_tag = self._model_tag(self.bundle)
        self.cost_ledger = ledger

    # ----------------------------------------------------- bundle turnover
    def set_lifecycle_tee(self, tee) -> None:
        """Install (or clear, with None) the lifecycle observation hook:
        a callable ``tee(cat_ids, numeric)`` invoked with each request's
        pre-padding encoded arrays on the dispatch path. The tee OWNS its
        cheapness and safety (bounded non-blocking enqueue, internal
        try/except) — the engine calls it bare on the hot path."""
        self._tee = tee

    def swap_bundle(self, candidate: "InferenceEngine") -> int:
        """Hot-promote a warmed candidate engine's bundle IN PLACE with
        zero downtime (lifecycle/promote.py): exec table + params +
        monitor + temperature + base jits ref-swap under the existing
        ``_compile_lock`` -> ``_acc_lock`` discipline (the declared
        TPULINT_LOCK_ORDER). Everything swapped is already device-resident
        on the candidate engine, so the critical section is pure ref
        assignment — no transfer, no compile, no fetch ever holds these
        locks (tpulint TPU403 stays clean by construction).

        Requests racing the swap are bit-stable: `_dispatch_fused` reads
        the same refs under the same ``_acc_lock`` hold, so each
        response's COMPUTE (program + params + monitor + temperature) is
        exactly one bundle generation. The host-side encode stage reads
        ``self.bundle.preprocessor`` before dispatch, outside these
        locks — identical across generations in the default lifecycle
        flow (``lifecycle.refit_preprocessor=false``, and forced false on
        the ring plane, where the fork-time preprocessor is the encode
        contract), so the one-generation guarantee is unconditional
        there; with an opted-in refit, a request already past encode when
        the swap lands scores old-stats-encoded rows against the new
        generation for that instant. The outgoing state is retained
        (one-deep) for `rollback`. Returns the new generation."""
        if not self._accumulate or not candidate._accumulate:
            raise ValueError(
                "hot swap requires device-accumulating engines on both "
                "sides — the host-hybrid flavor (rf) redeploys instead"
            )
        if self.supports_grouping and not candidate.supports_grouping:
            raise ValueError(
                "candidate engine lacks the grouped path the live engine "
                "serves — build it with enable_grouping=True"
            )
        if candidate.max_bucket < self.max_bucket:
            # The front ends clamp max_batch against max_bucket at START
            # (server.py / the ring slab geometry) — a swap that shrinks
            # coverage would admit requests no warmed entry can hold.
            # Regrids may re-tile below the ceiling, never lower it.
            raise ValueError(
                f"candidate max_bucket {candidate.max_bucket} < live "
                f"{self.max_bucket}: a swap may never shrink shape "
                "coverage below the admission ceiling"
            )
        with self._compile_lock:
            with self._acc_lock:
                self._retired = (
                    self.bundle, self._variables, self._monitor,
                    self._temperature, self._exec, self._predict,
                    self._predict_group, self.buckets, self.max_bucket,
                    self._tier_extra, self.default_tier, self.gbm_geometry,
                )
                regrid = candidate.buckets != self.buckets
                self.bundle = candidate.bundle
                self._variables = candidate._variables
                self._monitor = candidate._monitor
                self._temperature = candidate._temperature
                self._exec = candidate._exec
                self._predict = candidate._predict
                self._predict_group = candidate._predict_group
                self.buckets = candidate.buckets
                self.max_bucket = candidate.max_bucket
                # Tier routing state swaps with the bundle it describes:
                # the candidate's gated extra tiers (and, for gbm-tensor
                # bundles, the traversal geometry) belong to the NEW
                # generation's params, never the old one's.
                self._tier_extra = candidate._tier_extra
                self.default_tier = candidate.default_tier
                self.gbm_geometry = candidate.gbm_geometry
                self.bundle_generation += 1
                if regrid:
                    self.grid_generation += 1
        if self.cost_ledger is not None:
            # Re-key the ledger to the promoted architecture (outside the
            # locks: hashing a config dict must not extend the swap's
            # critical section; the attr store is atomic, and at most a
            # dispatch already in flight bills the outgoing tag).
            self._cost_tag = self._model_tag(self.bundle)
        return self.bundle_generation

    def rollback(self) -> int:
        """Instantly restore the previous bundle (same ref-swap, same
        locks, same bit-stability). The states EXCHANGE, so a rollback is
        itself rollback-able (roll forward again in one call). Raises if
        no swap ever happened."""
        if self._retired is None:
            raise ValueError("no retired bundle to roll back to")
        with self._compile_lock:
            with self._acc_lock:
                retired = self._retired
                self._retired = (
                    self.bundle, self._variables, self._monitor,
                    self._temperature, self._exec, self._predict,
                    self._predict_group, self.buckets, self.max_bucket,
                    self._tier_extra, self.default_tier, self.gbm_geometry,
                )
                regrid = retired[7] != self.buckets
                (self.bundle, self._variables, self._monitor,
                 self._temperature, self._exec, self._predict,
                 self._predict_group, self.buckets, self.max_bucket,
                 self._tier_extra, self.default_tier,
                 self.gbm_geometry) = retired
                self.bundle_generation += 1
                if regrid:
                    self.grid_generation += 1
        if self.cost_ledger is not None:
            self._cost_tag = self._model_tag(self.bundle)  # see swap_bundle
        return self.bundle_generation

    def seed_monitor_totals(
        self,
        rows: float,
        outliers: float,
        batches: float,
        drift_sum,
        drift_last,
    ) -> None:
        """Install absolute monitor totals from a previous engine
        incarnation (ISSUE 11 — the shm mon block survives an engine
        ``kill -9``; the respawned process seeds its exact host-side f64
        totals from it so `monitor_snapshot` — and therefore every
        exported counter — stays MONOTONE across the respawn instead of
        restarting from zero). The accumulator window the dead process
        never fetched is gone (bounded by the telemetry cadence) and is
        counted by the caller in ``monitor_rows_lost_total``, never
        silently absorbed."""
        if not self._accumulate:
            return
        # Materialize the host copies OUTSIDE the lock (TPU403: the
        # critical section is ref assignment only, like monitor_snapshot).
        seeded_sum = np.array(drift_sum, dtype=np.float64)
        seeded_last = np.array(drift_last, dtype=np.float64)
        with self._totals_lock:
            t = self._totals
            t["rows"] = float(rows)
            t["outliers"] = float(outliers)
            t["batches"] = float(batches)
            t["drift_sum"] = seeded_sum
            t["drift_last"] = seeded_last

    def monitor_snapshot(self) -> dict[str, Any]:
        """ONE device->host fetch of the monitor aggregate — the telemetry
        read path (`serve/server.py` calls it every K requests / T
        seconds, and on /metrics scrapes), OFF the request path.

        Fetch-and-RESET: a fresh zero accumulator is swapped in under the
        lock and the fetched window is folded into exact host-side f64
        totals. Left to grow on device, the f32 counters would silently
        stop incrementing at 2^24 rows; windows stay orders of magnitude
        below that (the server fetches every <=512 requests / 2 s) and the
        f64 totals are exact to 2^53. The swap also makes the fetched
        buffers donation-safe — once replaced, no later dispatch can
        donate them — so no defensive on-device copy is needed."""
        if not self._accumulate:
            return {}
        from mlops_tpu.monitor.state import init_accumulator, merge_accumulators

        with self._acc_lock:
            window = self._acc
            self._acc = self._place_replicated(init_accumulator())
        try:
            host = jax.device_get(window)  # blocks OUTSIDE the dispatch lock
        except Exception:
            # Transient fetch failure (remote-chip tunnel error): the window
            # was already swapped out, so fold it BACK into the live
            # accumulator — the counts must be delayed, never dropped.
            # (merge is an eager device enqueue; reads window + the current
            # acc under the lock, so no dispatch can donate either mid-merge.)
            with self._acc_lock:
                self._acc = merge_accumulators(window, self._acc)
            raise
        # Host numpy work (dtype casts, rounding, dict building) stays
        # OUTSIDE the totals lock (tpulint TPU403): the critical section
        # is only the counter updates plus alias grabs. Aliasing out is
        # safe because the drift arrays are REPLACED under the lock, never
        # mutated in place — a snapshot read here can't be half-updated by
        # a concurrent fold.
        window_batches = float(host.batches)
        window_drift_sum = np.asarray(host.drift_sum, dtype=np.float64)
        window_drift_last = np.asarray(host.drift_last, dtype=np.float64)
        with self._totals_lock:
            t = self._totals
            t["rows"] += float(host.rows)
            t["outliers"] += float(host.outliers)
            t["batches"] += window_batches
            t["drift_sum"] = t["drift_sum"] + window_drift_sum
            if window_batches:
                t["drift_last"] = window_drift_last
            rows, outliers, batches = t["rows"], t["outliers"], t["batches"]
            drift_sum, drift_last = t["drift_sum"], t["drift_last"]
        drift_mean = drift_sum / max(batches, 1.0)
        return {
            "rows": rows,
            "outliers": outliers,
            "batches": batches,
            "drift_last": dict(
                zip(SCHEMA.feature_names, drift_last.round(6).tolist())
            ),
            "drift_mean": dict(
                zip(SCHEMA.feature_names, drift_mean.round(6).tolist())
            ),
            # UNROUNDED cumulative per-feature sums, schema order — the
            # lifecycle trigger policy differences consecutive snapshots
            # into windows, and reconstructing the sum from the rounded
            # means above would accumulate up to 5e-7 * batches of error
            # (unbounded over a long-lived server). The gauges keep their
            # rounded display values; windowing reads this.
            "drift_sum": drift_sum.tolist(),
        }

    # -------------------------------------------------------------- predict
    def _normalize_tier(self, tier: str | None) -> str | None:
        """Dispatch-entry tier normalization: None and the default tier
        both mean the plain un-suffixed dispatch; anything else must be a
        committed extra tier (routing never invents a tier — a typo'd
        demand fails loudly, exactly like serve_tier='quant' at init)."""
        if tier is None or tier == self.default_tier:
            return None
        if not self._accumulate or tier not in self._tier_extra:
            raise ValueError(
                f"tier {tier!r} is not committed on this engine "
                f"(available: {self.available_tiers})"
            )
        return tier

    def predict_records(
        self, records: list[dict[str, Any]], span=None,
        tier: str | None = None,
    ) -> dict[str, Any]:
        """Validated records -> reference response dict (`app/model.py:64-70`).
        ``span`` (tracewire, `trace/span.Span`) gets the engine-side stage
        stamps — encode / dispatch / device_fetch — when tracing is armed;
        None (the default) costs two branches."""
        columns = records_to_columns(records)
        ds = self.bundle.preprocessor.encode(columns)
        if span is not None:
            span.stamp("encode")
        return self.predict_arrays(ds.cat_ids, ds.numeric, span=span, tier=tier)

    def predict_records_wire(
        self, records: list[dict[str, Any]], span=None,
        tier: str | None = None,
    ) -> bytes:
        """`predict_records` straight to wire bytes: the whole
        encode→dispatch→fetch→json pipeline stays in the executor thread,
        so the event loop only ever writes pre-encoded bytes (the
        encode-bound residue the bench's http_vs_engine_ratio measured)."""
        columns = records_to_columns(records)
        ds = self.bundle.preprocessor.encode(columns)
        if span is not None:
            span.stamp("encode")
        handle = self.dispatch_arrays(ds.cat_ids, ds.numeric, tier=tier)
        if handle is None:
            return EMPTY_RESPONSE_BYTES
        if span is not None:
            span.stamp("dispatch")
            span.entry = _entry_name(f"bucket_{handle.rows}", handle.tier)
        handle.start_copy()
        response = self.fetch_arrays_wire(handle)
        if span is not None:
            span.stamp("device_fetch")
        return response

    def predict_arrays(
        self, cat_ids: np.ndarray, numeric: np.ndarray, span=None,
        tier: str | None = None,
    ) -> dict[str, Any]:
        handle = self.dispatch_arrays(cat_ids, numeric, tier=tier)
        if handle is None:
            # Empty request: nothing to score, no drift signal (an empty
            # batch must not poison the drift gauges with statistic=1).
            return empty_response()
        if span is not None:
            span.stamp("dispatch")
            span.entry = _entry_name(f"bucket_{handle.rows}", handle.tier)
        handle.start_copy()
        response = self.fetch_arrays(handle)
        if span is not None:
            span.stamp("device_fetch")
        return response

    def dispatch_arrays(
        self, cat_ids: np.ndarray, numeric: np.ndarray,
        tier: str | None = None,
    ) -> _ArraysHandle | None:
        """Pad to the bucket and fire the device dispatch WITHOUT waiting
        for (or fetching) the result: returns a handle whose ``start_copy``
        begins the async D2H and whose ``fetch_arrays`` blocks. None for
        the empty request (no device work at all). ``tier`` selects a
        committed non-default serving tier (per-request SLO routing)."""
        tier = self._normalize_tier(tier)
        n = cat_ids.shape[0]
        if n == 0:
            return None
        tee = self._tee
        if tee is not None:
            # Lifecycle observation (reservoir feed + shadow mirror):
            # pre-padding arrays, bounded non-blocking enqueue inside the
            # tee — never a hot-path stall.
            tee(cat_ids, numeric)
        # Injection point (mlops_tpu/faults): raise = device error (the
        # caller's 500 contract); delay = engine stall (the deadline 504
        # contract). Fired pre-padding, outside every lock.
        faults.fire("serve.engine.dispatch")
        bucket = self._bucket_for(n)
        rows = bucket if bucket is not None else n
        if not self._accumulate:
            # sklearn hybrid: host classifier + device monitors, the seed's
            # dict output (no packed program exists for a non-XLA model).
            cat_ids, numeric, mask = _pad_rows(cat_ids, numeric, n, rows)
            out = self._predict(cat_ids, numeric, mask)
            stats = self.shape_stats
            if stats is not None:
                stats.observe(f"bucket_{rows}", n, rows)
            return _ArraysHandle(out, n, rows, packed=False)
        t0 = time.perf_counter() if self.cost_ledger is not None else 0.0
        out, rows = self._dispatch_padded(cat_ids, numeric, n, rows, tier)
        stats = self.shape_stats
        if stats is not None:
            # rows is the shape that actually SERVED (the degraded
            # fallback bucket when the target failed) — the histogram must
            # describe the compute paid, not the compute intended.
            stats.observe(_entry_name(f"bucket_{rows}", tier), n, rows)
        return _ArraysHandle(out, n, rows, packed=True, t0=t0, tier=tier)

    def _dispatch_padded(
        self, cat_ids, numeric, n: int, rows: int, tier: str | None = None
    ):
        """Pad to ``rows`` and dispatch the fused packed program, keyed by
        the padded row count (equal to the bucket for bucketed requests,
        the exact size for oversized ones — so a repeated oversized shape
        reuses its table entry instead of recompiling).

        DEGRADED MODE: a failure for an unwarmed target shape (compile
        error, corrupt-cache load — the `serve.engine.compile` fault
        class) retries through the NEXT LARGER warmed bucket instead of
        500ing: padding is masked out of every statistic, so the degraded
        response is bit-identical to the target-bucket response — the
        request pays extra padded compute, never an outage. Counted in
        ``degraded_dispatch_total``; with no larger warmed bucket the
        original failure propagates (the caller's 500 contract). Returns
        ``(packed_out, rows_used)``. Degraded fallbacks stay WITHIN the
        request's tier: padding is bit-neutral, a tier change is not."""
        key = ("bucket", rows) if tier is None else ("bucket", rows, tier)
        try:
            cat, num, mask = _pad_rows(cat_ids, numeric, n, rows)
            return self._dispatch_fused(key, cat, num, mask, tier=tier), rows
        except Exception:
            fallback = self._degraded_rows(rows, tier)
            if fallback is None:
                raise
            logger.warning(
                "dispatch at %d rows failed; degrading to warmed bucket %d",
                rows, fallback, exc_info=True,
            )
            cat, num, mask = _pad_rows(cat_ids, numeric, n, fallback)
            fkey = (
                ("bucket", fallback) if tier is None
                else ("bucket", fallback, tier)
            )
            out = self._dispatch_fused(fkey, cat, num, mask, tier=tier)
            self._count_degraded()
            return out, fallback

    def _degraded_rows(
        self, rows: int, tier: str | None = None
    ) -> int | None:
        """Smallest WARMED same-tier bucket strictly larger than ``rows``
        (the degraded-dispatch target), or None when nothing larger is
        warmed for that tier."""
        with self._compile_lock:
            larger = [
                key[1]
                for key in self._exec
                if key[0] == "bucket" and key[1] > rows
                and _key_tier(key) == tier
            ]
        return min(larger, default=None)

    def fetch_arrays(self, handle: _ArraysHandle) -> dict[str, Any]:
        """Block on the host copy and slice the packed buffer into the
        reference response. ONE contiguous f32 buffer per request: the
        seed's 3-leaf tree fetch paid a device->host transfer per leaf
        (~70-90 ms each through the remote-chip tunnel — measured), the
        packed buffer pays exactly one."""
        return format_response(*self.fetch_arrays_raw(handle))

    def fetch_arrays_wire(self, handle: _ArraysHandle) -> bytes:
        """`fetch_arrays` straight to wire bytes (serve/wire.py
        `encode_response` — byte-identical to the dict path's json). The
        batcher runs this in the executor thread, so the event loop never
        pays the per-response encode again."""
        return encode_response(*self.fetch_arrays_raw(handle))

    def fetch_arrays_raw(
        self, handle: _ArraysHandle
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The fetch minus the dict/list formatting: ``(predictions f64[n],
        outliers f64[n], drift f64[D] rounded)`` — exactly what
        `format_response` turns into the wire dict. The shared-memory ring
        service (serve/ipc.py) writes these arrays straight into response
        slabs so the front-end processes format the identical floats."""
        n, rows = handle.n, handle.rows
        if handle.packed:
            arr = np.asarray(handle.out)
            p, o, d = packed_layout(rows)
            predictions = arr[p][:n]
            outliers = arr[o][:n]
            drift = arr[d]
        else:
            out = jax.device_get(handle.out)
            predictions = np.asarray(out["predictions"])[:n]
            outliers = np.asarray(out["outliers"])[:n]
            drift = np.asarray(out["feature_drift_batch"])
        ledger = self.cost_ledger
        if ledger is not None and handle.t0:
            # Device-path seconds: dispatch enqueue -> host copy landed
            # (on a remote-attached chip this includes the transfer —
            # exactly the cost a regrid would re-shape). The np.asarray
            # above is the blocking wait, so the buffer is in hand here.
            ledger.observe(
                _entry_name(f"bucket_{rows}", handle.tier),
                self._cost_tag, n, rows,
                time.perf_counter() - handle.t0,
            )
        return (
            predictions.astype(float),
            outliers.astype(float),
            drift.astype(float).round(6),
        )

    # ----------------------------------------------------- grouped predict
    def predict_group(
        self, requests: list[list[dict[str, Any]]],
        tier: str | None = None,
    ) -> list[dict[str, Any]]:
        """Score several concurrent requests in ONE device dispatch.

        Every request must have 1..GROUP_ROW_BUCKET records (the batcher
        enforces this); responses are exactly what each request would get
        from ``predict_records`` alone — per-request drift included.
        """
        return self.fetch_group(self.dispatch_group(requests, tier=tier))

    def dispatch_group(
        self, requests: list[list[dict[str, Any]]],
        tier: str | None = None,
    ) -> _GroupHandle:
        """Encode + fire the grouped device dispatch and start the packed
        output's async host copy, WITHOUT blocking on the result — the
        micro-batcher claims and dispatches the next group while this one's
        fetch completes (`serve/batcher.py`'s fetch ring)."""
        if (
            self._predict_group is None
            or len(requests) == 1
            or len(requests) > GROUP_SLOT_BUCKETS[-1]
        ):
            return _GroupHandle(
                responses=[
                    self.predict_records(r, tier=tier) for r in requests
                ]
            )
        sizes = [len(r) for r in requests]
        if not all(1 <= n <= GROUP_ROW_BUCKET for n in sizes):
            raise ValueError(
                f"grouped requests must have 1..{GROUP_ROW_BUCKET} records, "
                f"got sizes {sizes}"
            )
        # ONE encode pass over the whole group, split back into per-request
        # views: encoding is row-wise (vocab lookup + standardization), so
        # the flat encode is bit-identical to per-request encodes while
        # doing the Python/dict work once instead of per request — this
        # host work is serial (GIL) and sits on the grouped hot path.
        flat = [record for records in requests for record in records]
        ds = self.bundle.preprocessor.encode(records_to_columns(flat))
        parts, offset = [], 0
        for n in sizes:
            parts.append(
                (ds.cat_ids[offset : offset + n], ds.numeric[offset : offset + n])
            )
            offset += n
        return self.dispatch_group_arrays(parts, tier=tier)

    def dispatch_group_arrays(
        self, parts: list[tuple[np.ndarray, np.ndarray]],
        tier: str | None = None,
    ) -> _GroupHandle:
        """Grouped dispatch from PRE-ENCODED per-request arrays — the entry
        the shared-memory ring service uses (serve/ipc.py): front-end
        processes encode before enqueue (the native encoder releases the
        GIL there), so the engine process scatters rows straight into the
        group buffers without touching records or the preprocessor.
        Requires 2..GROUP_SLOT_BUCKETS[-1] requests of 1..GROUP_ROW_BUCKET
        rows each (the callers' coalescing policy guarantees it). The
        whole group serves ONE tier (per-(tier, tenant) coalescing is the
        callers' contract — one grouped dispatch is one program)."""
        tier = self._normalize_tier(tier)
        sizes = [cat.shape[0] for cat, _ in parts]
        tee = self._tee
        if tee is not None:
            for part_cat, part_num in parts:
                tee(part_cat, part_num)
        if not 2 <= len(parts) <= GROUP_SLOT_BUCKETS[-1]:
            raise ValueError(
                f"grouped dispatch takes 2..{GROUP_SLOT_BUCKETS[-1]} "
                f"requests, got {len(parts)}"
            )
        if not all(1 <= n <= GROUP_ROW_BUCKET for n in sizes):
            raise ValueError(
                f"grouped requests must have 1..{GROUP_ROW_BUCKET} records, "
                f"got sizes {sizes}"
            )
        # Injection point (mlops_tpu/faults): the grouped twin of
        # serve.engine.dispatch — covers the micro-batcher and the shm
        # ring plane's coalesced jobs.
        faults.fire("serve.engine.dispatch_group")
        t0 = time.perf_counter() if self.cost_ledger is not None else 0.0
        slots = GROUP_SLOT_BUCKETS[
            bisect.bisect_left(GROUP_SLOT_BUCKETS, len(parts))
        ]
        # Batch-1-only groups (the dominant serving traffic) take the
        # [slots, 1] shape family — no row padding, ~8x less compute per
        # dispatch on serial backends.
        rows = GROUP_ROW_BUCKETS[0] if max(sizes) == 1 else GROUP_ROW_BUCKET
        try:
            out = self._dispatch_group_at(parts, sizes, slots, rows, tier)
        except Exception:
            # DEGRADED MODE, grouped flavor: a compile/cache failure for
            # this group geometry retries through the smallest warmed
            # geometry that FITS (slot padding is masked out of every
            # statistic, so responses stay bit-identical) instead of
            # failing the whole coalesced job.
            fallback = self._degraded_group_shape(
                len(parts), max(sizes), (slots, rows), tier
            )
            if fallback is None:
                raise
            logger.warning(
                "grouped dispatch at (%d, %d) failed; degrading to warmed "
                "geometry (%d, %d)", slots, rows, *fallback, exc_info=True,
            )
            out = self._dispatch_group_at(parts, sizes, *fallback, tier)
            self._count_degraded()
            slots, rows = fallback
        stats = self.shape_stats
        if stats is not None:
            # Geometry occupancy: requested = the rows clients asked for,
            # padded = the full slots x rows grid the program computed
            # (slot padding AND row padding both count as waste).
            stats.observe(
                _entry_name(f"group_{slots}x{rows}", tier),
                sum(sizes), slots * rows,
            )
        handle = _GroupHandle(
            out=out, sizes=sizes, rows=rows, slots=slots, t0=t0, tier=tier
        )
        handle.start_copy()
        return handle

    def _dispatch_group_at(
        self,
        parts: list[tuple[np.ndarray, np.ndarray]],
        sizes: list[int],
        slots: int,
        rows: int,
        tier: str | None = None,
    ):
        """Scatter the pre-encoded parts into one [slots, rows, ...] stack
        and fire the fused grouped dispatch — shared by the target-shape
        and degraded-shape paths (one scatter rule = identical masking)."""
        cat = np.zeros((slots, rows, SCHEMA.num_categorical), np.int32)
        num = np.zeros((slots, rows, SCHEMA.num_numeric), np.float32)
        mask = np.zeros((slots, rows), bool)
        for i, (part_cat, part_num) in enumerate(parts):
            n = sizes[i]
            cat[i, :n] = part_cat
            num[i, :n] = part_num
            mask[i, :n] = True
        key = (
            ("group", slots, rows) if tier is None
            else ("group", slots, rows, tier)
        )
        return self._dispatch_fused(key, cat, num, mask, tier=tier)

    def _degraded_group_shape(
        self, n_parts: int, max_rows: int, failed: tuple[int, int],
        tier: str | None = None,
    ) -> tuple[int, int] | None:
        """Smallest-area WARMED same-tier group geometry that fits
        ``n_parts`` requests of up to ``max_rows`` rows, excluding the
        shape that just failed; None when nothing warmed fits."""
        with self._compile_lock:
            fits = [
                (key[1], key[2])
                for key in self._exec
                if key[0] == "group"
                and key[1] >= n_parts
                and key[2] >= max_rows
                and (key[1], key[2]) != failed
                and _key_tier(key) == tier
            ]
        return min(fits, key=lambda sr: sr[0] * sr[1], default=None)

    def fetch_group(self, handle: _GroupHandle) -> list[dict[str, Any]]:
        """Block on the packed group buffer (ONE D2H transfer for the whole
        group) and slice it back into per-request responses."""
        if handle.responses is not None:
            return handle.responses
        sizes, preds, outs, drifts = self.fetch_group_raw(handle)
        return [
            format_response(preds[i, :n], outs[i, :n], drifts[i])
            for i, n in enumerate(sizes)
        ]

    def fetch_group_wire(self, handle: _GroupHandle) -> list[bytes]:
        """`fetch_group` straight to per-request wire bytes (executor-side
        encode; see `fetch_arrays_wire`). Degenerate handles carry already
        formatted dicts from the solo fallback — encode those here too so
        the caller always gets bytes."""
        if handle.responses is not None:
            return [
                json.dumps(r, separators=(",", ":")).encode()
                for r in handle.responses
            ]
        sizes, preds, outs, drifts = self.fetch_group_raw(handle)
        return [
            encode_response(preds[i, :n], outs[i, :n], drifts[i])
            for i, n in enumerate(sizes)
        ]

    def fetch_group_raw(
        self, handle: _GroupHandle
    ) -> tuple[list[int], np.ndarray, np.ndarray, np.ndarray]:
        """The grouped fetch minus the per-request dict building:
        ``(sizes, predictions f64[slots, rows], outliers f64[slots, rows],
        drift f64[slots, D] rounded)``. Degenerate handles (solo fallback
        responses) never reach here — the ring service only groups through
        `dispatch_group_arrays`."""
        if handle.responses is not None:
            raise ValueError("degenerate group handle carries formatted "
                             "responses; fetch_group owns that path")
        rows = handle.rows
        arr = np.asarray(handle.out)  # [slots, 2*rows + D]
        ledger = self.cost_ledger
        if ledger is not None and handle.t0:
            # Grouped twin of the solo fetch's ledger hook: the whole
            # group rode one device dispatch, so the group's seconds
            # land on its geometry entry (requested = the rows clients
            # asked for; padded = the full slots x rows grid).
            ledger.observe(
                _entry_name(f"group_{handle.slots}x{rows}", handle.tier),
                self._cost_tag,
                sum(handle.sizes), handle.slots * rows,
                time.perf_counter() - handle.t0,
            )
        # Response assembly is serial host Python on the grouped hot path:
        # do the dtype casts/rounding ONCE over the stacked arrays, then
        # slice per slot (per-slot .astype/.round cost ~3x more).
        p, o, d = packed_layout(rows)
        return (
            handle.sizes,
            arr[:, p].astype(float),
            arr[:, o].astype(float),
            arr[:, d].astype(float).round(6),
        )

    def _bucket_for(self, n: int) -> int | None:
        i = bisect.bisect_left(self.buckets, n)
        return self.buckets[i] if i < len(self.buckets) else None
