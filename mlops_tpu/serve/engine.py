"""Inference engine: bundle -> warmed, bucketed, fused predict.

TPU serving mechanics (SURVEY.md SS7 "hard parts" — batch-1 latency):

- ONE compiled program per batch bucket (1, 8, 64, 256 by default): requests
  are padded up to the nearest bucket with a validity mask, so XLA never
  recompiles in steady state and drift/outlier statistics ignore padding.
- warmup compiles every bucket at startup (readiness gate — the reference
  has no readiness probe at all, `kubernetes/manifest.yml:1-54`).
- host work is minimal: string->id lookups and one float array build per
  request; everything else (classifier + monitors) is a single device
  dispatch.
"""

from __future__ import annotations

import bisect
from typing import Any

import jax
import numpy as np

from mlops_tpu.bundle.bundle import Bundle
from mlops_tpu.ops.predict import (
    make_grouped_predict_fn,
    make_hybrid_predict_fn,
    make_padded_predict_fn,
)
from mlops_tpu.schema import SCHEMA, records_to_columns

# Micro-batching shape grid: concurrent requests coalesce into [R, B, ...]
# stacks — R request-slots (padded up to a slot bucket), each padded to B
# rows. Only small requests coalesce; big ones already fill the MXU alone.
# Slot buckets go to 64: on a remote-attached chip every dispatch pays a
# flat transport round trip (measured ~70-90 ms through this harness's
# tunnel), so request throughput scales with requests-per-dispatch — 64
# batch-1 requests in one vmapped program cost the same wall time as one.
# Row buckets are (1, 8): batch-1 is the dominant serving shape and
# padding it to 8 rows made every grouped dispatch compute 8x the rows it
# returned — on CPU backends (serial compute) that padding was the
# throughput ceiling. An all-batch-1 group now rides the [R, 1, ...]
# family; mixed small sizes pad to 8 as before.
GROUP_SLOT_BUCKETS = (2, 4, 8, 16, 32, 64)
GROUP_ROW_BUCKETS = (1, 8)
GROUP_ROW_BUCKET = GROUP_ROW_BUCKETS[-1]


class InferenceEngine:
    def __init__(
        self,
        bundle: Bundle,
        buckets: tuple[int, ...] = (1, 8, 64, 256),
        service_name: str = "credit-default-api",
        enable_grouping: bool = True,
        compile_cache=None,
        warmup_workers: int = 0,
    ):
        self.bundle = bundle
        if bundle.flavor == "doc":
            raise ValueError(
                "doc bundles score record HISTORIES, not single records — "
                "the HTTP predict contract does not apply; score offline "
                "via `predict-file data.train_path=<history csv>`"
            )
        self.buckets = sorted(buckets)
        self.max_bucket = self.buckets[-1]
        self.service_name = service_name
        # Persistent AOT executable cache (compilecache/): warmup probes it
        # before compiling, so a second process on the same box (deploy,
        # rollout, autoscale replica) deserializes in seconds instead of
        # recompiling for a minute. None = compile-only warmup.
        self.compile_cache = compile_cache
        self.warmup_workers = warmup_workers
        self.warmup_stats: dict[str, Any] = {}
        # AOT dispatch table: ("bucket", b) / ("group", slots, rows) ->
        # compiled executable for exactly that shape (filled by warmup).
        # Misses fall back to the bound jitted programs below, which
        # compile on demand — exactly the pre-cache behavior.
        self._exec: dict[tuple, Any] = {}
        temperature = bundle.temperature  # calibration (train/calibrate.py)
        if bundle.flavor == "sklearn":
            # CPU tree-ensemble floor: host classifier + device monitors.
            # No grouped path — trees run on host threads anyway (and no
            # AOT table: the classifier is not an XLA program).
            self._predict = make_hybrid_predict_fn(
                bundle.estimator, bundle.monitor, temperature
            )
            self._predict_group = None
        else:
            # device_put ONCE: params/monitor/temperature are per-call
            # ARGUMENTS of the cached programs — host numpy trees would
            # re-pay the full host->device param transfer on every
            # request; committed device arrays pass by reference.
            self._variables = jax.device_put(bundle.variables)
            self._monitor = jax.device_put(bundle.monitor)
            self._temperature = jax.device_put(np.float32(temperature))
            self._predict = make_padded_predict_fn(
                bundle.model, self._variables, self._monitor, temperature
            )
            self._predict_group = (
                make_grouped_predict_fn(
                    bundle.model, self._variables, self._monitor, temperature
                )
                if enable_grouping
                else None
            )
        self.ready = False

    @property
    def supports_grouping(self) -> bool:
        return self._predict_group is not None

    # ------------------------------------------------------------- warmup
    def warmup(self) -> None:
        """Ready every bucket size (and group shape) before traffic.

        Flax flavors warm ahead-of-time through `compilecache/warmup.py`:
        probe the persistent cache -> deserialize hits, compile misses IN
        PARALLEL (XLA compilation releases the GIL; a small thread pool
        over shapes) -> persist -> execute each program once on zeros (pay
        first-dispatch allocation; fail loudly on an artifact that loads
        but cannot run). ``warmup_stats`` records the wall time plus the
        cache's hit/miss/bypass counts and per-program compile vs
        deserialize seconds.
        """
        import time

        t0 = time.perf_counter()
        if self.bundle.flavor == "sklearn":
            for bucket in self.buckets:
                cat = np.zeros((bucket, SCHEMA.num_categorical), np.int32)
                num = np.zeros((bucket, SCHEMA.num_numeric), np.float32)
                mask = np.ones((bucket,), bool)
                jax.block_until_ready(self._predict(cat, num, mask)["outliers"])
            self.ready = True
            self.warmup_stats = {
                "warmup_s": round(time.perf_counter() - t0, 3),
                "programs": len(self.buckets),
                "cache": None,
            }
            return

        from mlops_tpu.compilecache.warmup import (
            default_workers,
            run_jobs,
            serve_group_jobs,
            serve_predict_jobs,
        )

        bundle = self.bundle
        jobs = serve_predict_jobs(
            bundle.model,
            bundle.model_config,
            self._variables,  # device-resident (init): avals identical,
            self._monitor,  # and the execute-once pass skips a transfer
            tuple(self.buckets),
            temperature=bundle.temperature,
        )
        if self._predict_group is not None:
            grid = [
                (slots, rows)
                for rows in GROUP_ROW_BUCKETS
                for slots in GROUP_SLOT_BUCKETS
            ]
            jobs += serve_group_jobs(
                bundle.model,
                bundle.model_config,
                self._variables,
                self._monitor,
                grid,
                temperature=bundle.temperature,
            )
        for job, fn in run_jobs(
            jobs, cache=self.compile_cache, workers=self.warmup_workers
        ):
            if "bucket" in job.meta:
                self._exec[("bucket", job.meta["bucket"])] = fn
            else:
                self._exec[("group", job.meta["slots"], job.meta["rows"])] = fn
        self.ready = True
        self.warmup_stats = {
            "warmup_s": round(time.perf_counter() - t0, 3),
            "programs": len(jobs),
            "workers": default_workers(len(jobs), self.warmup_workers),
            "cache": (
                self.compile_cache.stats()
                if self.compile_cache is not None
                else None
            ),
        }

    def _run_exec(self, key: tuple, cat_ids, numeric, mask, fallback):
        """Dispatch through the AOT table when the shape was warmed; the
        bound jitted program otherwise (novel shapes compile on demand)."""
        fn = self._exec.get(key)
        if fn is None:
            return fallback(cat_ids, numeric, mask)
        return fn(
            self._variables, self._monitor, self._temperature,
            cat_ids, numeric, mask,
        )

    # -------------------------------------------------------------- predict
    def predict_records(self, records: list[dict[str, Any]]) -> dict[str, Any]:
        """Validated records -> reference response dict (`app/model.py:64-70`)."""
        columns = records_to_columns(records)
        ds = self.bundle.preprocessor.encode(columns)
        return self.predict_arrays(ds.cat_ids, ds.numeric)

    def predict_arrays(
        self, cat_ids: np.ndarray, numeric: np.ndarray
    ) -> dict[str, Any]:
        n = cat_ids.shape[0]
        if n == 0:
            # Empty request: nothing to score, no drift signal (an empty
            # batch must not poison the drift gauges with statistic=1).
            return {
                "predictions": [],
                "outliers": [],
                "feature_drift_batch": dict.fromkeys(SCHEMA.feature_names, 0.0),
            }
        bucket = self._bucket_for(n)
        if bucket is not None:
            pad = bucket - n
            if pad:
                cat_ids = np.pad(cat_ids, ((0, pad), (0, 0)))
                numeric = np.pad(numeric, ((0, pad), (0, 0)))
            mask = np.arange(bucket) < n
        else:
            # Oversized request: run at exact shape (compiles once per novel
            # size — rare; offline batch scoring uses this path).
            mask = np.ones((n,), bool)
        # ONE device_get of the whole tree: separate np.asarray calls per
        # field each pay a full device->host round trip (~70 ms through the
        # remote-chip tunnel — measured; 3 fetches were the entire 210 ms
        # batch-1 latency wall), while a tree fetch batches into one.
        out = jax.device_get(
            self._run_exec(
                ("bucket", bucket), cat_ids, numeric, mask, self._predict
            )
            if bucket is not None
            else self._predict(cat_ids, numeric, mask)
        )
        predictions = np.asarray(out["predictions"])[:n]
        outliers = np.asarray(out["outliers"])[:n]
        drift = np.asarray(out["feature_drift_batch"])
        return {
            "predictions": predictions.astype(float).tolist(),
            "outliers": outliers.astype(float).tolist(),
            "feature_drift_batch": dict(
                zip(SCHEMA.feature_names, drift.astype(float).round(6).tolist())
            ),
        }

    # ----------------------------------------------------- grouped predict
    def predict_group(
        self, requests: list[list[dict[str, Any]]]
    ) -> list[dict[str, Any]]:
        """Score several concurrent requests in ONE device dispatch.

        Every request must have 1..GROUP_ROW_BUCKET records (the batcher
        enforces this); responses are exactly what each request would get
        from ``predict_records`` alone — per-request drift included.
        """
        if (
            self._predict_group is None
            or len(requests) == 1
            or len(requests) > GROUP_SLOT_BUCKETS[-1]
        ):
            return [self.predict_records(r) for r in requests]
        sizes = [len(r) for r in requests]
        if not all(1 <= n <= GROUP_ROW_BUCKET for n in sizes):
            raise ValueError(
                f"grouped requests must have 1..{GROUP_ROW_BUCKET} records, "
                f"got sizes {sizes}"
            )

        slots = GROUP_SLOT_BUCKETS[
            bisect.bisect_left(GROUP_SLOT_BUCKETS, len(requests))
        ]
        # Batch-1-only groups (the dominant serving traffic) take the
        # [slots, 1] shape family — no row padding, ~8x less compute per
        # dispatch on serial backends.
        rows = GROUP_ROW_BUCKETS[0] if max(sizes) == 1 else GROUP_ROW_BUCKET
        cat = np.zeros((slots, rows, SCHEMA.num_categorical), np.int32)
        num = np.zeros((slots, rows, SCHEMA.num_numeric), np.float32)
        mask = np.zeros((slots, rows), bool)
        # ONE encode pass over the whole group, scattered into slots:
        # encoding is row-wise (vocab lookup + standardization), so the
        # flat encode is bit-identical to per-request encodes while doing
        # the Python/dict work once instead of per request — this host
        # work is serial (GIL) and sits on the grouped hot path.
        flat = [record for records in requests for record in records]
        ds = self.bundle.preprocessor.encode(records_to_columns(flat))
        offset = 0
        for i, n in enumerate(sizes):
            cat[i, :n] = ds.cat_ids[offset : offset + n]
            num[i, :n] = ds.numeric[offset : offset + n]
            mask[i, :n] = True
            offset += n

        # Single tree fetch (see predict_arrays): one transport round trip.
        out = jax.device_get(
            self._run_exec(
                ("group", slots, rows), cat, num, mask, self._predict_group
            )
        )
        # Response assembly is serial host Python on the grouped hot path:
        # do the dtype casts/rounding ONCE over the stacked arrays, then
        # slice per slot (per-slot .astype/.round cost ~3x more).
        preds = np.asarray(out["predictions"]).astype(float)
        outs = np.asarray(out["outliers"]).astype(float)
        drifts = np.asarray(out["feature_drift_batch"]).astype(float).round(6)
        names = SCHEMA.feature_names
        responses = []
        for i, n in enumerate(sizes):
            responses.append(
                {
                    "predictions": preds[i, :n].tolist(),
                    "outliers": outs[i, :n].tolist(),
                    "feature_drift_batch": dict(zip(names, drifts[i].tolist())),
                }
            )
        return responses

    def _bucket_for(self, n: int) -> int | None:
        i = bisect.bisect_left(self.buckets, n)
        return self.buckets[i] if i < len(self.buckets) else None
