"""Serving layer: inference engine + dependency-free asyncio HTTP server.

Replaces the reference's FastAPI + uvicorn + mlflow-pyfunc serving stack
(`app/main.py`). Same HTTP contract:

- ``POST /predict``  body ``list[LoanApplicant]`` -> ``ModelOutput``
  (`app/main.py:42-86`)
- port 5000, env ``MODEL_DIRECTORY`` / ``SERVICE_NAME``
  (`app/Dockerfile:22-24`, `app/main.py:27,36`)
- two structured JSON log events per request (``InferenceData`` /
  ``ModelOutput``) sharing a ``request_id`` (`app/main.py:57-84`)

plus what the reference lacks (SURVEY.md SS5.1/5.3): ``/healthz/live`` and
``/healthz/ready`` probes, a Prometheus ``/metrics`` endpoint with latency
percentiles, jit warmup over fixed batch buckets, and micro-batch padding so
steady-state serving never recompiles.
"""

# LAZY exports: `serve.engine`/`serve.server` pull jax at import time,
# but the multi-worker front-end processes (serve/frontend.py) import
# sibling modules (httpcore, ipc, wire, metrics) from this package and
# must stay jax-free — an eager import here would drag the whole backend
# into every forked worker.
_EXPORTS = {
    "InferenceEngine": "mlops_tpu.serve.engine",
    "HttpServer": "mlops_tpu.serve.server",
    "serve_forever": "mlops_tpu.serve.server",
}

__all__ = ["HttpServer", "InferenceEngine", "serve_forever"]


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)
