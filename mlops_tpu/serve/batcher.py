"""Micro-batching queue: coalesce concurrent requests into one dispatch.

SURVEY.md SS7 step 5 names this as serving hardening the reference lacks
(its model is called strictly once per request, `app/main.py:72`). Under
concurrent load, per-request dispatch leaves the chip idle between small
kernels; here requests that arrive within a short window ride a single
vmapped program (``InferenceEngine.predict_group``) — identical per-request
responses, up to GROUP_SLOT_BUCKETS[-1]x fewer dispatches.

Policy: only small requests (<= GROUP_ROW_BUCKET rows) coalesce — large
ones already fill the MXU alone and go straight through. The window closes
early the moment a full group is waiting, so the added latency under load
is ~0 (the group fills faster than the window) and at idle is bounded by
``window_ms`` (default 1 ms, well inside the 5 ms p50 budget).

Two admission modes (ISSUE 17, ``serve.batch_mode``): the legacy
"windowed" wave holds every group open for the fixed window first;
"continuous" (default) admits pending requests into in-flight group slots
at dispatch boundaries — the in-flight round trip is itself the
coalescing window (paid for free), and only an empty pipe waits, for a
deadline derived from the measured dispatch time instead of a guess. See
``MicroBatcher.__init__`` and docs/performance.md "Continuous
micro-batching".
"""

from __future__ import annotations

import asyncio
from typing import Any

from mlops_tpu.serve.engine import InferenceEngine

# The coalescing policy constants come from the jax-free wire-contract
# module shared with the multi-worker plane: the shared-memory ring
# service (serve/ipc.py RingService) applies the SAME small-request
# grouping rule engine-side, so one process or N, identical requests
# ride identical compiled shapes.
from mlops_tpu.serve.wire import (
    GROUP_ROW_BUCKET,
    GROUP_SLOT_BUCKETS,
    DeadlineExceeded,
)

# Declared order for the two-phase rings, OUTERMOST FIRST (tpulint Layer 3
# manifest — analysis/concurrency.py / lockcheck.py): the fetch ring is
# only ever claimed while a dispatch slot is held (`_dispatch` claims it
# BEFORE releasing the slot — round-5 review: released-first let a lagging
# fetch path pile un-purgeable handles at the ring). The reverse nesting
# would deadlock once both rings sit at capacity. The `_inflight`
# acquire/release pair legitimately spans `_drain` -> `_dispatch` (the slot
# outlives the method that claimed it), which the static pairing rule
# (TPU404) cannot follow lexically — declared below so the split is intent,
# not an accident of `_drain`'s error-path release; the seeded stress tests
# in tests/test_batcher.py exercise the pairing at runtime.
TPULINT_LOCK_ORDER = {"MicroBatcher": ("_inflight", "_fetch_ring")}
TPULINT_CROSS_METHOD_SEMAPHORES = {"MicroBatcher": ("_inflight",)}


class MicroBatcher:
    """Single drain-loop + overlapped dispatches: one background task owns
    the queue and no task is ever cancelled (a cancel racing a
    mid-dispatch flush would strand futures). The loop waits out the
    window, claims up to ``max_group`` requests, and fires the dispatch as
    its own task WITHOUT awaiting it — on a remote-attached chip a
    dispatch is wall-clocked by a flat transport round trip (~70-90 ms
    measured), and round trips from separate threads overlap, so serial
    dispatches would cap throughput at one group per round trip.
    ``max_inflight`` bounds the overlap (it must not exceed the engine
    thread pool, or dispatches would queue inside the executor anyway).

    With the packed two-phase engine API (dispatch_group / fetch_group)
    each dispatch task additionally splits into a dispatch phase (encode +
    device enqueue + async D2H copy start, under the inflight bound) and a
    fetch phase (the blocking host-copy wait, under the fetch ring) — the
    drain loop dispatches group N+1 while group N's bytes land."""

    def __init__(
        self,
        engine: InferenceEngine,
        executor,
        window_ms: float = 1.0,
        max_group: int = GROUP_SLOT_BUCKETS[-1],
        max_inflight: int = 4,
        fetch_inflight: int | None = None,
        batch_mode: str = "continuous",
        admit_fraction: float = 0.5,
        wire_responses: bool = False,
    ):
        if batch_mode not in ("continuous", "windowed"):
            raise ValueError(
                f"batch_mode must be 'continuous' or 'windowed', "
                f"got {batch_mode!r}"
            )
        self.engine = engine
        self._executor = executor
        self.window_s = window_ms / 1e3
        # A group can never exceed the largest warmed slot bucket — beyond
        # it predict_group would have no compiled shape to run.
        self.max_group = min(max_group, GROUP_SLOT_BUCKETS[-1])
        # Admission policy (ISSUE 17). "windowed" (the legacy wave): every
        # group holds its window open for the full window_s before
        # claiming. "continuous": admission happens at DISPATCH
        # BOUNDARIES — the drain loop claims the in-flight slot first,
        # then admits whatever is pending. While other dispatches are in
        # flight the admit wait is ZERO (their device round trips already
        # gave co-travelers time to accumulate — that accumulation IS the
        # window, paid for free); only an empty pipe waits, and then for
        # ``admit_fraction`` of the EWMA-measured dispatch-stage seconds
        # (the span stage that dominates batch-1 latency — BENCH_r08:
        # fetch_sync ~1.59 of 2.02 ms p50), capped by window_s. Group
        # geometry never changes per-request math, so responses are
        # bit-identical across modes at any load.
        self.batch_mode = batch_mode
        self.admit_fraction = admit_fraction
        self._dispatch_ewma_s = 0.0  # EWMA of measured dispatch-phase
        # seconds (event-loop confined: updated by _dispatch tasks, read
        # by _drain — both on the loop thread, never the executor)
        # (records, future, absolute loop-clock deadline or None,
        #  tracewire span or None, routed tier name or None)
        self._pending: list[
            tuple[list[dict], asyncio.Future, float | None, Any, str | None]
        ] = []
        self._drain_task: asyncio.Task | None = None
        self._full = asyncio.Event()  # set when a full group is waiting
        self._inflight = asyncio.Semaphore(max_inflight)
        # Fetch ring: engines exposing the two-phase dispatch_group /
        # fetch_group API (serve/engine.py) release their DISPATCH slot as
        # soon as the device work + async D2H copy are in flight, then
        # complete the blocking fetch under this SECOND bound — so the
        # drain loop claims and dispatches the next group while the
        # previous group's host copy lands. The two bounds together can
        # occupy dispatch + fetch executor threads at once; callers that
        # share the executor with other work (the server's solo fast path,
        # /metrics monitor fetches) size ``fetch_inflight`` so the sum
        # leaves headroom (serve/server.py) — default: max_inflight.
        self._fetch_ring = asyncio.Semaphore(
            max_inflight if fetch_inflight is None else max(1, fetch_inflight)
        )
        self._dispatch_tasks: set[asyncio.Task] = set()  # strong refs
        self._last_enqueue = float("-inf")  # loop-clock time of the most
        # recent coalescable arrival (idle fast-path bookkeeping)
        self._solo_inflight = 0  # fast-path calls currently in the
        # executor: they must count against the idle condition, or a
        # stalled engine would accumulate unbounded un-cancellable
        # executor work outside the batcher's claim-time purge
        # Wire mode (encode-residue fix): prefer the engine's *_wire
        # fetches — responses come back as pre-encoded json bytes built in
        # the EXECUTOR thread, so the event loop never pays the
        # per-response `json.dumps` (~7% of loop time at c128, profiled).
        # getattr fallbacks keep stub/sklearn engines on the dict path.
        self.wire_responses = bool(wire_responses)
        self._predict_solo = (
            getattr(engine, "predict_records_wire", None)
            if wire_responses
            else None
        ) or engine.predict_records

    @property
    def enabled(self) -> bool:
        return self.engine.supports_grouping and self.window_s > 0

    async def predict(
        self,
        records: list[dict[str, Any]],
        deadline: float | None = None,
        span: Any = None,
        tier: str | None = None,
    ) -> dict[str, Any] | bytes:
        """Entry point for the request handler. ``deadline`` (absolute
        loop-clock time, from the request's ``x-request-deadline-ms``
        budget) rides with the queued entry: the drain loop's claim-time
        purge completes an already-expired entry with
        ``DeadlineExceeded`` INSTEAD of dispatching it — dead work is
        shed engine-side, before it costs a device dispatch, not just
        abandoned by the waiting handler. ``span`` (tracewire) rides the
        same way and gets the queue/dispatch/fetch stage stamps; None
        (the default, tracing disarmed) costs one branch per path.
        ``tier`` (ISSUE 19 SLO routing, resolved upstream by
        `engine.route_tier`) rides the entry too: a group is ONE compiled
        program, so the drain loop only coalesces same-tier entries and
        the dispatch carries the tier down to the engine. None (the
        default and the single-tier fast path) is the engine's default
        tier — stub engines without the keyword never see it."""
        loop = asyncio.get_running_loop()
        if (
            not self.enabled
            or not (1 <= len(records) <= GROUP_ROW_BUCKET)
        ):
            if span is None and tier is None:
                return await loop.run_in_executor(
                    self._executor, self._predict_solo, records
                )
            # Span/tier threading needs the keyword form; stub engines
            # (tests, sklearn shims) only see it with tracing armed or
            # tier routing on.
            return await loop.run_in_executor(
                self._executor,
                lambda: self._predict_solo(records, span=span, tier=tier)
                if tier is not None
                else self._predict_solo(records, span=span),
            )

        # Idle fast-path: a request arriving with nothing queued, nothing
        # in flight (grouped OR solo), and no arrival within the last
        # window has no co-travelers to wait for — holding it the full
        # window would buy zero coalescing and cost the whole window in
        # p50 (measured: the 1 ms default tripled sequential-client
        # latency). Sustained load arrives within the window of the
        # previous request and still coalesces; a stalled solo call
        # (counter > 0) pushes new arrivals back onto the batcher, whose
        # claim-time purge and max_inflight bound the backlog.
        now = loop.time()
        idle = (
            not self._pending
            and not self._dispatch_tasks
            and self._solo_inflight == 0
            and (now - self._last_enqueue) > self.window_s
        )
        self._last_enqueue = now
        if idle:
            # The decrement is tied to EXECUTOR completion, not caller
            # exit: a deadline-cancelled caller leaves the engine call
            # occupying its thread, and decrementing early would re-open
            # the fast-path for the next victim — re-creating the
            # unbounded-dead-backlog failure the counter exists to stop.
            self._solo_inflight += 1
            if span is None and tier is None:
                fut = loop.run_in_executor(
                    self._executor, self._predict_solo, records
                )
            else:
                fut = loop.run_in_executor(
                    self._executor,
                    lambda: self._predict_solo(records, span=span, tier=tier)
                    if tier is not None
                    else self._predict_solo(records, span=span),
                )

            def _done(f: asyncio.Future) -> None:
                self._solo_inflight -= 1
                if not f.cancelled():
                    f.exception()  # retrieve, or the loop logs a warning
                    # when the deadline-cancelled caller never awaits it

            fut.add_done_callback(_done)
            # shield: a deadline-cancelled caller must not cancel the
            # wrapper future (that would fire _done at cancel time while
            # the thread still runs — the early decrement again).
            return await asyncio.shield(fut)

        future: asyncio.Future = loop.create_future()
        self._pending.append((records, future, deadline, span, tier))
        if len(self._pending) >= self.max_group:
            self._full.set()  # close the window early
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.create_task(self._drain())
        return await future

    def _admit_deadline_s(self) -> float:
        """Continuous mode's empty-pipe admit wait. 0 while dispatches are
        in flight (the dispatch boundary IS the admission point — arrivals
        during the in-flight round trip coalesced for free); otherwise a
        fraction of the measured dispatch time, capped by the configured
        window (cold start, before any measurement, waits the full cap)."""
        if self._dispatch_tasks:
            return 0.0
        if self._dispatch_ewma_s <= 0.0:
            return self.window_s
        return min(self.window_s, self.admit_fraction * self._dispatch_ewma_s)

    async def _drain(self) -> None:
        continuous = self.batch_mode == "continuous"
        while self._pending:
            if continuous:
                # Admission at the dispatch boundary: claim the in-flight
                # slot FIRST (the declared _inflight -> _fetch_ring order
                # is unchanged — the wait below holds no other lock), then
                # give an empty pipe a short, measured co-traveler wait.
                await self._inflight.acquire()
                admit = self._admit_deadline_s()
                if admit > 0 and len(self._pending) < self.max_group:
                    self._full.clear()
                    try:
                        await asyncio.wait_for(self._full.wait(), admit)
                    except asyncio.TimeoutError:
                        pass
            else:
                if len(self._pending) < self.max_group:
                    # Hold the window open for co-travelers; a full group
                    # (or anything setting _full) closes it early.
                    self._full.clear()
                    try:
                        await asyncio.wait_for(
                            self._full.wait(), self.window_s
                        )
                    except asyncio.TimeoutError:
                        pass
                # Claim a group, then block only on the in-flight bound —
                # NOT on the dispatch itself, so up to max_inflight groups
                # ride overlapping device round trips.
                await self._inflight.acquire()
            # Claim-time purge, two kinds of dead entry: ABANDONED ones
            # (the server's request deadline cancelled the caller's
            # future, e.g. during a device stall) are dropped — without
            # this, a long stall with ongoing traffic grows _pending
            # unboundedly and a recovering device would burn through a
            # dead backlog before serving live requests. EXPIRED ones
            # (deadline budget spent waiting in this queue) are completed
            # with DeadlineExceeded so the handler answers 504 NOW and
            # the entry never costs a dispatch — the engine-side
            # dead-work shed.
            now = asyncio.get_running_loop().time()
            live = []
            for entry in self._pending:
                _, future, entry_deadline, _, _ = entry
                if future.done():
                    continue
                if entry_deadline is not None and now >= entry_deadline:
                    future.set_exception(DeadlineExceeded())
                    continue
                live.append(entry)
            self._pending = live
            if not self._pending:
                self._inflight.release()
                continue
            # Same-tier claim (ISSUE 19): a group rides ONE compiled
            # program, so a mixed-tier queue splits into per-tier
            # dispatches — take the head entry's tier and every queued
            # co-traveler on it (FIFO within the tier); other tiers stay
            # queued and dispatch on the next loop iteration.
            head_tier = self._pending[0][4]
            batch: list = []
            rest: list = []
            for entry in self._pending:
                if len(batch) < self.max_group and entry[4] == head_tier:
                    batch.append(entry)
                else:
                    rest.append(entry)
            self._pending = rest
            task = asyncio.create_task(self._dispatch(batch, head_tier))
            self._dispatch_tasks.add(task)
            task.add_done_callback(self._dispatch_tasks.discard)
        # Exit with an empty queue: predict() observes the done() task and
        # spawns a fresh drain for the next arrival (no lost wakeups — both
        # run on the event loop and the final emptiness check returns
        # without awaiting). In-flight dispatch tasks complete on their
        # own; their futures don't need the drain loop.

    def _observe_dispatch_s(self, seconds: float) -> None:
        """Fold one measured dispatch-phase duration into the EWMA the
        continuous admit deadline reads (event-loop confined, like every
        other mutable batcher field)."""
        if self._dispatch_ewma_s <= 0.0:
            self._dispatch_ewma_s = seconds
        else:
            self._dispatch_ewma_s = (
                0.8 * self._dispatch_ewma_s + 0.2 * seconds
            )

    async def _dispatch(
        self,
        batch: list[
            tuple[list[dict], asyncio.Future, float | None, Any, str | None]
        ],
        tier: str | None = None,
    ) -> None:
        loop = asyncio.get_running_loop()
        requests = [records for records, _, _, _, _ in batch]
        spans = [span for _, _, _, span, _ in batch]
        if any(span is not None for span in spans):
            # Queue stage ends at claim: the window wait + any
            # inflight-bound wait the entry paid before this task ran.
            for span in spans:
                if span is not None:
                    span.stamp("queue")
        # Two-phase path when the engine supports it: dispatch (encode +
        # device enqueue + async D2H start) holds the inflight slot, the
        # blocking fetch rides the fetch ring — overlapping the next
        # group's dispatch with this group's host copy. The handle is
        # local to this task, so responses can never cross-wire between
        # overlapped groups (each task owns exactly its batch's futures).
        dispatch = getattr(self.engine, "dispatch_group", None)
        fetch = (
            getattr(self.engine, "fetch_group_wire", None)
            if self.wire_responses
            else None
        ) or getattr(self.engine, "fetch_group", None)
        released = False
        t_dispatch = loop.time()
        try:
            if dispatch is None or fetch is None:
                responses = await loop.run_in_executor(
                    self._executor,
                    (lambda: self.engine.predict_group(requests, tier=tier))
                    if tier is not None
                    else (lambda: self.engine.predict_group(requests)),
                )
                # One-phase engines: the whole call is the best available
                # dispatch-time proxy for the continuous admit deadline.
                self._observe_dispatch_s(loop.time() - t_dispatch)
            else:
                handle = await loop.run_in_executor(
                    self._executor,
                    (lambda: dispatch(requests, tier=tier))
                    if tier is not None
                    else (lambda: dispatch(requests)),
                )
                self._observe_dispatch_s(loop.time() - t_dispatch)
                for span in spans:
                    if span is not None:
                        # Encode rides inside dispatch_group on this plane
                        # (the engine's flat-encode optimization), so the
                        # dispatch stage covers encode + device enqueue.
                        span.stamp("dispatch")
                        span.entry = getattr(handle, "entry", None)
                # Claim the fetch ring BEFORE releasing the dispatch slot:
                # released first, a lagging fetch path would let the drain
                # loop keep dispatching while handles (each pinning live
                # device buffers) pile up un-purgeably at the ring — this
                # order hard-bounds dispatched-but-unfetched groups at
                # max_inflight + fetch_inflight. No deadlock: ring permits
                # free on fetch completion, which never needs a dispatch
                # slot.
                async with self._fetch_ring:
                    self._inflight.release()
                    released = True
                    responses = await loop.run_in_executor(
                        self._executor, fetch, handle
                    )
                for span in spans:
                    if span is not None:
                        span.stamp("device_fetch")
        # Not swallowed: whatever the dispatch raised (device error,
        # encode bug) is re-routed onto every waiter's future, where the
        # request handler surfaces it as a 500.
        except Exception as err:  # tpulint: disable=TPU201
            for _, future, _, _, _ in batch:
                if not future.done():
                    future.set_exception(err)
        else:
            for (_, future, _, _, _), response in zip(batch, responses):
                if not future.done():
                    future.set_result(response)
        finally:
            if not released:
                self._inflight.release()
