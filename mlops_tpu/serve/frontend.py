"""Multi-worker server plane: SO_REUSEPORT front ends + one engine.

``mlops-tpu serve --workers N`` (serve.workers >= 2) replaces the
single-process asyncio server with N front-end PROCESSES that each bind
the same host:port through ``SO_REUSEPORT`` — the kernel load-balances
accepted connections across them, so HTTP parsing, pydantic validation,
JSON serialization, and feature ENCODING (the native C++ encoder) run on
N cores instead of fighting one GIL — all feeding ONE engine process
over the zero-copy shared-memory ring (`serve/ipc.py`). The engine
process owns everything expensive exactly once: the compile cache, the
warmed exec tables, the device monitor accumulator.

Process model (Linux, ISSUE 11): the parent is a thread-free, jax-free
SUPERVISOR. It builds the ring, reserves the port, and forks EVERY other
process — the N front ends and the ENGINE child (which imports jax only
after the fork) — so no fork ever crosses a threaded world (jax/XLA
runtime, dispatch pool, collector — the classic fork-after-threads
deadlock), respawns included. Front ends restart freely: a crashed
worker is respawned within ~0.5 s and re-attaches to its slot partition
via the shm generation counters. ENGINE death is a survivable BROWNOUT,
not an outage: the supervisor forks a replacement that warm-starts from
the AOT compile cache, re-attaches to the same ring under a new
incarnation counter, and REPLAYS every busy slot whose completion never
arrived (`RingService.reattach` — slabs hold the full pre-encoded input
and packed predict is pure, so replayed answers are bit-identical).
While the engine is down, in-flight requests PARK against their PR 9
deadline budgets (200 if the replay lands in time, 504 only on true
budget expiry) and new admissions keep parking until the partition
fills.

Load shedding: each front end's slot partition is its bounded admission
queue, per bucket class (small/coalescable vs large/solo). No free slot
=> immediate ``503`` with ``Retry-After`` — overload degrades into fast
rejections while admitted requests keep their latency, instead of an
unbounded queue melting p99 (the fleet-goodput framing of PAPERS.md
arXiv 2502.06982). During an engine outage the partition doubles as the
parking lot and the shed becomes a BROWNOUT 503: Retry-After advertises
the respawn ETA and the shed counts in ``brownout_shed_total``.

Graceful drain: SIGTERM to the supervisor forwards to every front end;
each stops accepting, finishes in-flight exchanges (the engine child
keeps serving through this window, so parked slots still land), and
exits; the supervisor then SIGTERMs the engine (which drains the ring
service — every accepted slot still gets its response) and exits 0.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import math
import multiprocessing
import os
import signal
import socket
import time
from typing import Any

import numpy as np

from mlops_tpu import faults
from mlops_tpu.config import Config, ServeConfig
from mlops_tpu.serve.httpcore import HttpProtocol, _LazyJson, deadline_response
from mlops_tpu.serve.ipc import RequestRing, RingClient, RingService, ShmWorkerMetrics
from mlops_tpu.serve.metrics import (
    ENG_DOWN_SINCE,
    ENG_RESPAWNS,
    render_ring_metrics,
)
from mlops_tpu.serve.tierroute import SLO_DEFAULT, BrownoutGovernor
from mlops_tpu.serve.wire import (
    EMPTY_RESPONSE_BYTES,
    RESP_EXPIRED,
    RESP_OK,
    encode_response,
)

logger = logging.getLogger("mlops_tpu.serve")

# tpulint Layer-5 manifest: each front-end process is one asyncio loop;
# FrontendServer's mutable state and the ring client's doorbell path are
# EVENT-LOOP CONFINED — blocking work (encode, flight-recorder dumps,
# anomaly scans) goes through run_in_executor, never the loop thread.
TPULINT_LOOP_CONFINED = ("FrontendServer", "RingClient.on_doorbell")

# How long a front end waits for the engine collector to acknowledge a
# forwarded /debug/profile request before cancelling it and answering
# 504. Covers any healthy collector iteration (its idle select tick is
# 1 s) with a wide margin; an operator debug endpoint, not a config knob.
_PROFILE_ACK_S = 10.0


def reuseport_socket(host: str, port: int) -> socket.socket:
    """A bound (not listening) TCP socket with SO_REUSEPORT: every front
    end binds its own; the kernel hashes incoming connections across all
    LISTENING sockets on the tuple. The parent binds one too — never
    listening — purely to pin the port (port=0 resolution, respawn
    safety)."""
    if not hasattr(socket, "SO_REUSEPORT"):  # pragma: no cover - non-Linux
        raise OSError("SO_REUSEPORT is not available on this platform")
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, port))
    return sock


class FrontendServer(HttpProtocol):
    """The ring-backed front end: the same HTTP protocol, validation, and
    two-event logging as the single-process server, with the engine call
    replaced by claim slot -> write pre-encoded arrays -> await the
    completion doorbell -> encode the raw response arrays (the identical
    `encode_response` wire formatter the engine-side fetch uses, so
    responses are bit-identical to the single-process path)."""

    def __init__(
        self,
        config: ServeConfig,
        ring: RequestRing,
        worker_id: int,
        preprocessor: Any,
        trace: Any = None,
        tenancy: Any = None,
        slo: Any = None,
    ) -> None:
        from mlops_tpu.tenancy import QuotaGovernor, TenantRouter

        super().__init__(config)
        self.ring = ring
        self.worker_id = worker_id
        # Tenant fleet (mlops_tpu/tenancy/): one preprocessor per tenant
        # (each bundle's own encode contract, loaded at fork), the header
        # router, and a per-worker weighted max-min admission governor
        # over this worker's slot partition. A plain single preprocessor
        # (every pre-tenancy caller) is the 1-tenant fleet.
        self.preprocessors = (
            list(preprocessor)
            if isinstance(preprocessor, (list, tuple))
            else [preprocessor]
        )
        if len(self.preprocessors) != ring.tenants:
            raise ValueError(
                f"{len(self.preprocessors)} preprocessors for "
                f"{ring.tenants} ring tenants"
            )
        default_index = (
            tenancy.default_index if tenancy is not None else 0
        )
        weights = (
            tenancy.weights
            if tenancy is not None
            else (1.0,) * ring.tenants
        )
        self.tenants = TenantRouter(ring.tenant_names, default_index)
        # ONE GOVERNOR PER SLOT CLASS over the worker's partition: the
        # classes are separate physical pools (a large request can only
        # land in a large slab), so fairness must hold per class — a
        # single partition-wide governor would let a hot tenant park
        # requests in every large slab while staying under its combined
        # floor, starving cold tenants' large traffic with no quota
        # signal. Physical exhaustion within an admitted class still
        # sheds through the classic slot path at claim time. A 1-tenant
        # fleet needs no governor (fairness is trivial), and skipping it
        # keeps single-tenant admission EXACTLY the pre-tenancy path.
        # Event-loop confined like the RingClient free lists — no locks
        # (tenancy/quota.py).
        self.quota = (
            (
                QuotaGovernor(ring.slots_small, weights),
                QuotaGovernor(ring.slots_large, weights),
            )
            if ring.tenants > 1
            else None
        )
        self.client = RingClient(
            ring, worker_id, affinity_slack=config.replica_affinity_slack
        )
        # Brownout-over-shed governor (ISSUE 19, serve/tierroute.py):
        # per worker, fed by this worker's own slot-partition occupancy
        # — the resource whose exhaustion sheds — so each front end
        # demotes its own default-class traffic before its own partition
        # 503s. The demoted CLASS rides the slot header; the engine
        # resolves it to a tier, so a front end never needs the model's
        # tier ladder. ``slo_routing`` (the shared shell's flag, from
        # serve.tier_routing) gates header parsing and the governor
        # together.
        self._brownout = (
            BrownoutGovernor(
                demote_depth=config.brownout_demote_depth,
                restore_depth=config.brownout_restore_depth,
            )
            if self.slo_routing
            else None
        )
        self.metrics = ShmWorkerMetrics(
            ring, worker_id, default_tenant=default_index
        )
        self.trace_plane = "ring"
        self.trace_worker = worker_id
        if trace is not None and trace.enabled:
            # tracewire: this worker's spans -> its own JSONL (per-worker
            # files need no cross-process append coordination); drops
            # land in the worker's shm cell so any scrape sees the fleet
            # total. The engine half-stamps stitch in via `_score`.
            from pathlib import Path

            from mlops_tpu.trace import TraceRecorder

            def _count_drops(n: int) -> None:
                ring.trace_dropped[worker_id] += n

            self.tracer = TraceRecorder(
                Path(trace.dir) / f"spans-w{worker_id}.jsonl",
                capacity=trace.ring_capacity,
                flush_interval_s=trace.flush_interval_s,
                on_drop=_count_drops,
            )
        if slo is not None and slo.enabled and slo.flightrec_enabled:
            # sloscope flight recorder (mlops_tpu/slo/): EACH front end
            # keeps its own evidence ring (its requests, its spans) and
            # dumps it on anomaly — per-process files (pid in the name)
            # need no cross-process coordination, and the tmp+rename
            # discipline means a sibling's kill -9 can never tear a
            # dump. The SLO ENGINE itself runs engine-side (the lead
            # replica's telemetry loop); this worker watches the shm
            # alert flags and the respawn counter for its dump
            # triggers (_run_frontend's watchdog).
            from mlops_tpu.slo import FlightRecorder

            def _count_dump(path) -> None:
                # Single-writer shm cell (like trace_dropped): any
                # worker's scrape shows the fleet's landed dumps.
                ring.flight_dumps[worker_id] += 1

            self.flightrec = FlightRecorder(
                slo.flightrec_dir,
                capacity=slo.flightrec_capacity,
                cooldown_s=slo.flightrec_cooldown_s,
                keep=slo.flightrec_keep,
                source="ring",
                worker=worker_id,
                spike_errors=slo.flightrec_spike_errors,
                spike_window_s=slo.flightrec_spike_window_s,
                on_dump=_count_dump,
            )
        # The ring's large slabs are sized by the parent to the (possibly
        # bucket-clamped) request cap; the slab capacity is the contract.
        self.max_batch = min(config.max_batch, ring.large_rows)
        # Encoding runs in a tiny thread pool: the native C++ encoder
        # releases the GIL, and a 256-row encode would otherwise stall
        # the accept loop.
        import concurrent.futures

        self._encode_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix=f"encode-w{worker_id}"
        )

    # ------------------------------------------------------------- routes
    def _ready(self) -> bool:
        return self.ring.engine_ready and not self.draining

    def _outage_stamped(self) -> bool:
        """True when the supervisor has stamped at least one engine
        replica's death AND no replica is ready — a real FULL outage
        (every replica down), not a cold boot and not the partial-outage
        brownout the router absorbs by routing around the hole."""
        return not self.ring.engine_ready and bool(
            (self.ring.eng_vals[:, ENG_DOWN_SINCE] > 0).any()
        )

    def _respawn_retry_after(self) -> int:
        """Retry-After seconds for a BROWNOUT 503 (every engine replica
        down, parking full): the configured respawn ETA minus how long
        the outage has been running — a well-behaved client's retry
        lands just after the first replacement's replay finishes,
        instead of hammering into the same full parking lot. The outage
        clock starts at the EARLIEST still-down replica's stamp (the
        furthest-along respawn is what ends a full outage). Never below
        1 s (the header is integer seconds, and 0 invites an immediate
        retry)."""
        eta = self.config.engine_respawn_eta_s
        stamps = [
            float(v) for v in self.ring.eng_vals[:, ENG_DOWN_SINCE] if v > 0
        ]
        down_since = min(stamps) if stamps else 0.0
        remaining = eta - (time.monotonic() - down_since) if down_since else eta
        if remaining <= 0:
            # The ETA estimate is already blown (a respawn slower than
            # advertised — e.g. the AOT cache was cold and the
            # replacement is recompiling): re-advertise the FULL ETA so
            # clients pace their retries at the estimate's cadence
            # instead of hammering 1 s retries into a still-full parking
            # lot for the whole recompile.
            remaining = eta
        return max(1, math.ceil(remaining))

    def _slo_view(self):
        # /healthz verdict source (httpcore._healthz): the fleet view the
        # lead replica last mirrored into shm — rows never written render
        # the zero baseline (last-known-values contract).
        if not self.ring.slo_armed:
            return None
        from mlops_tpu.slo.engine import read_slo_view

        return read_slo_view(
            self.ring.slo_vals,
            self.ring.alert_vals,
            tuple(self.ring.tenant_names),
            tuple(float(x) for x in self.ring.slo_meta[:4]),
        )

    def _engine_down(self) -> bool:
        # The /healthz verdict's "down" condition IS the full-outage
        # predicate the brownout shed uses.
        return self._outage_stamped()

    async def _metrics_endpoint(self):
        # Every gauge renders straight from shared memory — all workers'
        # request/latency blocks, the ring depth/shed counters, and the
        # engine-process monitor aggregate (single-flight in the engine's
        # telemetry loop; a front end never touches the device). Any
        # worker can serve the scrape with the full fleet view, which is
        # what SO_REUSEPORT requires: Prometheus lands on a random one.
        return (
            200,
            render_ring_metrics(self.ring),
            "text/plain; version=0.0.4",
        )

    async def _score(
        self,
        record_dicts: list[dict],
        request_id: str,
        deadline: float | None = None,
        span=None,
        tenant: int = 0,
        slo: int = SLO_DEFAULT,
    ):
        """The ring-backed scoring hook under the shared `_predict` shell
        (serve/httpcore.py): per-tenant quota, then slot admission, then
        encode, then the slot round trip. The deadline budget
        (``x-request-deadline-ms``) decrements across every stage:
        checked before the encode pool is touched, stamped into the slot
        header so the ENGINE can complete an expired descriptor without
        dispatching, and bounding the completion wait — each stage
        answers the documented 504 rather than doing work the client
        stopped waiting for.

        ``tenant`` (resolved by the shell from ``x-tenant``) selects the
        preprocessor, tags the slot so the engine dispatches the right
        bundle, and is the quota/metrics dimension."""
        if not record_dicts:
            return EMPTY_RESPONSE_BYTES
        if self.quota is None:
            # 1-tenant fleet: fairness is trivial; admission is exactly
            # the pre-tenancy slot path.
            return await self._score_admitted(
                record_dicts, request_id, deadline, span, tenant, slo
            )
        # QUOTA BEFORE EVERYTHING (weighted max-min, tenancy/quota.py),
        # per slot CLASS — the request's row count picks the physical
        # pool it will claim from, and fairness is enforced over that
        # pool: a hot tenant past its share sheds against its OWN quota
        # while every other tenant's reserved floor in EACH class stays
        # claimable. The 503 + Retry-After is the same wire contract as
        # the slot shed, with the tenant and the word "quota" in the
        # detail and the rejection counted per tenant
        # (mlops_tpu_tenant_quota_shed_total — quota sheds are NOT
        # physical sheds: shed_total stays a pure slot-exhaustion
        # counter operators can difference against). A physically FULL
        # class is NOT a quota event: it falls through to the classic
        # slot-shed contract (class detail, brownout ETA during an
        # engine outage) via claim() below.
        governor = self.quota[
            0 if len(record_dicts) <= self.ring.small_rows else 1
        ]
        verdict = governor.try_acquire(tenant)
        if verdict == "quota":
            self.client.count_quota_shed(tenant)
            retry_s = self.config.shed_retry_after_s
            name = self.tenants.names[tenant]
            return (
                503,
                {
                    "detail": f"tenant {name!r} over quota; retry in "
                    f"{retry_s}s"
                },
                "application/json",
                {"retry-after": str(retry_s)},
            )
        if verdict == "full":
            # No governor hold to release: score through the claim path,
            # which answers the physical-shed 503 (claim can still
            # succeed if a slot freed since the check — benign).
            return await self._score_admitted(
                record_dicts, request_id, deadline, span, tenant, slo
            )
        try:
            return await self._score_admitted(
                record_dicts, request_id, deadline, span, tenant, slo
            )
        finally:
            # The governor tracks ADMITTED REQUESTS, not slots: a zombie
            # slot awaiting a late engine completion keeps holding its
            # slot (never its quota), so a stalled engine degrades into
            # slot sheds, never into quota leakage.
            governor.release(tenant)

    async def _score_admitted(
        self,
        record_dicts: list[dict],
        request_id: str,
        deadline: float | None,
        span,
        tenant: int,
        slo: int = SLO_DEFAULT,
    ):
        from mlops_tpu.schema import records_to_columns

        # Injection point (mlops_tpu/faults): kill = a front-end worker
        # crash mid-request — the supervisor-respawn + slot-quarantine
        # path the chaos smoke drives.
        faults.fire("serve.frontend.predict")
        n = len(record_dicts)
        # ADMISSION BEFORE ENCODE: a to-be-shed request must cost nothing
        # — the row count is known from the validated records, so the
        # shed 503 never queues through (or wastes) the encode pool, and
        # its latency stays flat no matter how deep the overload. On a
        # multi-tenant plane the claim may not cross classes: the quota
        # governor admitted against the class the row count names, so an
        # overflow slab would hold capacity the other class's governor
        # never accounted (tenancy/quota.py).
        # Brownout before shed (ISSUE 19): when this worker's partition
        # occupancy crosses the governor's threshold, default-class
        # requests demote to the cheap class BEFORE claiming — the
        # demoted class rides the slot header and the engine serves the
        # cheaper tier, so pressure turns into faster (still-correct)
        # answers instead of 503s. Explicit cheap/accurate headers are
        # never overridden, and the governor auto-restores once
        # occupancy falls back through the restore threshold.
        demoted = False
        if self._brownout is not None:
            self._brownout.observe(self.client.pressure())
            slo, demoted = self._brownout.route(slo)
        slot = self.client.claim(
            n, tenant, allow_overflow=self.quota is None, slo=slo
        )
        if slot is None:
            # Bounded admission per bucket class: shed FAST with a
            # Retry-After instead of queueing — the slots free up as
            # in-flight responses land, so a well-behaved client's retry
            # lands in capacity. During an ENGINE OUTAGE (ISSUE 11) the
            # partition doubles as the parking lot, so a full partition
            # means "parking full": the shed becomes a BROWNOUT 503
            # whose Retry-After advertises the respawn ETA, counted
            # separately — shed latency stays flat either way.
            self.client.count_shed(n, tenant)
            cls = "small" if n <= self.ring.small_rows else "large"
            if self._outage_stamped():
                # A real OUTAGE (the supervisor stamped the engine's
                # death), not a cold boot: first-boot warmup can take
                # minutes and its sheds must advertise the steady-state
                # Retry-After below, not a ~5 s respawn ETA that would
                # hammer retries into a still-warming plane.
                self.ring.brownout_shed[self.worker_id] += 1
                retry_s = self._respawn_retry_after()
                return (
                    503,
                    {
                        "detail": "engine restarting and parking is "
                        f"full (no free {cls} request slot); retry in "
                        f"{retry_s}s"
                    },
                    "application/json",
                    {"retry-after": str(retry_s)},
                )
            retry_s = self.config.shed_retry_after_s
            return (
                503,
                {
                    "detail": "overloaded: no free "
                    f"{cls} "
                    f"request slot; retry in {retry_s}s"
                },
                "application/json",
                {"retry-after": str(retry_s)},
            )
        if demoted:
            # Counted only for ADMITTED requests: a demote-then-shed is a
            # shed (the demotion never served anyone), so the counter
            # stays "requests served below their requested class".
            self.client.count_demotion(brownout=True)
        submitted = False
        try:
            loop = asyncio.get_running_loop()
            if deadline is not None and loop.time() >= deadline:
                # Budget spent before the encode pool was touched (slot
                # waits, slow header/body): release the claim unused and
                # shed the dead work — the cheap 504.
                self.client.release(slot)
                slot = None
                self.metrics.count_deadline_expired()
                return deadline_response()
            # Encode BEFORE enqueue (the tentpole's division of labor):
            # the engine process receives ready-to-scatter arrays and
            # spends its cycles on device dispatch only. The native
            # encoder releases the GIL, so the pool keeps the accept loop
            # responsive through a 256-row encode.
            preprocessor = self.preprocessors[tenant]
            ds = await loop.run_in_executor(
                self._encode_pool,
                lambda: preprocessor.encode(
                    records_to_columns(record_dicts)
                ),
            )
            if span is not None:
                span.stamp("encode")
            # The slot header carries the absolute deadline (the loop
            # clock IS time.monotonic, which the engine process shares):
            # a descriptor that expires while queued in the ring comes
            # back RESP_EXPIRED without ever dispatching.
            future = self.client.submit(
                slot, ds.cat_ids, ds.numeric, deadline=deadline
            )
            submitted = True
            timeout = self.config.request_timeout_s or None
            if deadline is not None:
                remaining = deadline - loop.time()
                timeout = min(timeout or remaining, remaining)
            # Parking (ISSUE 11): a request admitted while the engine is
            # down holds its slot and WAITS — the respawned engine's
            # re-attach replays it (200 if the budget allows) or the
            # deadline below turns it into the documented 504. The gauge
            # counts requests currently parked this way; like the
            # brownout shed above it requires a supervisor-stamped
            # OUTAGE, so routine first-boot warmup waits never read as
            # outage evidence on dashboards.
            parked = self._outage_stamped()
            if parked:
                self.ring.parked[self.worker_id] += 1
            try:
                if timeout is not None:
                    status = await asyncio.wait_for(future, max(timeout, 0.0))
                else:
                    status = await future
            except asyncio.TimeoutError:
                logger.error(
                    "prediction deadline (%.1fs) exceeded request_id=%s — "
                    "engine stall?",
                    timeout,
                    request_id,
                )
                self.client.abandon(slot)
                slot = None
                return deadline_response(
                    f"prediction exceeded the {timeout:g}s deadline"
                )
            finally:
                if parked:
                    self.ring.parked[self.worker_id] -= 1
            if status == RESP_EXPIRED:
                # The engine shed the dead work (already counted engine-
                # side); the completion is the proof the slab is quiescent.
                self.client.release(slot)
                slot = None
                return deadline_response()
            if status != RESP_OK:
                # The engine process logged the traceback; the wire
                # contract matches the single-process 500.
                self.client.release(slot)
                slot = None
                return 500, {"detail": "prediction failed"}, "application/json"
            if span is not None:
                self._stitch_engine_half(span, slot)
            pred, out, drift = self.client.response_arrays(slot)
            # encode_response (serve/wire.py) goes straight from the slab
            # views to wire bytes — byte-identical to the old
            # format_response + json.dumps, but the handler's event loop
            # never re-serializes the dict (the encode-bound residue).
            # The encode materializes every float, so the slab is
            # quiescent before release.
            response = encode_response(pred, out, drift)
            self.client.release(slot)
            slot = None
            return response
        # Top-of-handler boundary (same contract as the single-process
        # server): ANY failure becomes a logged 500, never a dropped
        # connection or a leaked slot.
        except Exception:  # tpulint: disable=TPU201
            logger.exception("prediction failed request_id=%s", request_id)
            if slot is not None:
                if submitted:
                    self.client.abandon(slot)
                else:
                    self.client.release(slot)
            return 500, {"detail": "prediction failed"}, "application/json"

    def _stitch_engine_half(self, span, slot: int) -> None:
        """Fold the engine process's half-span (the four CLOCK_MONOTONIC
        stamps + compiled-entry encoding it wrote into the slot header —
        serve/ipc.py ``resp_trace``) into this request's span: one
        stitched record whose stages are monotone and non-overlapping by
        the span's clamping rule. Read between completion and release —
        the same ownership window as the response slab."""
        stamps = self.ring.resp_trace[slot]
        collect, jobstart, dispatched, fetched = (
            float(stamps[0]), float(stamps[1]),
            float(stamps[2]), float(stamps[3]),
        )
        if not (collect and jobstart and dispatched and fetched):
            return  # engine ran untraced (armed mid-flight); keep ours
        span.stamp_at("ring_wait", collect)
        span.stamp_at("engine_queue", jobstart)
        span.stamp_at("dispatch", dispatched)
        span.stamp_at("device_fetch", fetched)
        # Which engine replica served (the router's choice, read from the
        # slot tag inside the same ownership window): trace-report
        # --replica slices per-replica latency pictures from this.
        span.replica = int(self.ring.slot_replica[slot]) % self.ring.replicas
        kind, geom = int(stamps[4]), int(stamps[5])
        if kind == 1:
            span.entry = f"bucket_{geom}"
        elif kind == 2:
            span.entry = f"group_{geom // 100000}x{geom % 100000}"

    async def _profile(self, action: str):
        """Forward /debug/profile to the ENGINE process (the only one
        holding the device) through the ring's single-word control
        channel: claim the channel non-blocking (busy -> 409), publish
        the request word, await the collector's acknowledgement, answer
        with the shared wire shapes (`httpcore.profile_payload`)."""
        from mlops_tpu.serve.httpcore import profile_payload

        if not self.config.profile_dir:
            return profile_payload(404, action, "")
        code = {"start": 1, "stop": 2}.get(action)
        if code is None:
            return 404, {"detail": "not found"}, "application/json"
        ring = self.ring
        token = ring.try_claim_profile()
        if token is None:
            return 409, {"detail": "profile control busy"}, "application/json"
        try:
            seq = ring.post_profile_request(code)
            deadline = asyncio.get_running_loop().time() + _PROFILE_ACK_S
            while True:
                status = ring.read_profile_ack(seq)
                if status is not None:
                    break
                if asyncio.get_running_loop().time() >= deadline:
                    # Engine collector never answered (stalled in a long
                    # compile / chaos stall): CANCEL the pending word so
                    # the start/stop does not execute later against a
                    # client already told it failed.
                    ring.cancel_profile_request(seq, token)
                    status = 504
                    break
                await asyncio.sleep(0.02)
        finally:
            ring.release_profile(token)
        return profile_payload(status, action, self.config.profile_dir)

    def close_tracer(self) -> None:
        """Drain-path flush of this worker's span recorder (joins the
        writer thread; call only once the in-flight exchanges finished)."""
        if self.tracer is not None:
            self.tracer.close()

    # ---------------------------------------------------------- lifecycle
    async def start(self) -> asyncio.AbstractServer:
        """Bind this worker's own SO_REUSEPORT socket and hook the
        completion doorbell into the event loop."""
        sock = reuseport_socket(self.config.host, self.config.port)
        loop = asyncio.get_running_loop()
        for replica in range(self.ring.replicas):
            # One reader per engine replica's completion doorbell: each
            # (worker, replica) queue has its own counted-credit fence.
            loop.add_reader(
                self.ring.worker_doorbell(self.worker_id, replica).fileno(),
                self.client.on_doorbell,
                replica,
            )
            # One unconditional kick per replica: a respawned client may
            # have seeded credit for completions whose doorbell the DEAD
            # incarnation already drained — the eventfd sits at 0, so
            # add_reader alone would never fire, and with every slot
            # quarantined no new traffic could ring it either (permanent
            # 503s). A spurious call is harmless (zero credit pops
            # nothing).
            loop.call_soon(self.client.on_doorbell, replica)
        return await asyncio.start_server(self.handle_connection, sock=sock)

    def stop_doorbell(self) -> None:
        for replica in range(self.ring.replicas):
            with contextlib.suppress(Exception):
                asyncio.get_running_loop().remove_reader(
                    self.ring.worker_doorbell(
                        self.worker_id, replica
                    ).fileno()
                )


# --------------------------------------------------------------- children
def _frontend_main(
    worker_id: int,
    config: ServeConfig,
    ring: RequestRing,
    preprocess_path: str | list[str],
    trace: Any = None,
    tenancy: Any = None,
    slo: Any = None,
) -> None:
    """Front-end child process entry (forked — everything arrives by
    inheritance). Never imports jax, never touches the device.
    ``preprocess_path`` is one path per tenant (a bare string = the
    1-tenant fleet)."""
    from mlops_tpu.data.encode import Preprocessor

    paths = (
        [preprocess_path]
        if isinstance(preprocess_path, str)
        else list(preprocess_path)
    )
    preprocessors = [Preprocessor.load(path) for path in paths]
    try:
        asyncio.run(
            _run_frontend(
                worker_id, config, ring, preprocessors, trace, tenancy, slo
            )
        )
    except KeyboardInterrupt:  # pragma: no cover - interactive stop
        pass


async def _run_frontend(
    worker_id: int,
    config: ServeConfig,
    ring: RequestRing,
    preprocessor,
    trace: Any = None,
    tenancy: Any = None,
    slo: Any = None,
) -> None:
    server = FrontendServer(
        config, ring, worker_id, preprocessor, trace, tenancy, slo
    )
    srv = await server.start()
    logger.info(
        "frontend %d serving %s on %s:%s (pid %d)",
        worker_id, config.service_name, config.host, config.port, os.getpid(),
    )
    loop = asyncio.get_running_loop()
    if config.loop_lag_monitor:
        # Runtime half of the Layer-5 discipline, per worker process:
        # the watchdog drains each window max into this worker's shm
        # cell, so any worker's scrape renders the fleet's lag gauges.
        from mlops_tpu.analysis.loopcheck import LoopLagSanitizer

        server.loop_monitor = LoopLagSanitizer(
            slow_ms=config.loop_lag_slow_ms
        )
        server.loop_monitor.attach(loop)
        logger.info(
            "frontend %d: loop-lag sanitizer armed (slow_ms=%g)",
            worker_id, config.loop_lag_slow_ms,
        )
    draining = asyncio.Event()

    def _drain(signum=None, frame=None) -> None:
        server.draining = True
        draining.set()
        srv.close()
        for w in list(server._connections - server._busy):
            w.close()  # idle keep-alive readers see EOF; handlers exit

    with contextlib.suppress(NotImplementedError, RuntimeError):
        loop.add_signal_handler(signal.SIGTERM, _drain)
        loop.add_signal_handler(signal.SIGINT, _drain)

    parent = os.getppid()

    def _read_alert_flags() -> dict:
        # ONE snapshot rule for the edge detector's seed and its
        # per-pass read: the two must stay identical or a respawned
        # worker would re-trigger dumps on historical alerts.
        from mlops_tpu.slo.engine import ENGINE_ALERTS

        return {
            (alert, tenant): bool(ring.alert_vals[t, a_i])
            for a_i, alert in enumerate(ENGINE_ALERTS)
            for t, tenant in enumerate(ring.tenant_names)
        }

    def _watch_anomalies(state: dict) -> None:
        # Flight-recorder triggers this worker can only see in shm
        # (mlops_tpu/slo/): an engine respawn (the supervisor bumped a
        # replica's counter) and alert flags flipping ACTIVE (the lead
        # replica's SLO engine mirrored a rising edge). Edge-detected
        # against the previous watchdog pass, so a sustained alert
        # triggers once (plus the recorder's own cooldown).
        from mlops_tpu.slo.engine import ALERT_SEVERITY

        respawns = int(ring.eng_vals[:, ENG_RESPAWNS].sum())
        if respawns > state["respawns"]:
            server.flightrec.trigger("engine_respawn")
        state["respawns"] = respawns
        flags = _read_alert_flags()
        for key, active in flags.items():
            if active and not state["alerts"].get(key):
                alert, tenant = key
                server.flightrec.note_alert(
                    alert, tenant, ALERT_SEVERITY[alert]
                )
        state["alerts"] = flags

    async def _watch_plane() -> None:
        # Two drain triggers besides the direct SIGTERM: the shared ring
        # drain flag (a front end forked mid-drain, or a missed signal),
        # and a DEAD parent — the supervisor in production, the test
        # harness process otherwise; either way nobody can respawn this
        # worker anymore, so drain rather than linger. ENGINE death is
        # deliberately NOT a drain trigger (ISSUE 11): the supervisor
        # respawns the engine, in-flight requests park against their
        # deadline budgets, and the replay answers them — the watchdog
        # split that turned engine death from an outage into a brownout.
        # Seed the edge detector from the CURRENT shm state: a worker
        # (re)spawned into a plane mid-incident must not re-trigger on
        # history it never witnessed — only on new transitions.
        anomaly_state = {
            "respawns": int(ring.eng_vals[:, ENG_RESPAWNS].sum()),
            "alerts": {},
        }
        if server.flightrec is not None and ring.slo_armed:
            anomaly_state["alerts"] = _read_alert_flags()
        while not draining.is_set():
            await asyncio.sleep(1.0)
            if server.loop_monitor is not None:
                # Single-writer shm publish (this worker's own cell):
                # the gauge shows each worker's worst callback over the
                # last watchdog window, 0.0 when the loop stayed smooth.
                server.metrics.set_loop_lag(
                    server.loop_monitor.snapshot_ms()
                )
            if server.flightrec is not None:
                # Executor: a triggered dump writes a file, which must
                # not stall the accept loop (the recorder is
                # thread-safe; one leaf lock).
                await loop.run_in_executor(
                    None, _watch_anomalies, anomaly_state
                )
            if ring.draining:
                logger.info("frontend %d: ring drain flag set; draining",
                            worker_id)
                _drain()
            elif os.getppid() != parent:
                logger.error("frontend %d: parent process died; draining",
                             worker_id)
                _drain()

    watchdog = asyncio.create_task(_watch_plane())
    await draining.wait()
    # Busy exchanges get a bounded window to finish their responses and
    # in-flight ring slots to land (serve.drain_deadline_s; the kubelet's
    # grace period is the hard stop).
    deadline = loop.time() + config.drain_deadline_s
    while (server._busy or server.client.pending_count()) and (
        loop.time() < deadline
    ):
        await asyncio.sleep(0.05)
    for w in list(server._connections):
        w.close()
    server.stop_doorbell()
    watchdog.cancel()
    if server.loop_monitor is not None:
        server.loop_monitor.detach()
        server.loop_monitor = None
    with contextlib.suppress(asyncio.TimeoutError):
        await asyncio.wait_for(srv.wait_closed(), timeout=5)
    # AFTER the busy/pending drain above: every finished exchange has
    # recorded its span; the final flush guarantees no torn or lost
    # lines on SIGTERM (O_APPEND single-write discipline in the writer).
    await asyncio.get_running_loop().run_in_executor(
        None, server.close_tracer
    )
    if server.flightrec is not None:
        # Evidence-gated SIGTERM dump (a clean drain writes nothing).
        await asyncio.get_running_loop().run_in_executor(
            None, server.flightrec.dump_if_evidence, "sigterm"
        )
    logger.info("frontend %d drained; exiting", worker_id)


def start_frontends(
    config: ServeConfig,
    ring: RequestRing,
    preprocess_path: str | list[str],
    trace: Any = None,
    tenancy: Any = None,
    slo: Any = None,
) -> list[multiprocessing.Process]:
    """Fork one front-end process per worker (call BEFORE any jax backend
    initializes in the parent — the children inherit a clean world)."""
    return [
        _respawn(
            config, ring, preprocess_path, worker_id, trace, tenancy, slo
        )
        for worker_id in range(ring.workers)
    ]


def _write_pid_files(engine_pids: list[int | None]) -> None:
    """Operator convenience (ISSUE 11 satellite): pid files live under
    ``runs/`` (gitignored), never at the repo root — ``serve.pid`` is the
    supervisor (SIGTERM target for a drain), ``engine.pid`` the current
    engine incarnations ONE PID PER LINE, replica order (SIGKILL targets
    for a survivability drill — line k is replica k). Best-effort: a
    read-only working directory must not fail serving."""
    try:
        os.makedirs("runs", exist_ok=True)
        with open(os.path.join("runs", "serve.pid"), "w") as f:
            f.write(f"{os.getpid()}\n")
        pids = [pid for pid in engine_pids if pid is not None]
        if pids:
            with open(os.path.join("runs", "engine.pid"), "w") as f:
                f.write("".join(f"{pid}\n" for pid in pids))
    except OSError:
        logger.warning(
            "could not write pid files under runs/", exc_info=True
        )


def _engine_main(
    config: Config,
    ring: RequestRing,
    bundle_dir: str,
    trace: Any = None,
    tenancy: Any = None,
    replica: int = 0,
) -> None:
    """Engine child process entry (forked from the jax-free supervisor —
    ring, doorbells, and locks arrive by inheritance; jax imports happen
    HERE, after the fork, so no backend thread ever crosses one). Loads
    the tenant fleet's bundles (the 1-tenant "default" fleet when no
    tenants.toml was given), warms through the AOT compile cache with
    architecture-level executable dedupe (`tenancy/registry.py`),
    re-attaches to the ring under a fresh incarnation — replaying any
    slots a dead predecessor left busy, each under its shm-tagged tenant
    (`RingService.reattach`) — and serves until SIGTERM or supervisor
    death. ``kill -9`` of this process is the survivable-engine
    tentpole: the supervisor forks a replacement that runs this same
    function against the same shm ring."""
    from mlops_tpu.compilecache.cache import from_config
    from mlops_tpu.tenancy import TenantRegistry, single_tenant_config

    serve_cfg = config.serve
    stop = {"flag": False}

    def _stop(signum=None, frame=None) -> None:
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)

    if tenancy is None:
        tenancy = single_tenant_config(bundle_dir)
    # Per-replica device assignment (post-review fix): when THIS
    # process's jax visibility spans enough devices for the whole fleet
    # (a dev box, the forced-host-device sim — production multi-chip
    # deployments scope visibility per process instead, making each
    # replica's device 0 its own chip), replica r takes its own
    # S-device slice so replicas actually occupy E·S devices instead of
    # all stacking on device 0. The slice index rides into the AOT
    # cache key (device_tag), so differently-placed artifacts never
    # cross-load. With too few visible devices, replicas share the
    # default device — still useful when dispatches are
    # latency/transport-bound (the bench's simulated-device framing).
    import jax

    shards = serve_cfg.model_shards
    device_index: int | None = None
    if ring.replicas > 1 and jax.device_count() >= ring.replicas * shards:
        device_index = replica * shards
        logger.info(
            "engine replica %d pinned to device slice [%d, %d)",
            replica, device_index, device_index + shards,
        )
    registry = TenantRegistry(
        tenancy,
        buckets=tuple(serve_cfg.warmup_batch_sizes),
        service_name=serve_cfg.service_name,
        enable_grouping=serve_cfg.batch_window_ms > 0,
        compile_cache=from_config(config),
        warmup_workers=config.cache.warmup_workers,
        model_shards=serve_cfg.model_shards,
        device_index=device_index,
        serve_tier=serve_cfg.serve_tier,
        tier_routing=serve_cfg.tier_routing,
    )
    engines = registry.engines
    if trace is not None:
        # Shape histograms accumulate ENGINE-side (the only process that
        # dispatches); ONE shared ShapeStats across the fleet — entries
        # are keyed by compiled shape, which tenants share by design —
        # mirrored into shm for every front end's /metrics.
        from mlops_tpu.trace import ShapeStats

        stats = ShapeStats()
        for eng in engines:
            eng.set_shape_stats(stats)
    slo_cfg = getattr(config, "slo", None)
    ledger = None
    if slo_cfg is not None and slo_cfg.ledger_dir:
        # Device-time cost ledger (slo/ledger.py): ONE per engine
        # process, shared across the tenant fleet (entries key by
        # entry + model fingerprint, so arch twins correctly share);
        # sharded per replica on disk so concurrent flushes never
        # clobber a sibling's totals.
        from mlops_tpu.slo import CostLedger

        ledger = CostLedger(
            slo_cfg.ledger_dir,
            flush_interval_s=slo_cfg.ledger_flush_s,
            shard=f"r{replica}" if ring.replicas > 1 else "",
        )
        for eng in engines:
            eng.set_cost_ledger(ledger)
        logger.info("cost ledger armed -> %s", ledger.path)
    service = RingService(
        engines[0],
        ring,
        max_group=serve_cfg.max_group,
        max_inflight=serve_cfg.max_inflight,
        threads=serve_cfg.max_workers,
        monitor_fetch_every_s=serve_cfg.monitor_fetch_every_s,
        monitor_fetch_every_requests=serve_cfg.monitor_fetch_every_requests,
        engines=engines,
        replica=replica,
    )
    service.cost_ledger = ledger
    if slo_cfg is not None and slo_cfg.enabled and replica == 0:
        # SLO engine on the LEAD replica only (one writer for the shm
        # alert rows; every replica reads the same fleet-wide counters
        # anyway): evaluated each telemetry tick from the ring's shm
        # request matrices, mirrored for the front ends' renders. The
        # lifecycle breaker flags ride in from the life rows so a broken
        # retrain path alerts through the same channel as a burn.
        from mlops_tpu.serve.metrics import LIFE_BREAKER_OPEN
        from mlops_tpu.slo import SLOEngine
        from mlops_tpu.slo.engine import SLO_NAMES, read_slo_view

        def _ring_breakers() -> dict:
            return {
                name: bool(ring.life_vals[t, LIFE_BREAKER_OPEN])
                for t, name in enumerate(ring.tenant_names)
            }

        # Respawn-base seed (the ISSUE 11 monotone-counter discipline):
        # a respawned engine's fresh evaluator re-baselines against the
        # surviving shm request counters — seed it with the dead
        # incarnation's last-published totals so slo_*_total never
        # regresses across a respawn (first boot reads the zero view).
        prev = read_slo_view(
            ring.slo_vals, ring.alert_vals, tuple(ring.tenant_names),
            tuple(float(x) for x in ring.slo_meta[:4]),
        )
        prior = {
            name: (
                prev[name]["slos"][SLO_NAMES[0]]["good"],
                prev[name]["slos"][SLO_NAMES[0]]["total"],
                prev[name]["slos"][SLO_NAMES[1]]["good"],
                prev[name]["slos"][SLO_NAMES[1]]["total"],
            )
            for name in ring.tenant_names
        }
        service.slo = SLOEngine(
            slo_cfg,
            tuple(ring.tenant_names),
            source=lambda: ring.slo_counts(slo_cfg.latency_threshold_ms),
            breaker_source=_ring_breakers,
            prior_counts=prior,
        )
        logger.info("sloscope armed (lead replica evaluator)")
    if serve_cfg.profile_dir and replica == 0:
        # /debug/profile: front ends forward start/stop through the
        # ring's single control word, answered by the LEAD replica (one
        # device trace at a time).
        from mlops_tpu.serve.server import JaxProfiler

        service.profiler = JaxProfiler(serve_cfg.profile_dir).control
    # Warmup -> re-attach (incarnation bump + busy-slot replay) -> serve:
    # parked requests are re-answered by the replay BEFORE this
    # replica's ready flag flips, so "ready" means "this replica's share
    # of the outage is fully healed". Replicas warm from the SAME
    # compile cache — replica 0's cold boot compiles, every sibling (and
    # every respawn) deserializes.
    warm_report = registry.warmup()
    attach = service.reattach()
    service.start()
    ring.set_ready(True, replica)
    ring.eng_vals[replica, ENG_DOWN_SINCE] = 0.0
    logger.info("warmup complete; ready %s", _LazyJson(warm_report))
    logger.info(
        "engine replica %d incarnation %d attached %s",
        replica, attach["incarnation"], _LazyJson(attach),
    )
    if config.lifecycle.enabled and replica == 0:
        # The closed loops run ENGINE-SIDE (the only process with the
        # device, the exec tables, and the compile cache) — ONE
        # controller PER TENANT, each on a tenant-namespaced state dir,
        # so tenant A drifting retrains/shadows/promotes A alone; the
        # telemetry loop mirrors each controller's gauges into its
        # tenant's shm row. The fork-time preprocessors are the encode
        # contract, so every controller is forced onto its incumbent
        # preprocessor. A respawned engine restarts each loop from its
        # on-disk reservoir state. (The 1-tenant "default" fleet keeps
        # the un-namespaced state dir — bit-identical to pre-tenancy.)
        from mlops_tpu.lifecycle import LifecycleController
        from mlops_tpu.tenancy import tenant_scoped_config

        single_default = len(registry) == 1 and registry.names[0] == "default"
        service.lifecycles = []
        for name, eng in zip(registry.names, engines):
            scoped = (
                config if single_default
                else tenant_scoped_config(config, name)
            )
            controller = LifecycleController(
                eng, scoped, force_incumbent_preprocessor=True
            )
            controller.start()
            service.lifecycles.append(controller)
        service.lifecycle = service.lifecycles[0]
        logger.info(
            "lifecycle controllers started (engine process, %d tenants)",
            len(service.lifecycles),
        )
    autotune = None
    if getattr(config, "autotune", None) is not None and config.autotune.enabled:
        # gridtuner (mlops_tpu/autotune/), engine-side like the
        # lifecycle loops: the LEAD replica fits/searches/applies and
        # persists the plan (plan_dir/plan.json, atomic); every sibling
        # runs an ADOPT-mode controller that applies the lead's plan
        # locally — warming through the SHARED compile cache, so the
        # lead paid each new bucket's compile exactly once and siblings
        # deserialize. Started after warmup (it measures the warmed
        # grid); gauges mirror into this replica's shm row each
        # telemetry tick.
        from mlops_tpu.autotune import AutotuneController

        autotune = AutotuneController(
            engines[0],
            config.autotune,
            adopt=(replica != 0),
            replica=replica,
        )
        autotune.start()
        service.autotune = autotune
        logger.info(
            "autotune controller started (replica %d, %s mode)",
            replica, "adopt" if replica != 0 else "plan",
        )

    supervisor = os.getppid()
    rc = 0
    try:
        # NOT drained by the ring's drain flag: during a graceful drain
        # the front ends finish their in-flight slots FIRST and this
        # process must keep answering them; the supervisor SIGTERMs the
        # engine only after the front ends have joined.
        while not stop["flag"]:
            time.sleep(0.5)
            # Injection point (mlops_tpu/faults): kill = deterministic
            # in-process engine death (the chaos path without needing a
            # pid from outside); raise = an engine main-loop failure —
            # either way the supervisor forks a replacement.
            faults.fire("serve.engine.exit")
            if os.getppid() != supervisor:
                logger.error(
                    "engine: supervisor died; exiting for restart"
                )
                rc = 1
                break
    finally:
        ring.set_ready(False)
        for _, controller in service._tenant_lifecycles():
            controller.stop()
        if autotune is not None:
            autotune.stop()
        service.stop()
        if ledger is not None:
            ledger.close()  # final atomic flush
        logger.info("engine process drained; exiting")
    if rc:
        raise SystemExit(rc)


def _spawn_engine(
    config: Config,
    ring: RequestRing,
    bundle_dir: str,
    trace: Any = None,
    tenancy: Any = None,
    replica: int = 0,
) -> multiprocessing.Process:
    """Fork one engine replica child from the (thread-free, jax-free)
    supervisor — first boot and every respawn run the identical path."""
    ctx = multiprocessing.get_context("fork")
    proc = ctx.Process(
        target=_engine_main,
        args=(config, ring, bundle_dir, trace, tenancy, replica),
        name=f"mlops-tpu-engine-{replica}",
    )
    proc.start()
    return proc


# --------------------------------------------------------------- parent
# Engine crash-loop guard: more than this many engine deaths inside one
# 60 s window means the engine cannot hold (corrupt bundle, broken
# cache, OOM loop) — the supervisor drains and exits 1 so the
# orchestrator restarts the pod instead of brownout-flapping forever.
_ENGINE_STORM_DEATHS = 5
_ENGINE_STORM_WINDOW_S = 60.0


class _DrainNow(Exception):
    """Internal control flow: a replica crash-loop verdict inside the
    per-replica supervision loop must break out of BOTH loops into the
    drain path (a bare ``break`` would only leave the replica scan)."""


def serve_multi_worker(config: Config, bundle_dir: str) -> int:
    """Parent orchestration (ISSUE 11): the parent is a thread-free,
    jax-free SUPERVISOR — ring -> fork front ends -> fork engine child ->
    supervise both.

    Because the supervisor never loads a backend and never starts a
    thread, every fork it performs is safe (the PR 6 zygote's guarantee,
    absorbed into the parent now that the engine lives in a child): a
    crashed front end respawns in ~0.5 s, and a crashed/killed ENGINE is
    a brownout — the replacement warm-starts from the AOT cache,
    re-attaches under a new incarnation, and replays every busy slot
    while in-flight requests park against their deadline budgets
    (docs/operations.md "Engine death is a brownout").
    """
    from pathlib import Path

    serve_cfg = config.serve.validate()
    # eventfd is part of the gate, not just an optimization: the
    # completion-credit protocol rides the eventfd counter, and the pipe
    # fallback exists for dev harnesses, not deployments (macOS passes
    # the fork + SO_REUSEPORT checks but has no eventfd).
    if (
        not hasattr(os, "fork")
        or not hasattr(socket, "SO_REUSEPORT")
        or not hasattr(os, "eventfd")
    ):
        raise SystemExit(
            "serve.workers > 1 needs fork + SO_REUSEPORT + eventfd "
            "(Linux); run single-process (serve.workers=0) on this "
            "platform"
        )
    # Tenant fleet (mlops_tpu/tenancy/): serve.tenants_path names a
    # tenants.toml; without one the plane is the 1-tenant "default"
    # fleet serving the resolved bundle — the identical code path with a
    # one-row tenant axis (bit-identical degradation, test-pinned).
    from mlops_tpu.tenancy import (
        load_tenants_toml,
        single_tenant_config,
    )

    if serve_cfg.tenants_path:
        try:
            tenancy = load_tenants_toml(serve_cfg.tenants_path).validate()
        except ValueError as err:
            raise SystemExit(str(err))
    else:
        tenancy = single_tenant_config(bundle_dir)
    # Engine replica set (ISSUE 13): E supervised engine children behind
    # one ring. The lifecycle loop is single-writer machinery (one
    # controller hot-swaps ONE engine's bundle); running it against a
    # replica fleet would promote replica 0 alone and silently serve
    # mixed generations. The gridtuner (mlops_tpu/autotune/) shipped a
    # fleet-wide lead-plans/siblings-adopt protocol for EXEC-TABLE
    # changes (docs/operations.md "Hot regrid runbook"), but bundle
    # promotion also moves params/preprocessor state, which that
    # adoption path deliberately does not carry — lifting this
    # restriction stays out of scope here; refuse at startup.
    replicas = serve_cfg.engine_replicas
    if replicas > 1 and config.lifecycle.enabled:
        raise SystemExit(
            "serve.engine_replicas > 1 is incompatible with "
            "lifecycle.enabled: the lifecycle controller hot-swaps one "
            "engine process's bundle, and a replica fleet would serve "
            "mixed generations — run E=1 with the lifecycle loop, or "
            "the replica set without it. (The autotune plane's "
            "lead-plans/siblings-adopt regrid protocol covers exec-table "
            "changes only, not bundle promotion — see docs/operations.md)"
        )
    preprocess_paths: list[str] = []
    for spec in tenancy.tenants:
        path = str(Path(spec.bundle_dir) / "preprocess.npz")
        if not Path(path).is_file():
            raise SystemExit(
                f"no preprocessor at {path} (tenant {spec.name!r})"
            )
        preprocess_paths.append(path)

    # Same invariant the single-process server clamps at runtime: the
    # request cap must not exceed the largest warmed bucket, or
    # steady-state traffic triggers exact-shape compiles on the serving
    # hot path. Front ends cannot see the engine, but the bucket grid IS
    # config here (warmup_batch_sizes feeds the engine below), so clamp
    # BEFORE sizing slabs and forking — the children enforce the clamped
    # cap via their 413 gate.
    max_batch = serve_cfg.max_batch
    max_bucket = max(serve_cfg.warmup_batch_sizes)
    if max_batch > max_bucket:
        logger.warning(
            "serve.max_batch=%d exceeds largest warmup bucket %d; clamping",
            max_batch,
            max_bucket,
        )
        max_batch = max_bucket

    ring = RequestRing(
        workers=serve_cfg.workers,
        slots_small=serve_cfg.ring_slots_small,
        slots_large=serve_cfg.ring_slots_large,
        large_rows=max_batch,
        tenant_names=tenancy.names,
        replicas=replicas,
    )
    trace_cfg = getattr(config, "trace", None)
    if trace_cfg is not None and trace_cfg.enabled:
        # tracewire: validate + create the span dir BEFORE the fork (the
        # children write their per-worker JSONL into it) and flip the
        # shm tracing flag so the engine side stamps slot half-spans.
        trace_cfg.validate()
        Path(trace_cfg.dir).mkdir(parents=True, exist_ok=True)
        ring.set_tracing(True)
    else:
        trace_cfg = None
    slo_cfg = getattr(config, "slo", None)
    if slo_cfg is not None and (slo_cfg.enabled or slo_cfg.ledger_dir):
        # sloscope (mlops_tpu/slo/): validate + publish the SLO geometry
        # into shm BEFORE the fork — front ends render the SLO/alert
        # block (and label its windows) straight from the ring; the
        # lead engine replica evaluates and mirrors (_engine_main).
        slo_cfg.validate()
        if slo_cfg.enabled:
            ring.arm_slo(slo_cfg)
    else:
        slo_cfg = None
    # Reserve the port once (also resolves port=0), then hand the concrete
    # port to every child; the placeholder never listens, so the kernel
    # routes nothing to it.
    placeholder = reuseport_socket(serve_cfg.host, serve_cfg.port)
    import dataclasses

    child_cfg = dataclasses.replace(
        serve_cfg, port=placeholder.getsockname()[1], max_batch=max_batch
    )
    procs = start_frontends(
        child_cfg, ring, preprocess_paths, trace_cfg, tenancy, slo_cfg
    )
    logger.info(
        "supervisor %d spawned %d front ends (pids %s) for %d tenant(s) %s",
        os.getpid(), len(procs), [p.pid for p in procs],
        len(tenancy.tenants), list(tenancy.names),
    )
    # STAGGERED spawn (post-review fix): replica 0 boots FIRST and the
    # siblings fork only once its ready word flips — on a cold cache
    # every replica would otherwise compile the full warmup grid
    # simultaneously (E× the multi-minute compile bill; the tmp+rename
    # persist keeps it correct but wasteful). Replica 0 pays the
    # compiles once, persists them, and the siblings deserialize — the
    # "E deserializes, not E compiles" math, made true on cold boots
    # too. (Per-device-pinned artifacts still compile per slice; the
    # shared-device case — and every respawn — deserializes.)
    engine_procs: list[multiprocessing.Process | None] = [
        _spawn_engine(
            config, ring, bundle_dir, trace_cfg, tenancy, replica=0
        )
    ] + [None] * (replicas - 1)
    logger.info(
        "serving %s on %s:%s with %d SO_REUSEPORT front ends "
        "(engine pid %s)",
        serve_cfg.service_name, child_cfg.host, child_cfg.port,
        serve_cfg.workers, engine_procs[0].pid,
    )
    logger.info("engine replica 0 started (pid %s)", engine_procs[0].pid)
    _write_pid_files([p.pid if p else None for p in engine_procs])

    stopping = {"sigterm": False}

    def _sigterm(signum, frame=None) -> None:
        stopping["sigterm"] = True

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)

    # Per-replica crash-loop windows: replica k flapping must drain the
    # pod exactly as the single engine did, and sibling deaths must not
    # pool into one shared storm counter (two replicas each dying twice
    # is two brownouts, not one crash loop).
    engine_deaths: list[list[float]] = [[] for _ in range(replicas)]
    rc = 0
    try:
        # ---- supervise: front ends respawn in-place; an engine replica
        # respawns as a 1/E BROWNOUT (its ready word drops, the router
        # routes around it, its busy slots park and replay when the
        # replacement re-attaches) ----
        while not stopping["sigterm"]:
            time.sleep(0.5)
            for i, proc in enumerate(procs):
                if proc.is_alive() or stopping["sigterm"]:
                    continue
                logger.error(
                    "frontend %d (pid %s) died with exit code %s; "
                    "respawning",
                    i, proc.pid, proc.exitcode,
                )
                procs[i] = _respawn(
                    child_cfg, ring, preprocess_paths, i, trace_cfg,
                    tenancy, slo_cfg,
                )
            if engine_procs[-1] is None and ring.rep_ready[0]:
                # Replica 0 is warm: its compiles are persisted, so the
                # siblings' warmups deserialize — spawn the rest of the
                # fleet now (the staggered cold-boot contract above).
                for r in range(1, replicas):
                    engine_procs[r] = _spawn_engine(
                        config, ring, bundle_dir, trace_cfg, tenancy,
                        replica=r,
                    )
                    logger.info(
                        "engine replica %d started (pid %s)",
                        r, engine_procs[r].pid,
                    )
                _write_pid_files([p.pid if p else None for p in engine_procs])
            for r, engine_proc in enumerate(engine_procs):
                if engine_proc is None:
                    continue
                if engine_proc.is_alive() or stopping["sigterm"]:
                    continue
                now = time.monotonic()
                engine_deaths[r] = [
                    t for t in engine_deaths[r]
                    if now - t < _ENGINE_STORM_WINDOW_S
                ] + [now]
                if len(engine_deaths[r]) > _ENGINE_STORM_DEATHS:
                    logger.error(
                        "engine replica %d died %d times inside %.0f s "
                        "— crash loop, not a blip; draining for an "
                        "orchestrator restart",
                        r, len(engine_deaths[r]), _ENGINE_STORM_WINDOW_S,
                    )
                    rc = 1
                    raise _DrainNow
                logger.error(
                    "engine replica %d (pid %s) died with exit code %s; "
                    "respawning",
                    r, engine_proc.pid, engine_proc.exitcode,
                )
                # Brownout begins for THIS replica: its ready word drops
                # (the router routes fresh admissions around it; only a
                # full outage parks), the supervisor stamps the outage
                # start for the Retry-After math and counts the respawn
                # in the replica's own row.
                ring.set_ready(False, r)
                ring.eng_vals[r, ENG_DOWN_SINCE] = now
                ring.eng_vals[r, ENG_RESPAWNS] += 1
                engine_procs[r] = _spawn_engine(
                    config, ring, bundle_dir, trace_cfg, tenancy, replica=r
                )
                logger.info(
                    "engine replica %d started (pid %s)",
                    r, engine_procs[r].pid,
                )
                _write_pid_files([p.pid if p else None for p in engine_procs])
        return rc
    except _DrainNow:
        return rc
    finally:
        # ---- graceful drain: front ends FIRST (their in-flight slots
        # need live engines to land), then the engine replicas ----
        ring.set_draining()
        ring.set_ready(False)
        for proc in procs:
            if proc.is_alive() and proc.pid:
                with contextlib.suppress(ProcessLookupError):
                    os.kill(proc.pid, signal.SIGTERM)
        # One shared wall-clock budget for ALL front-end joins (they
        # drain concurrently — per-child timeouts would compound when
        # several are stuck; serve.zygote_join_deadline_s), then SIGKILL
        # the stragglers: they already ignored SIGTERM.
        deadline = time.monotonic() + serve_cfg.zygote_join_deadline_s
        for proc in procs:
            proc.join(timeout=max(0.0, deadline - time.monotonic()))
        for proc in procs:
            if proc.is_alive():  # pragma: no cover - stuck child
                proc.kill()
                proc.join(timeout=5)
        live_engines = [p for p in engine_procs if p is not None]
        for engine_proc in live_engines:
            if engine_proc.is_alive() and engine_proc.pid:
                with contextlib.suppress(ProcessLookupError):
                    os.kill(engine_proc.pid, signal.SIGTERM)
        # The engines drain their ring services (final monitor write,
        # in-flight jobs) on SIGTERM, concurrently; one shared
        # serve.engine_zygote_join_s budget bounds the waits before
        # SIGKILL escalation.
        deadline = time.monotonic() + serve_cfg.engine_zygote_join_s
        for engine_proc in live_engines:
            engine_proc.join(
                timeout=max(0.0, deadline - time.monotonic())
            )
        for engine_proc in live_engines:
            if engine_proc.is_alive():  # pragma: no cover - stuck engine
                engine_proc.kill()
                engine_proc.join(timeout=5)
        placeholder.close()
        ring.close()
        logger.info("multi-worker plane drained; exiting")


def _respawn(
    config: ServeConfig,
    ring: RequestRing,
    preprocess_path: str | list[str],
    worker_id: int,
    trace: Any = None,
    tenancy: Any = None,
    slo: Any = None,
) -> multiprocessing.Process:
    """Fork a replacement front end for one worker slot partition (the
    generation counters in shm make any of the dead worker's in-flight
    completions stale on arrival). Call only from a process without
    running threads — the supervisor in production, the harness process
    in tests — never from the engine once its backend is up."""
    ctx = multiprocessing.get_context("fork")
    proc = ctx.Process(
        target=_frontend_main,
        args=(worker_id, config, ring, preprocess_path, trace, tenancy, slo),
        name=f"mlops-tpu-frontend-{worker_id}",
    )
    proc.start()
    return proc
