"""Per-request SLO tier routing and the brownout-before-shed governor.

Jax-free by design (like `serve/wire.py`): the ring-plane front-end
processes, the single-process server, and the engine process all import
this without pulling jax.

The routing model (ISSUE 19): one engine holds a MULTI-TIER exec table —
the default tier it was configured with plus every other gated tier the
bundle admits (quant student, exact teacher, the gbm tensorization) —
and each request carries an SLO CLASS chosen at admission:

- ``x-slo-class: cheap|default|accurate`` when the client states it;
- otherwise a tight ``x-request-deadline-ms`` budget (below
  ``serve.slo_cheap_deadline_ms``) routes ``cheap`` — the ML-fleet
  goodput rule (arxiv 2502.06982): the cheapest tier that can still meet
  the deadline is the one that should serve it;
- otherwise ``default``.

The class, not the tier, is what rides the wire (HTTP header -> shm slot
tag): front ends don't know which tiers an engine's bundle gates, so the
ENGINE maps class -> tier (`InferenceEngine.route_tier`) at dispatch.
That also makes the ring's crash replay bit-stable: the class tag
survives in shm, and the same engine maps it to the same tier.

BROWNOUT BEFORE SHED: when admission pressure (live occupancy of the
inflight capacity) crosses ``brownout_demote_depth``, the governor
demotes ``default``-class requests to ``cheap`` INSTEAD of letting them
reach the 503 shed path — overload costs fidelity (a cheaper gated tier
answers) before it costs availability. ``accurate``-class requests are
never demoted (that's the tenant pin escape hatch — see
docs/operations.md), and requests still shed once the cheapest tier
itself saturates. Restoration is automatic with hysteresis
(``brownout_restore_depth`` < demote depth), so the switch cannot
flap at the threshold.
"""

from __future__ import annotations

# SLO classes, wire order — the shm slot tag stores the index, so the
# order is a cross-process contract (bump ``serve/ipc.py RING_MAGIC``
# if it ever changes).
SLO_CLASSES = ("default", "cheap", "accurate")
SLO_DEFAULT, SLO_CHEAP, SLO_ACCURATE = 0, 1, 2
_CLASS_BY_NAME = {name: i for i, name in enumerate(SLO_CLASSES)}

# Every serving tier any engine can hold, cheapest -> most accurate:
# the quant student (int8/bf16), the gbm tensorization (the sklearn
# floor's exact bits, so it is both a family's only tier and "cheap"
# relative to nothing), the exact teacher. Closed set — the ``tier``
# metric label is bounded by construction (TPULINT_BOUNDED_LABELS).
TIERS = ("quant", "gbm", "exact")


def parse_slo_class(raw: str) -> int | None:
    """``x-slo-class`` header value -> class index; None when the value
    is not one of the three classes (admission treats an unknown value
    as absent rather than 422ing — the header is advisory routing, not
    part of the scoring payload contract)."""
    return _CLASS_BY_NAME.get(raw.strip().lower())


def resolve_slo_class(
    header: str, deadline_ms: float | None, cheap_deadline_ms: float
) -> int:
    """Admission-time class resolution: an explicit header wins; absent
    that, a deadline budget at or under ``cheap_deadline_ms`` routes
    cheap (a client that can only wait 20 ms has already chosen the
    cheap tier, whether it knows the header or not); everything else is
    default class. ``cheap_deadline_ms <= 0`` disables deadline routing."""
    if header:
        cls = parse_slo_class(header)
        if cls is not None:
            return cls
    if (
        deadline_ms is not None
        and cheap_deadline_ms > 0
        and deadline_ms <= cheap_deadline_ms
    ):
        return SLO_CHEAP
    return SLO_DEFAULT


def tier_for_class(
    ladder: tuple[str, ...], default_tier: str, slo_class: int
) -> str:
    """Class -> tier against one engine's gated ladder (cheapest ->
    most accurate). ``cheap`` takes the ladder floor, ``accurate`` the
    ceiling, ``default`` the engine's configured tier — on a one-tier
    engine all three collapse to the same program, so routing is safe
    to apply unconditionally."""
    if slo_class == SLO_CHEAP:
        return ladder[0]
    if slo_class == SLO_ACCURATE:
        return ladder[-1]
    return default_tier


class BrownoutGovernor:
    """The demote-over-shed switch, one per admission point (a front-end
    worker, or the single-process server) — intentionally unlocked: every
    admission point is single-threaded where it admits (asyncio event
    loop), and the counters are plain int adds.

    ``observe(pressure)`` feeds the current 0..1 occupancy (live inflight
    over capacity) and flips the state with hysteresis; ``route(cls)``
    applies the active state to one request's class. Counters:

    - ``demotions``: requests whose class was demoted (the
      mlops_tpu_tier_demotions_total series)
    - ``brownout_demotions``: the same demotions attributed to the
      brownout switch specifically (mlops_tpu_brownout_demote_total —
      today the only demotion cause, kept as its own counter so a future
      non-brownout demotion cause cannot silently fold in)
    - ``entered`` / ``exited``: state transitions, for the runbook's
      flap check.
    """

    __slots__ = (
        "demote_depth",
        "restore_depth",
        "active",
        "demotions",
        "brownout_demotions",
        "entered",
        "exited",
    )

    def __init__(
        self, demote_depth: float = 0.75, restore_depth: float = 0.5
    ):
        if not 0.0 < demote_depth <= 1.0:
            raise ValueError(
                f"brownout demote depth must be in (0, 1], got {demote_depth}"
            )
        if not 0.0 <= restore_depth < demote_depth:
            raise ValueError(
                "brownout restore depth must be in [0, demote depth) for "
                f"hysteresis, got {restore_depth} vs {demote_depth}"
            )
        self.demote_depth = demote_depth
        self.restore_depth = restore_depth
        self.active = False
        self.demotions = 0
        self.brownout_demotions = 0
        self.entered = 0
        self.exited = 0

    def observe(self, pressure: float) -> bool:
        """Feed the current occupancy fraction; returns the (possibly
        flipped) brownout state. Hysteresis: once active, only dropping
        to ``restore_depth`` deactivates."""
        if self.active:
            if pressure <= self.restore_depth:
                self.active = False
                self.exited += 1
        elif pressure >= self.demote_depth:
            self.active = True
            self.entered += 1
        return self.active

    def route(self, slo_class: int) -> tuple[int, bool]:
        """Apply the CURRENT state (callers ``observe`` first with fresh
        pressure) to one request: under brownout, default class demotes
        to cheap; cheap is already at the floor and accurate is pinned.
        Returns ``(effective class, demoted?)``."""
        if self.active and slo_class == SLO_DEFAULT:
            self.demotions += 1
            self.brownout_demotions += 1
            return SLO_CHEAP, True
        return slo_class, False
