"""``python -m mlops_tpu`` — the CLI entry point."""

import sys

from mlops_tpu.cli import main

sys.exit(main())
