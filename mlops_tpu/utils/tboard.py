"""Optional TensorBoard event writer for training metrics.

The metrics store of record is the run's ``metrics.jsonl``
(`utils/jsonl.py`) — greppable, diffable, no daemon. This adds the
SURVEY.md SS5.5 "jsonl + TensorBoard" counterpart for interactive runs:
the same records stream into TF event files when
``train.tensorboard_dir`` is set. The writer is import-gated (torch's
``SummaryWriter`` is the only event-file encoder in this image); if it's
absent the writer degrades to a no-op with one warning rather than
failing training.
"""

from __future__ import annotations

import warnings
from pathlib import Path


class TensorBoardWriter:
    """Scalar-event writer; constructible even when tensorboard is absent."""

    def __init__(self, logdir: str | Path):
        self._writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._writer = SummaryWriter(log_dir=str(logdir))
        # Any import/init failure (torch absent, incompatible protobuf,
        # unwritable logdir) -> warn-and-no-op; metrics still reach jsonl.
        except Exception as err:  # tpulint: disable=TPU201
            warnings.warn(
                f"tensorboard writer unavailable ({err}); metrics go to "
                "metrics.jsonl only",
                stacklevel=2,
            )

    def write(self, record: dict) -> None:
        """Log every numeric field of a metrics record at its 'step'."""
        if self._writer is None:
            return
        step = int(record.get("step", 0))
        for key, value in record.items():
            if key == "step" or not isinstance(value, (int, float)):
                continue
            self._writer.add_scalar(key, float(value), global_step=step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
