"""Append-only JSONL writer for training metrics.

Replaces the reference's MLflow metric logging
(`01-train-model.ipynb:296-304`) with a local, greppable metrics stream that
the registry manifest links to.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any


class JsonlWriter:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = self.path.open("a")

    def write(self, record: dict[str, Any]) -> None:
        record = {"ts": time.time(), **record}
        self._f.write(json.dumps(record, default=float) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "JsonlWriter":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
