"""Atomic filesystem primitives shared by checkpointing and the registry."""

from __future__ import annotations

import os
import tempfile
from pathlib import Path

from mlops_tpu import faults


def atomic_write(path: str | Path, data: bytes) -> None:
    """Write via temp file + rename so a crash never leaves a torn file, and
    a failed write never leaks the temp file."""
    path = Path(path)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        # Injection point (mlops_tpu/faults): kill between write and
        # rename — the torn-write proof for every atomic_write consumer
        # (train checkpoints, registry records): the target path must
        # never hold a partial payload.
        faults.fire("io.atomic_write.midwrite")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        raise
