"""Atomic filesystem primitives shared by checkpointing and the registry."""

from __future__ import annotations

import os
import tempfile
from pathlib import Path


def atomic_write(path: str | Path, data: bytes) -> None:
    """Write via temp file + rename so a crash never leaves a torn file, and
    a failed write never leaks the temp file."""
    path = Path(path)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass
        raise
