"""Scheme-dispatching storage layer: local paths and ``gs://`` URIs.

The reference stages its training CSV into DBFS and reads it back through
the managed Spark runtime (`/root/reference/.github/workflows/
deploy-infrastructure.yml:195-198`, `spark.read.table` in the notebooks).
This stack's estate is GCS (`infra/main.tf` provisions the bucket and
`deploy-infrastructure.yml` uploads `curated.csv`), so the data pipeline
and the model registry must consume ``gs://`` URIs directly.

No google-cloud-storage SDK is assumed; the client speaks the GCS JSON
API over urllib with a bearer token from (in order) ``GCS_ACCESS_TOKEN``
or the GCE metadata server. The HTTP transport is a single injectable
function, so unit tests swap in an in-memory fake bucket and the suite
never needs network.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

from mlops_tpu.utils.io import atomic_write

_API = "https://storage.googleapis.com"
_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/service-accounts/default/token"
)


def is_gcs(path: str | Path) -> bool:
    return str(path).startswith("gs://")


def split_gcs(path: str) -> tuple[str, str]:
    """``gs://bucket/a/b`` -> ``("bucket", "a/b")``."""
    rest = str(path)[len("gs://") :]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"malformed gs:// path: {path!r}")
    return bucket, key


class GCSClient:
    """Minimal GCS JSON-API client. ``transport`` is
    ``(method, url, data, headers) -> (status, body_bytes)``; the default
    uses urllib, tests inject a fake."""

    def __init__(self, transport=None):
        self._transport = transport or self._urllib_transport
        self._token: str | None = None

    # ------------------------------------------------------------ transport
    @staticmethod
    def _urllib_transport(
        method: str, url: str, data: bytes | None, headers: dict[str, str]
    ) -> tuple[int, bytes]:
        req = urllib.request.Request(url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as err:
            return err.code, err.read()

    def _auth_headers(self) -> dict[str, str]:
        if self._token is None:
            token = os.environ.get("GCS_ACCESS_TOKEN")
            if not token:
                status, body = self._transport(
                    "GET",
                    _METADATA_TOKEN_URL,
                    None,
                    {"Metadata-Flavor": "Google"},
                )
                if status != 200:
                    raise RuntimeError(
                        "no GCS credentials: set GCS_ACCESS_TOKEN or run "
                        f"on GCE (metadata server returned {status})"
                    )
                token = json.loads(body)["access_token"]
            self._token = token
        return {"Authorization": f"Bearer {self._token}"}

    def _call(
        self, method: str, url: str, data: bytes | None = None
    ) -> tuple[int, bytes]:
        status, body = self._transport(method, url, data, self._auth_headers())
        if status == 401:
            # Metadata-server tokens expire (~1h); drop the cached one and
            # retry once with a fresh token so long-lived processes
            # (serving replicas, >1h training jobs) survive expiry.
            self._token = None
            status, body = self._transport(
                method, url, data, self._auth_headers()
            )
        return status, body

    # ------------------------------------------------------------- object ops
    def read_bytes(self, path: str) -> bytes:
        bucket, key = split_gcs(path)
        url = (
            f"{_API}/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
            f"/o/{urllib.parse.quote(key, safe='')}?alt=media"
        )
        status, body = self._call("GET", url)
        if status == 404:
            raise FileNotFoundError(path)
        if status != 200:
            raise RuntimeError(f"GCS read {path} failed: HTTP {status}")
        return body

    def read_to_file(self, path: str, local: "str | Path") -> None:
        """Stream an object to a local file without buffering it whole in
        memory (curated datasets can be multi-GB; ``read_bytes`` + a
        decoded copy would hold 2x the file in RAM). Streams through
        urllib when running on the real transport; injected (test)
        transports fall back to a buffered copy."""
        from mlops_tpu.utils.io import atomic_write

        if self._transport is not self._urllib_transport:
            atomic_write(local, self.read_bytes(path))
            return
        import os
        import tempfile

        bucket, key = split_gcs(path)
        url = (
            f"{_API}/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
            f"/o/{urllib.parse.quote(key, safe='')}?alt=media"
        )
        local = Path(local)
        local.parent.mkdir(parents=True, exist_ok=True)
        for attempt in (0, 1):
            req = urllib.request.Request(url, headers=self._auth_headers())
            try:
                with urllib.request.urlopen(req, timeout=300) as resp:
                    # mkstemp: concurrent fetchers of the same object each
                    # stream into their OWN temp file (a shared fixed name
                    # would interleave chunks), and the rename is atomic.
                    fd, tmp = tempfile.mkstemp(
                        dir=local.parent, prefix=f".{local.name}."
                    )
                    try:
                        with os.fdopen(fd, "wb") as f:
                            while chunk := resp.read(1 << 20):
                                f.write(chunk)
                        os.replace(tmp, local)
                    except BaseException:
                        try:
                            os.unlink(tmp)
                        except FileNotFoundError:
                            pass
                        raise
                return
            except urllib.error.HTTPError as err:
                if err.code == 401 and attempt == 0:
                    # Same expired-token recovery as _call: drop the
                    # cached token and retry once.
                    self._token = None
                    continue
                if err.code == 404:
                    raise FileNotFoundError(path) from None
                raise RuntimeError(
                    f"GCS read {path} failed: HTTP {err.code}"
                ) from None

    def write_bytes(self, path: str, data: bytes) -> None:
        bucket, key = split_gcs(path)
        url = (
            f"{_API}/upload/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
            f"/o?uploadType=media&name={urllib.parse.quote(key, safe='')}"
        )
        status, body = self._call("POST", url, data)
        if status not in (200, 201):
            raise RuntimeError(f"GCS write {path} failed: HTTP {status}")

    def stat(self, path: str) -> dict:
        """Object metadata (name/size/generation/md5Hash as available)."""
        bucket, key = split_gcs(path)
        url = (
            f"{_API}/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
            f"/o/{urllib.parse.quote(key, safe='')}"
        )
        status, body = self._call("GET", url)
        if status == 404:
            raise FileNotFoundError(path)
        if status != 200:
            raise RuntimeError(f"GCS stat {path} failed: HTTP {status}")
        return json.loads(body)

    def exists(self, path: str) -> bool:
        try:
            self.stat(path)
            return True
        except FileNotFoundError:
            return False

    def list_keys(self, path: str) -> list[str]:
        """All object keys under the ``gs://bucket/prefix`` (recursive)."""
        keys, _ = self._list(path, delimiter=None)
        return keys

    def list_prefixes(self, path: str) -> list[str]:
        """Immediate child "directories" of the prefix (``delimiter=/``
        listing) — one small page instead of every object key, e.g. the
        registry's version-number scan."""
        _, prefixes = self._list(path, delimiter="/")
        return prefixes

    def _list(
        self, path: str, delimiter: str | None
    ) -> tuple[list[str], list[str]]:
        bucket, prefix = split_gcs(path)
        keys: list[str] = []
        prefixes: list[str] = []
        page = ""
        while True:
            url = (
                f"{_API}/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                f"/o?prefix={urllib.parse.quote(prefix, safe='')}"
                f"&fields=items(name),prefixes,nextPageToken"
            )
            if delimiter:
                url += f"&delimiter={urllib.parse.quote(delimiter, safe='')}"
            if page:
                url += f"&pageToken={urllib.parse.quote(page, safe='')}"
            status, body = self._call("GET", url)
            if status != 200:
                raise RuntimeError(f"GCS list {path} failed: HTTP {status}")
            payload = json.loads(body or b"{}")
            keys.extend(item["name"] for item in payload.get("items", []))
            prefixes.extend(payload.get("prefixes", []))
            page = payload.get("nextPageToken", "")
            if not page:
                return keys, prefixes


_default_client: GCSClient | None = None


def gcs_client() -> GCSClient:
    """Process-wide client (token cached). Tests construct their own."""
    global _default_client
    if _default_client is None:
        _default_client = GCSClient()
    return _default_client


# ---------------------------------------------------------------- facade
def read_bytes(path: str | Path, client: GCSClient | None = None) -> bytes:
    if is_gcs(path):
        return (client or gcs_client()).read_bytes(str(path))
    return Path(path).read_bytes()


def write_bytes(
    path: str | Path, data: bytes, client: GCSClient | None = None
) -> None:
    if is_gcs(path):
        (client or gcs_client()).write_bytes(str(path), data)
        return
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    atomic_write(path, data)


def exists(path: str | Path, client: GCSClient | None = None) -> bool:
    if is_gcs(path):
        return (client or gcs_client()).exists(str(path))
    return Path(path).exists()


def join(base: str | Path, *parts: str) -> str | Path:
    if is_gcs(base):
        return "/".join([str(base).rstrip("/"), *parts])
    return Path(base).joinpath(*parts)


def upload_dir(
    local_dir: str | Path, dest: str, client: GCSClient | None = None
) -> None:
    """Recursively copy a local directory to ``gs://bucket/prefix``."""
    client = client or gcs_client()
    local_dir = Path(local_dir)
    for file in sorted(p for p in local_dir.rglob("*") if p.is_file()):
        rel = file.relative_to(local_dir).as_posix()
        client.write_bytes(f"{dest.rstrip('/')}/{rel}", file.read_bytes())


def download_dir(
    src: str, local_dir: str | Path, client: GCSClient | None = None
) -> Path:
    """Recursively copy ``gs://bucket/prefix`` into a local directory.

    The prefix is listed with a terminating ``/`` — a bare ``.../1``
    prefix would also match sibling keys ``.../10/...``, ``.../11/...``
    (registry version 1 pulling versions 10-19 into its cache).
    """
    client = client or gcs_client()
    local_dir = Path(local_dir)
    src = src.rstrip("/")
    bucket, prefix = split_gcs(src + "/")
    keys = client.list_keys(src + "/")
    if not keys:
        raise FileNotFoundError(src)
    for key in keys:
        rel = key[len(prefix) :].lstrip("/")
        target = local_dir / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        atomic_write(target, client.read_bytes(f"gs://{bucket}/{key}"))
    return local_dir
