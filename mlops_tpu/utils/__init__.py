"""Shared utilities: structured logging, jsonl metrics, timing."""

from mlops_tpu.utils.jsonl import JsonlWriter
from mlops_tpu.utils.timing import Timer

__all__ = ["JsonlWriter", "Timer"]
