"""FLOP accounting + MFU (model FLOPs utilization) reporting.

The reference publishes no efficiency evidence at all (SURVEY.md §6); the
bench here reports latency/throughput, and this module adds the roofline
axis: how much of the chip's peak the measured path actually uses, so
"actually fast" is auditable from the bench artifact alone.

FLOP counts come from XLA's OWN cost model (`compiled.cost_analysis()`),
not hand-derived formulas — it covers every model family, includes fused
elementwise work the analytic count would miss, and matches what the
compiler actually scheduled. Peak FLOP/s is a small device-kind table
(bf16/f32 matmul peaks from published TPU specs) with an env override
(``MLOPS_TPU_PEAK_FLOPS``) for kinds the table doesn't know.
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax

logger = logging.getLogger(__name__)

# Published per-chip dense matmul peaks (FLOP/s). Values are bf16 peaks for
# TPUs (the compute dtype the framework puts on the MXU) and deliberately
# None for CPUs: a portable peak for arbitrary host silicon isn't knowable
# from here, and a made-up denominator would make the MFU meaningless.
_PEAKS: tuple[tuple[str, float], ...] = (
    ("v5 lite", 197e12),  # v5e: 197 TFLOP/s bf16
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v6 lite", 918e12),  # Trillium
    ("v6e", 918e12),
)

# MXU throughput of each executing precision relative to the bf16 base
# above (published TPU ratios: int8 doubles the bf16 peak, f32 halves it).
# An MFU whose numerator is an f32 program but whose denominator is the
# bf16 peak understates utilization 2x — the ISSUE 17 `mfu_bulk` fix: the
# caller states the precision the measured program EXECUTES in, and the
# bench payload records it next to the number.
_DTYPE_SCALE: dict[str, float] = {
    "bf16": 1.0,
    "bfloat16": 1.0,
    "f32": 0.5,
    "float32": 0.5,
    "int8": 2.0,
}


def peak_flops(device: Any, dtype: str = "bf16") -> float | None:
    """Best-known peak FLOP/s for ``device`` at executing precision
    ``dtype`` ("bf16"/"f32"/"int8" and aliases), or None when unknown.

    ``MLOPS_TPU_PEAK_FLOPS`` overrides VERBATIM — no dtype scaling (the
    user measured it at whatever precision they measured it at; e.g. a
    CPU's measured GEMM peak, letting CPU bench runs report a real MFU
    too).
    """
    if dtype not in _DTYPE_SCALE:
        raise ValueError(
            f"unknown executing dtype {dtype!r}; expected one of "
            f"{sorted(_DTYPE_SCALE)}"
        )
    override = os.environ.get("MLOPS_TPU_PEAK_FLOPS")
    if override:
        return float(override)
    kind = getattr(device, "device_kind", "").lower()
    for needle, peak in _PEAKS:
        if needle in kind:
            return peak * _DTYPE_SCALE[dtype]
    return None


def compile_with_flops(fn, *args) -> tuple[Any | None, float | None]:
    """Compile ``fn(*args)`` ONCE; return ``(executable, flops)``.

    The executable is directly callable with the same args (so callers can
    time it without a second ``jax.jit`` compile). Either element is None
    when that half failed — some plugin backends compile fine but expose
    no cost analysis.
    """
    compiled = None
    try:
        compiled = jax.jit(fn).lower(*args).compile()
    # Plugin backends raise backend-specific compile errors that share no
    # base class (XlaRuntimeError, RuntimeError, ValueError, ...); the
    # contract here is "None when this backend can't compile it", so the
    # breadth is the point — logged so the cause is never silent.
    except Exception as err:  # tpulint: disable=TPU201
        logger.debug("compile for FLOP counting failed: %s", err)
        return None, None
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):  # per-device list on old APIs
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        return compiled, (flops if flops > 0 else None)
    except (
        AttributeError,  # backend exposes no cost_analysis / returns None
        IndexError,  # empty per-device analysis list
        KeyError,
        TypeError,  # non-mapping analysis object
        ValueError,
        NotImplementedError,  # plugin declines the query
        RuntimeError,  # XLA-side analysis failure
    ) as err:
        logger.debug("cost_analysis unavailable: %s", err)
        return compiled, None


def compiled_flops(fn, *args) -> float | None:
    """FLOPs of one call of ``fn(*args)`` per XLA's cost analysis (None
    when unavailable)."""
    return compile_with_flops(fn, *args)[1]


def measured_gemm_peak(
    n: int = 1024, reps: int = 5, dtype: str = "f32"
) -> float:
    """Empirical dense-matmul peak of the CURRENT backend (FLOP/s): best
    of ``reps`` timed ``n×n @ n×n`` matmuls at executing precision
    ``dtype``. The honest denominator for CPU fallback benches, where no
    published peak exists — reported MFU then reads "fraction of this
    host's measured GEMM rate at the SAME precision", which is the
    comparable quantity to a TPU's spec-sheet peak."""
    import time

    import jax.numpy as jnp

    jdt = {
        "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
        "f32": jnp.float32, "float32": jnp.float32,
        "int8": jnp.int8,
    }[dtype]
    if jdt == jnp.int8:
        # int8 GEMM accumulates in int32 on every backend that has it.
        a = jnp.ones((n, n), jnp.int8)
        b = jnp.ones((n, n), jnp.int8)
        f = jax.jit(
            lambda a, b: jax.lax.dot(
                a, b, preferred_element_type=jnp.int32
            )
        )
    else:
        a = jnp.ones((n, n), jdt)
        b = jnp.ones((n, n), jdt)
        f = jax.jit(lambda a, b: a @ b)
    jax.block_until_ready(f(a, b))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(a, b))
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n**3 / best


def mfu(flops_per_call: float | None, calls_per_s: float, peak: float | None):
    """Fraction of peak, rounded for the bench JSON; None when either side
    is unknown."""
    if not flops_per_call or not peak or calls_per_s <= 0:
        return None
    return round(flops_per_call * calls_per_s / peak, 4)
