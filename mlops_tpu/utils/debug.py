"""Numeric sanitizers — the framework's answer to SURVEY.md SS5.2.

The reference needs no race detection (single-threaded handler, write-once
model dict) and neither do we (asyncio discipline + immutable bundles); the
real TPU-side hazard class is NUMERIC: NaN/Inf escaping a kernel into
predictions, or out-of-range categorical ids silently gathering garbage
embeddings. ``jax.experimental.checkify`` turns those into structured,
jit-compatible errors — this module packages the two checks the serving
and training paths care about.

Opt-in (debug/CI), not always-on: checkify adds error-state plumbing to the
compiled program, which the <5 ms p50 hot path doesn't pay for. The test
suite runs the checked variants; production runs the bare ones.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.experimental import checkify

from mlops_tpu.schema.features import SCHEMA


def checked(fn: Callable, *, jit: bool = True) -> Callable:
    """Wrap ``fn`` with float checks (NaN/Inf anywhere in its outputs).

    Returns a callable with the same signature that RAISES
    ``checkify.JaxRuntimeError`` on the first numeric violation instead of
    silently propagating garbage.
    """
    err_fn = checkify.checkify(fn, errors=checkify.float_checks)
    if jit:
        err_fn = jax.jit(err_fn)

    def run(*args, **kwargs):
        err, out = err_fn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return run


def check_encoded_inputs(cat_ids: jnp.ndarray, numeric: jnp.ndarray) -> None:
    """Validate an encoded batch before it reaches a kernel: categorical
    ids must be inside every embedding table (OOV bucket included) and
    numerics finite. Host-side, cheap, suitable for the ingest boundary."""
    import numpy as np

    cat = np.asarray(cat_ids)
    cards = np.asarray(SCHEMA.cards)
    if cat.ndim != 2 or cat.shape[1] != SCHEMA.num_categorical:
        raise ValueError(f"cat_ids shape {cat.shape} != (N, {SCHEMA.num_categorical})")
    if (cat < 0).any() or (cat >= cards[None, :]).any():
        j = int(np.argwhere((cat < 0) | (cat >= cards[None, :]))[0][1])
        raise ValueError(
            f"categorical id out of range for feature "
            f"{SCHEMA.categorical[j].name!r} (card {cards[j]})"
        )
    num = np.asarray(numeric)
    if num.shape != (cat.shape[0], SCHEMA.num_numeric):
        raise ValueError(
            f"numeric shape {num.shape} != ({cat.shape[0]}, {SCHEMA.num_numeric})"
        )
    if not np.isfinite(num).all():
        raise ValueError("non-finite value in encoded numerics")
