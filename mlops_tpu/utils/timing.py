"""Wall-clock timing helpers (used by serving metrics and bench)."""

from __future__ import annotations

import math
import time


class Timer:
    """Context-manager stopwatch: ``with Timer() as t: ...; t.ms``."""

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._start
        self.ms = self.seconds * 1e3


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted list."""
    if not sorted_values:
        return float("nan")
    n = len(sorted_values)
    rank = min(n - 1, max(0, math.ceil(q / 100.0 * n) - 1))
    return sorted_values[rank]
