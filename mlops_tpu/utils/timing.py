"""Wall-clock timing helpers (used by serving metrics, the pipelined
streaming executor, and bench)."""

from __future__ import annotations

import contextlib
import math
import threading
import time


class Timer:
    """Context-manager stopwatch: ``with Timer() as t: ...; t.ms``."""

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.seconds = time.perf_counter() - self._start
        self.ms = self.seconds * 1e3


class StageClock:
    """Per-stage busy-time accumulator for pipelined executors
    (`data/pipeline_exec.py`).

    Each worker wraps its unit of work in ``with clock.stage(name): ...``;
    ``report(wall_s)`` returns ``{stage: {busy_s, items, occupancy}}``
    where ``occupancy`` is the fraction of the pipeline's wall clock the
    stage spent busy. Occupancies are the overlap evidence: in a serial
    run they sum to ~1.0; in an overlapped run the sum exceeds 1.0 and
    the largest single occupancy names the bottleneck stage.

    Thread-safe: each stage runs on its own thread, and the executor's
    serial mode shares one clock across all stages on the caller thread.

    ``sink`` (optional) streams every completed stage as a span event —
    ``sink(name, start_perf_counter, elapsed_s, items)`` — into the
    tracewire layer (`trace/recorder.py TraceRecorder.stage_sink`), so
    pipeline/bulk stage timings land in the same queryable JSONL as
    request spans. Called OUTSIDE the lock; the tracewire sink is a
    bounded non-blocking enqueue, never I/O on this thread.
    """

    def __init__(self, sink=None) -> None:
        self._lock = threading.Lock()
        self._busy: dict[str, float] = {}
        self._items: dict[str, int] = {}
        self._sink = sink

    @contextlib.contextmanager
    def stage(self, name: str, items: int = 1):
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                self._busy[name] = self._busy.get(name, 0.0) + elapsed
                self._items[name] = self._items.get(name, 0) + items
            if self._sink is not None:
                self._sink(name, start, elapsed, items)

    def report(self, wall_s: float) -> dict[str, dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "busy_s": round(busy, 4),
                    "items": self._items[name],
                    "occupancy": (
                        round(busy / wall_s, 4) if wall_s > 0 else 0.0
                    ),
                }
                for name, busy in self._busy.items()
            }


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted list."""
    if not sorted_values:
        return float("nan")
    n = len(sorted_values)
    rank = min(n - 1, max(0, math.ceil(q / 100.0 * n) - 1))
    return sorted_values[rank]
