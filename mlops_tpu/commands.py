"""CLI subcommand implementations."""

from __future__ import annotations

import argparse
import json

from mlops_tpu.config import load_config


def run(args: argparse.Namespace) -> int:
    if args.command == "analyze":
        # Static analysis BEFORE any jax import: no config tree, no
        # distributed init, no backend warmup — `analyze --no-trace` must
        # run identically on a JAX-less machine (_honor_jax_platforms_env
        # would import jax whenever JAX_PLATFORMS is set).
        from mlops_tpu.analysis.cli import run_analyze

        return run_analyze(args)
    if args.command == "flightrec":
        # Flight-recorder timeline render (mlops_tpu/slo/flightrec.py):
        # jax-free, takes dump paths rather than config — intercepted
        # like `analyze` so a post-mortem box needs no backend at all.
        return _flightrec_paths(list(getattr(args, "paths", [])))
    _honor_jax_platforms_env()
    # Multi-host launches (GKE JobSet / TPU pod) wire up DCN before any
    # backend use; single-host is a no-op (parallel/distributed.py).
    from mlops_tpu.parallel.distributed import initialize as distributed_init

    distributed_init()
    config = load_config(args.config, overrides=getattr(args, "overrides", []))
    # `warmup --cache-dir X` is sugar for `warmup cache.dir=X` (the flag
    # form is the documented container-build invocation).
    if getattr(args, "cache_dir", None):
        config.cache.dir = args.cache_dir
    # `serve --workers N` is sugar for `serve serve.workers=N` (the flag
    # form is the documented deployment invocation).
    if getattr(args, "workers", None) is not None:
        config.serve.workers = args.workers
    # `serve --tenants tenants.toml` is sugar for
    # `serve serve.tenants_path=<file>` (the multi-tenant fleet form).
    if getattr(args, "tenants", None):
        config.serve.tenants_path = args.tenants
    # `trace-report --tenant NAME` is sugar for `trace.tenant=NAME`.
    if getattr(args, "tenant", None):
        config.trace.tenant = args.tenant
    # `trace-report --replica N` is sugar for `trace.replica=N` (the
    # engine-replica slice, ISSUE 13).
    if getattr(args, "replica", None) is not None:
        config.trace.replica = args.replica
    # `serve --replicas E` is sugar for `serve.engine_replicas=E` (the
    # engine replica set, ISSUE 13).
    if getattr(args, "replicas", None) is not None:
        config.serve.engine_replicas = args.replicas
    # `trace-report --ledger` is sugar for `trace.ledger=true` (the
    # device-time cost ledger ranking, ISSUE 14).
    if getattr(args, "ledger", False):
        config.trace.ledger = True
    handler = _HANDLERS.get(args.command)
    if handler is None:
        raise SystemExit(f"subcommand {args.command!r} is not implemented yet")
    return handler(config) or 0


def _honor_jax_platforms_env() -> None:
    """Make an explicit ``JAX_PLATFORMS`` env win over site bootstrap.

    This container's TPU bootstrap force-sets ``jax_platforms="axon,cpu"``
    in every interpreter, which silently overrides the env var — a user who
    exported ``JAX_PLATFORMS=cpu`` (tests, CI, laptops) would still dial the
    TPU tunnel. Re-assert the env value at the config level before any
    backend initializes.
    """
    import os

    value = os.environ.get("JAX_PLATFORMS")
    if value:
        import jax

        try:
            jax.config.update("jax_platforms", value)
        except RuntimeError:
            pass  # backends already initialized; keep what we have


def _synth(config) -> int:
    from mlops_tpu.data import generate_synthetic, write_csv_columns

    path = config.data.train_path or "data/curated.csv"
    columns, labels = generate_synthetic(config.data.rows, seed=config.data.seed)
    write_csv_columns(path, columns, labels)
    print(f"wrote {config.data.rows} rows -> {path}")
    return 0


def _train(config) -> int:
    from mlops_tpu.train.pipeline import run_layout_training, run_training

    run_name = config.registry.run_name or None
    if config.model.uses_layout_trainer:
        # Multi-device training layouts (GPipe / DP×TP Megatron sharding /
        # ring-attention documents) run through their dedicated trainers
        # on a mesh built from the available devices
        # (train/pipeline.py run_layout_training).
        result = run_layout_training(config, run_name=run_name)
    else:
        result = run_training(config, run_name=run_name)
    print(
        json.dumps(
            {
                "bundle": str(result.bundle_dir) if result.bundle_dir else None,
                "model_uri": result.model_uri,
                "run_dir": str(result.run_dir),
                "steps": result.train_result.steps,
                "packaged_step": result.train_result.packaged_step,
                "metrics": result.train_result.metrics,
            }
        )
    )
    return 0


def _pretrain(config) -> int:
    """Masked-feature pretraining on unlabeled rows (BASELINE config 5's
    'fine-tune' implies a pretrain stage; labels are never read). Output:
    a params file consumable via ``train train.init_params=<path>``."""
    from mlops_tpu.data import Preprocessor, generate_synthetic, load_table_columns
    from mlops_tpu.train.pipeline import new_run_dir
    from mlops_tpu.train.pretrain import pretrain_bert, save_pretrained

    if config.model.family != "bert":
        raise SystemExit("pretrain supports model.family=bert")
    if config.model.uses_layout_trainer:
        raise SystemExit(
            "pretrain runs the dense single-record masked-LM; unset the "
            "layout knobs (model.pipeline_stages / seq_parallel / "
            "doc_records>1)"
        )
    if config.data.train_path:
        columns, _ = load_table_columns(config.data.train_path)
    else:
        columns, _ = generate_synthetic(config.data.rows, seed=config.data.seed)
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns)

    result = pretrain_bert(
        config.model,
        ds,
        steps=config.train.steps,
        batch_size=config.train.batch_size,
        learning_rate=config.train.learning_rate,
        seed=config.train.seed,
    )
    out = new_run_dir(config) / "pretrained.msgpack"
    save_pretrained(result, out)
    print(
        json.dumps(
            {"pretrained": str(out), "rows": ds.n, "loss_curve": result.losses}
        )
    )
    return 0


def _tune(config) -> int:
    import jax

    from mlops_tpu.parallel import make_mesh
    from mlops_tpu.train.pipeline import run_tuning

    # Shard the trial axis across every available chip; single-device runs
    # (laptops, 1-chip CI) skip the mesh and train trials vmapped in-place.
    mesh = make_mesh(jax.device_count()) if jax.device_count() > 1 else None
    result, hpo_result = run_tuning(
        config, run_name=config.registry.run_name or None, mesh=mesh
    )
    print(
        json.dumps(
            {
                "bundle": str(result.bundle_dir),
                "model_uri": result.model_uri,
                "best_trial": hpo_result.best_index,
                "best_hyperparams": hpo_result.best_hyperparams,
                "metrics": hpo_result.best_metrics,
                "trials": len(hpo_result.trials),
            }
        )
    )
    return 0


def _register(config) -> int:
    """Register an existing bundle directory (data.train_path doubles as the
    bundle path argument: ``mlops-tpu register data.train_path=<dir>``)."""
    from mlops_tpu.bundle import ModelRegistry

    bundle_dir = config.data.train_path
    if not bundle_dir:
        raise SystemExit("pass the bundle dir via data.train_path=<dir>")
    registry = ModelRegistry(config.registry.root)
    uri = registry.register(config.registry.model_name, bundle_dir)
    print(uri)
    return 0


def _promote(config) -> int:
    """Stage promotion (`mlops-tpu promote registry.promote_version=3
    registry.promote_stage=production`) — the registry-level half of the
    reference's staging->production gate (the image-level half lives in the
    deploy workflow's Production environment review)."""
    from mlops_tpu.bundle import ModelRegistry

    version = config.registry.promote_version
    stage = config.registry.promote_stage
    if not version:
        raise SystemExit(
            "pass registry.promote_version=<n> [registry.promote_stage=staging]"
        )
    registry = ModelRegistry(config.registry.root)
    registry.set_stage(config.registry.model_name, int(version), stage)
    print(
        json.dumps(
            {"model": config.registry.model_name, "version": int(version),
             "stage": stage}
        )
    )
    return 0


def _validate(config) -> int:
    """Lint a CSV/Parquet before training/scoring — streamed, so any size.

    Counts values the pipeline would silently degrade (OOV categoricals
    -> the OOV bucket; missing/unparseable numerics -> median imputation)
    and pre-flights label parseability the way training will see it
    (fail-fast semantics). Exit 2 when anything is flagged. (The
    reference's only data validation is Spark's inferSchema plus whatever
    breaks at train time.)"""
    import numpy as np

    from mlops_tpu.data.stream import iter_table_chunks
    from mlops_tpu.schema import SCHEMA

    path = config.data.train_path
    if not path:
        raise SystemExit("pass the dataset via data.train_path=<csv|parquet>")

    rows = 0
    oov = dict.fromkeys((f.name for f in SCHEMA.categorical), 0)
    vocabs = {f.name: set(f.vocab) for f in SCHEMA.categorical}
    degraded_numeric = dict.fromkeys((f.name for f in SCHEMA.numeric), 0)
    for columns, _ in iter_table_chunks(path, chunk_rows=65_536):
        rows += len(columns[SCHEMA.categorical[0].name])
        for feat in SCHEMA.categorical:
            vocab = vocabs[feat.name]
            oov[feat.name] += sum(
                1 for v in columns[feat.name] if v not in vocab
            )
        for feat in SCHEMA.numeric:
            raw = np.asarray(columns[feat.name], dtype=np.float64)
            degraded_numeric[feat.name] += int((~np.isfinite(raw)).sum())

    # Label pre-flight: replay training's strict parse (one bad value
    # fails `train` fast); "absent" is fine for scoring-only files.
    try:
        for _ in iter_table_chunks(path, chunk_rows=65_536, require_target=True):
            pass
        labels = "ok"
    except ValueError as err:
        labels = "absent" if "missing target column" in str(err) else str(err)

    report = {
        "path": path,
        "rows": rows,
        "oov_categorical": {k: v for k, v in oov.items() if v},
        # missing AND unparseable cells both impute to the median — the
        # pipeline handles them; the count is the lint signal.
        "numeric_imputed": {k: v for k, v in degraded_numeric.items() if v},
        "labels": labels,
        "ok": (
            not any(oov.values())
            and not any(degraded_numeric.values())
            and labels in ("ok", "absent")
        ),
    }
    print(json.dumps(report))
    return 0 if report["ok"] else 2


def _gc(config) -> int:
    """Prune crash orphans (and, with registry.gc_keep=N, old unstaged
    versions) for the configured model."""
    from mlops_tpu.bundle import ModelRegistry

    registry = ModelRegistry(config.registry.root)
    try:
        removed = registry.gc(
            config.registry.model_name, keep_unstaged=config.registry.gc_keep
        )
    except ValueError as err:  # gs:// root: clean message, no traceback
        raise SystemExit(str(err))
    print(json.dumps({"model": config.registry.model_name, **removed}))
    return 0


def _versions(config) -> int:
    from mlops_tpu.bundle import ModelRegistry

    registry = ModelRegistry(config.registry.root)
    print(
        json.dumps(registry.list_versions(config.registry.model_name), indent=2)
    )
    return 0


def _predict_file(config) -> int:
    """Batch-score a schema CSV offline with the full fused predict (works
    for every bundle flavor — flax on device, sklearn floor on host, and
    ``doc`` long-context bundles, which group consecutive rows into
    record histories and emit one prediction per document)."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.native import encode_csv
    from mlops_tpu.serve import InferenceEngine

    source = config.data.train_path
    if not source:
        raise SystemExit("pass the input csv via data.train_path=<csv>")
    bundle = load_bundle(_resolve_bundle(config))
    ds = encode_csv(source, bundle.preprocessor)
    if bundle.flavor == "doc":
        print(json.dumps(
            _predict_documents(bundle, ds, config.serve.max_batch)
        ))
        return 0
    engine = InferenceEngine(bundle, buckets=(config.serve.max_batch,))
    print(json.dumps(engine.predict_arrays(ds.cat_ids, ds.numeric)))
    return 0


def _predict_documents(bundle, ds, max_batch: int = 256) -> dict:
    """Score a record-history dataset with a doc bundle: consecutive rows
    group into ``doc_records``-length documents (the training-time
    `make_documents` convention: the prediction targets the LAST record's
    default) and the calibrated per-document probabilities come back with
    the grouping accounted for. Documents stream through one jitted
    forward in ``max_batch``-sized chunks (the tail chunk pads up to the
    same shape) — this is the doc flavor's bulk surface, so a 1M-row
    history file must not materialize one giant forward."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mlops_tpu.train.long_context import group_documents

    r = bundle.model_config.doc_records
    if ds.cat_ids.shape[0] < r:
        raise SystemExit(
            f"doc bundle needs at least doc_records={r} rows per document; "
            f"file has {ds.cat_ids.shape[0]}"
        )
    cat, num = group_documents(ds.cat_ids, ds.numeric, r)
    docs = cat.shape[0]
    chunk = max(1, min(int(max_batch), docs))
    forward = jax.jit(
        lambda c, x: bundle.model.apply(
            {"params": bundle.variables["params"]}, c, x, train=False
        )
    )
    probs = np.empty(docs, np.float32)
    for lo in range(0, docs, chunk):
        hi = min(lo + chunk, docs)
        pad = chunk - (hi - lo)  # pad the tail to the compiled shape
        c = np.pad(cat[lo:hi], ((0, pad), (0, 0), (0, 0)))
        x = np.pad(num[lo:hi], ((0, pad), (0, 0), (0, 0)))
        logits = forward(jnp.asarray(c), jnp.asarray(x))
        probs[lo:hi] = np.asarray(
            jax.nn.sigmoid(logits / bundle.temperature), np.float32
        )[: hi - lo]
    dropped = int(ds.cat_ids.shape[0] - docs * r)
    return {
        "predictions": [round(float(p), 6) for p in probs],
        "documents": int(docs),
        "records_per_document": r,
        "rows_dropped": dropped,  # tail rows short of a full document
    }


def _score_batch(config) -> int:
    """Bulk-score a large dataset data-parallel over every chip (BASELINE
    config 4). Input: ``data.train_path=<csv>`` or synthetic ``data.rows``."""
    import jax
    import numpy as np

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.data import generate_synthetic
    from mlops_tpu.native import encode_csv
    from mlops_tpu.parallel import make_mesh
    from mlops_tpu.parallel.bulk import score_dataset

    bundle = load_bundle(_resolve_bundle(config))
    if bundle.flavor == "doc":
        raise SystemExit(
            "doc bundles score record histories via "
            "`predict-file data.train_path=<history csv>`; the bulk "
            "scorer's per-record contract does not apply"
        )
    if config.score.streaming:
        # Out-of-core path (the Spark-scale analogue): the dataset never
        # materializes; peak memory is one chunk, each chunk data-parallel
        # over the mesh like the in-memory path (data/stream.py).
        if not config.data.train_path:
            raise SystemExit("score.streaming requires data.train_path=<csv>")
        from mlops_tpu.compilecache.cache import from_config
        from mlops_tpu.data.stream import score_csv_stream

        recorder = None
        stage_sink = None
        if config.trace.enabled:
            # tracewire: pipeline stage timings land in the same span
            # JSONL stream the servers write (kind="stage" records,
            # docs/observability.md) — the bulk path's half of the
            # queryable-log story.
            from pathlib import Path

            from mlops_tpu.trace import TraceRecorder

            config.trace.validate()
            recorder = TraceRecorder(
                Path(config.trace.dir) / "spans-bulk.jsonl",
                capacity=config.trace.ring_capacity,
                flush_interval_s=config.trace.flush_interval_s,
            )
            stage_sink = recorder.stage_sink("score-stream")
        mesh = make_mesh(jax.device_count()) if jax.device_count() > 1 else None
        try:
            stats = score_csv_stream(
                bundle,
                config.data.train_path,
                out_path=config.score.output_path or None,
                chunk_rows=config.score.chunk_rows,
                mesh=mesh,
                exact=True if config.score.exact else None,
                pipeline_depth=config.score.pipeline_depth,
                compile_cache=from_config(config),
                stage_sink=stage_sink,
            )
        finally:
            if recorder is not None:
                recorder.close()
        print(json.dumps(stats))
        return 0
    if config.data.train_path:
        from mlops_tpu.data.parquet import is_parquet, load_parquet_columns

        if is_parquet(config.data.train_path):
            # Columnar path: the C++ kernel is CSV-byte-oriented, so
            # Parquet encodes through the Python pipeline.
            columns, _ = load_parquet_columns(config.data.train_path)
            ds = bundle.preprocessor.encode(columns)
        else:
            # Native one-pass parse+encode when built (the 1M-row hot
            # path); transparent Python fallback otherwise.
            ds = encode_csv(config.data.train_path, bundle.preprocessor)
    else:
        columns, _ = generate_synthetic(config.data.rows, seed=config.data.seed)
        ds = bundle.preprocessor.encode(columns)

    from mlops_tpu.compilecache.cache import from_config

    mesh = make_mesh(jax.device_count()) if jax.device_count() > 1 else None
    result = score_dataset(
        bundle,
        ds,
        mesh=mesh,
        chunk_rows=config.score.chunk_rows,
        drift_sample=config.score.drift_sample,
        seed=config.data.seed,
        exact=True if config.score.exact else None,
        pipeline_depth=config.score.pipeline_depth,
        compile_cache=from_config(config),
    )
    if config.score.output_path:
        np.savez(
            config.score.output_path,
            predictions=result.predictions,
            outliers=result.outliers,
        )
    print(
        json.dumps(
            {
                "devices": jax.device_count(),
                "mesh": list(mesh.devices.shape) if mesh is not None else [1],
                **result.summary(),
            }
        )
    )
    return 0


def _bench(config) -> int:
    """Run the repo-root inference benchmark (the driver's headline number)."""
    import runpy
    from pathlib import Path

    for candidate in (Path.cwd() / "bench.py", Path(__file__).parents[1] / "bench.py"):
        if candidate.is_file():
            runpy.run_path(str(candidate), run_name="__main__")
            return 0
    raise SystemExit("bench.py not found (run from the repo root)")


def _looks_like_dir(value: str) -> bool:
    from pathlib import Path

    return Path(value).is_dir()


def _resolve_bundle(config, model_dir: str | None = None) -> str:
    """One rule for every command: a value that is an existing directory is
    the bundle itself; anything else (version number, stage, "latest")
    resolves through the registry."""
    from mlops_tpu.bundle import ModelRegistry

    model_dir = model_dir or config.serve.model_directory
    if _looks_like_dir(model_dir):
        return model_dir
    return ModelRegistry(config.registry.root).resolve(
        config.registry.model_name, model_dir
    )


def _serve(config) -> int:
    """Serve a bundle over HTTP.

    Env contract parity with the reference (`app/main.py:27,36`):
    ``MODEL_DIRECTORY`` points at a bundle dir (or a registry
    version/stage/"latest"), ``SERVICE_NAME`` names the service in logs.
    """
    import logging
    import os

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    model_dir = os.environ.get("MODEL_DIRECTORY", config.serve.model_directory)
    config.serve.service_name = os.environ.get(
        "SERVICE_NAME", config.serve.service_name
    )
    # Inconsistent worker/ring geometry (or trace/slo knobs) fails the
    # rollout HERE with the constraint named, before anything binds or
    # warms.
    config.serve.validate()
    config.trace.validate()
    config.slo.validate()
    config.autotune.validate()
    if config.autotune.enabled:
        # Cross-section contract, named HERE before anything warms: the
        # gridtuner's demand input is the tracewire shape table and its
        # cost input is the device-time ledger — without both armed the
        # loop would tick forever disarmed.
        if not config.trace.enabled:
            raise SystemExit(
                "autotune.enabled requires trace.enabled (the shape "
                "histograms are the demand input)"
            )
        if not config.slo.ledger_dir:
            raise SystemExit(
                "autotune.enabled requires slo.ledger_dir (the cost "
                "ledger is the cost-model input)"
            )
        if config.serve.tenants_path:
            # One tunable grid per plane: a tenant fleet shares ONE
            # shape table across engines with per-tenant grids, so
            # per-tenant demand cannot be attributed — named here for
            # BOTH planes, not silently mistuned.
            raise SystemExit(
                "autotune.enabled supports single-tenant planes only "
                "(the shared shape table cannot attribute demand per "
                "tenant grid)"
            )
    if config.serve.workers > 1:
        # Multi-worker plane: N SO_REUSEPORT front-end processes + one
        # ENGINE child process, all forked and supervised by this
        # (jax-free) parent over the shared-memory ring
        # (serve/frontend.py). Nothing jax-flavored may import before
        # this branch: the supervisor must stay thread-free and
        # backend-free so every fork — initial and respawn, front end
        # and engine — is safe.
        from mlops_tpu.serve.frontend import serve_multi_worker

        # A tenants.toml names every bundle itself — resolving
        # serve.model_directory (default "latest") against the registry
        # would fail a fleet-only deployment that never registered a
        # "default" model.
        bundle_dir = (
            "" if config.serve.tenants_path
            else _resolve_bundle(config, model_dir)
        )
        return serve_multi_worker(config, bundle_dir)
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.compilecache.cache import from_config
    from mlops_tpu.serve import InferenceEngine, serve_forever

    registry = None
    if config.serve.tenants_path:
        # Multi-tenant fleet on the single-process plane
        # (mlops_tpu/tenancy/): N bundles behind one HTTP server, with
        # architecture-identical tenants sharing compiled entries.
        from mlops_tpu.tenancy import TenantRegistry, load_tenants_toml

        try:
            tenancy = load_tenants_toml(
                config.serve.tenants_path
            ).validate()
        except ValueError as err:
            raise SystemExit(str(err))
        registry = TenantRegistry(
            tenancy,
            buckets=tuple(config.serve.warmup_batch_sizes),
            service_name=config.serve.service_name,
            enable_grouping=config.serve.batch_window_ms > 0,
            compile_cache=from_config(config),
            warmup_workers=config.cache.warmup_workers,
            model_shards=config.serve.model_shards,
            serve_tier=config.serve.serve_tier,
            tier_routing=config.serve.tier_routing,
        )
        engine = registry.default_engine
    else:
        bundle = load_bundle(_resolve_bundle(config, model_dir))
        engine = InferenceEngine(
            bundle,
            buckets=tuple(config.serve.warmup_batch_sizes),
            service_name=config.serve.service_name,
            enable_grouping=config.serve.batch_window_ms > 0,
            # cache.dir set (or MLOPS_TPU_CACHE_DIR, e.g. baked into the
            # Docker image by `warmup`): readiness deserializes
            # executables instead of recompiling them — restarts in
            # seconds, not minutes.
            compile_cache=from_config(config),
            warmup_workers=config.cache.warmup_workers,
            model_shards=config.serve.model_shards,
            serve_tier=config.serve.serve_tier,
            tier_routing=config.serve.tier_routing,
        )
    lifecycle = None
    if config.lifecycle.enabled:
        # Serve-integrated closed loop (mlops_tpu/lifecycle/): the
        # controller thread watches the monitor aggregates, retrains off
        # the hot path, shadow-mirrors, and hot-promotes through gates —
        # ONE controller PER TENANT on a multi-tenant plane (each on a
        # tenant-namespaced state dir; tenant A drifting retrains and
        # promotes A alone).
        from mlops_tpu.lifecycle import LifecycleController

        if registry is not None:
            from mlops_tpu.tenancy import tenant_scoped_config

            # The 1-tenant "default" fleet keeps the UN-NAMESPACED state
            # dir — same guard as the ring plane's _engine_main, so a
            # deployment migrating between a bare model_directory and a
            # one-tenant tenants.toml (or between planes) never abandons
            # its reservoir/candidates/generation state.
            single_default = (
                len(registry) == 1 and registry.names[0] == "default"
            )
            lifecycle = [
                LifecycleController(
                    eng,
                    config if single_default
                    else tenant_scoped_config(config, name),
                )
                for name, eng in zip(registry.names, registry.engines)
            ]
        else:
            lifecycle = LifecycleController(engine, config)
    autotune = None
    if config.autotune.enabled:
        # gridtuner (mlops_tpu/autotune/): periodic cost-model fit +
        # grid search + hot regrid on the live engine (single-tenant —
        # the tenants_path guard above already ran).
        from mlops_tpu.autotune import AutotuneController

        autotune = AutotuneController(engine, config.autotune)
    serve_forever(
        engine, config.serve, lifecycle=lifecycle, trace=config.trace,
        registry=registry, slo=config.slo, autotune=autotune,
    )
    return 0


def _warmup(config) -> int:
    """Pre-populate the AOT executable cache for every registered entry
    point (`mlops-tpu warmup --cache-dir <dir>`): run once at container
    build time and the image ships with its executables baked in — staging
    warms the artifact, prod inherits it, and process warmup becomes
    deserialization instead of compilation.

    With a resolvable bundle (serve.model_directory / MODEL_DIRECTORY /
    registry), the serve + bulk programs warm against that bundle's exact
    state. Without one, everything derives abstractly from the config —
    lowering needs only shapes, so no training has to exist yet.
    """
    import os

    from mlops_tpu.compilecache.cache import CompileCache
    from mlops_tpu.compilecache.warmup import warm_entry_points

    if not config.cache.dir:
        raise SystemExit("pass --cache-dir <dir> (or cache.dir=<dir>)")
    bundle = None
    model_dir = os.environ.get("MODEL_DIRECTORY", config.serve.model_directory)
    try:
        bundle_dir = _resolve_bundle(config, model_dir)
    # No bundle anywhere (fresh checkout, image built before training):
    # config-mode warmup is the documented degradation — announced, so a
    # Docker bake that EXPECTED bundle keys is debuggable from the log.
    except Exception as err:  # tpulint: disable=TPU201
        import sys

        print(
            f"warmup: no bundle at {model_dir!r} ({err}); warming "
            "config-derived programs instead",
            file=sys.stderr,
        )
        bundle_dir = None
    if bundle_dir is not None:
        # A bundle that RESOLVES but fails to load (corrupt weights, bad
        # schema fingerprint) must fail the build loudly — a silently
        # config-keyed cache would make every prod replica miss.
        from mlops_tpu.bundle import load_bundle

        bundle = load_bundle(bundle_dir)
    report = warm_entry_points(config, CompileCache(config.cache.dir), bundle)
    print(json.dumps(report))
    return 0


def _lifecycle(config) -> int:
    """One-shot OFFLINE lifecycle pass (the CI/cron twin of the
    serve-integrated loop): incumbent bundle + labeled window ->
    retrained candidate -> AUC/calibration gates (no mirrored traffic
    offline, so the latency gate auto-passes) -> register on pass. Exit
    0 = promoted/registered, 3 = gates rejected the candidate, SystemExit
    on a window that cannot produce a candidate at all."""
    from mlops_tpu.bundle import ModelRegistry, load_bundle
    from mlops_tpu.lifecycle import (
        LifecycleError,
        ShadowEngine,
        evaluate_gates,
        run_retrain,
    )
    from mlops_tpu.serve import InferenceEngine

    incumbent = load_bundle(_resolve_bundle(config))
    try:
        result = run_retrain(incumbent, config, generation=2)
    except LifecycleError as err:
        raise SystemExit(f"lifecycle: {err}")
    # Grade through the REAL packed serving programs (bucket-shaped
    # chunks), exactly what the serve-integrated shadow does — small
    # bucket grid, no grouping: this is a batch pass, not a server.
    live = InferenceEngine(
        incumbent,
        buckets=tuple(config.serve.warmup_batch_sizes),
        enable_grouping=False,
    )
    live.warmup()
    shadow = ShadowEngine(live, result.bundle)
    shadow.warm()
    report = shadow.evaluate(result.holdout, result.holdout_incumbent)
    decision = evaluate_gates(report, config.lifecycle)
    model_uri = None
    if decision.passed and config.lifecycle.auto_promote:
        registry = ModelRegistry(config.registry.root)
        model_uri = registry.register(
            config.registry.model_name,
            result.candidate_dir,
            tags={"lifecycle": "gated-promotion"},
        )
    print(
        json.dumps(
            {
                "candidate": str(result.candidate_dir),
                "labeled_rows": result.labeled_rows,
                "retrain_wall_s": result.wall_s,
                "auc_candidate": round(report.auc_candidate, 6),
                "auc_incumbent": round(report.auc_incumbent, 6),
                "auc_delta": round(report.auc_delta, 6),
                "ece_candidate": round(report.ece_candidate, 6),
                "gates": decision.as_dict(),
                "model_uri": model_uri,
            }
        )
    )
    return 0 if decision.passed else 3


def _autotune(config) -> int:
    """One-shot OFFLINE gridtuner pass (the CI/cron twin of the
    serve-integrated loop, `lifecycle`'s discipline): persisted ledger
    shards + optional span history in -> one plan JSON line on stdout.
    Exit 0 = a regrid is warranted (plan emitted), 3 = the searched grid
    does not clear ``autotune.min_gain_pct`` (plan still printed for the
    audit trail), SystemExit when the telemetry cannot produce a model
    at all. jax-free end to end — runs anywhere the ledger dir mounts."""
    from mlops_tpu.autotune import demand_from_spans, fit_cost_model
    from mlops_tpu.autotune.search import search_plan
    from mlops_tpu.slo import ledger_report

    config.autotune.validate()
    if not config.slo.ledger_dir:
        raise SystemExit(
            "autotune needs slo.ledger_dir (the directory a served "
            "plane's cost ledger flushed into)"
        )
    report = ledger_report(config.slo.ledger_dir)
    rows = report.get("entries", [])
    model = fit_cost_model(rows)
    if model is None:
        raise SystemExit(
            "autotune: no solo bucket_N entries in the ledger — serve "
            "traffic with slo.ledger_dir armed first"
        )
    # Demand: span history when the trace dir has it (exact per-request
    # rows), else the ledger's per-entry mean rows per dispatch (coarse
    # — one point per warmed bucket — but measured).
    demand = []
    if config.trace.dir:
        from mlops_tpu.trace import load_spans

        try:
            demand = demand_from_spans(load_spans(config.trace.dir))
        except OSError:
            demand = []
    if not demand:
        demand = [
            (
                max(1, int(round(r["rows"] / r["dispatches"]))),
                float(r["dispatches"]),
            )
            for r in rows
            if str(r.get("entry", "")).startswith("bucket_")
            and float(r.get("dispatches", 0)) > 0
        ]
    if not demand:
        raise SystemExit("autotune: no demand observations")
    plan = search_plan(
        demand,
        model,
        tuple(config.serve.warmup_batch_sizes),
        config.autotune.max_entries,
    )
    doc = plan.as_dict()
    warranted = (
        plan.buckets != plan.baseline_buckets
        and plan.predicted_gain_pct >= config.autotune.min_gain_pct
    )
    doc["regrid_warranted"] = warranted
    print(json.dumps(doc))
    return 0 if warranted else 3


def _trace_report(config) -> int:
    """Aggregate a traced server's span JSONL (`mlops-tpu trace-report
    [trace.dir=<dir>]`): p50/p99 per stage per compiled entry — the local
    twin of the reference repo's Kusto latency queries, answering the
    question its logs never could (where did THIS latency go). Prints the
    human table on stderr and the JSON report on stdout (the CLI's
    one-JSON-line discipline). Exit 2 when the dir holds no spans."""
    import sys

    from mlops_tpu.trace import format_report, load_spans, stage_report

    if config.trace.ledger:
        # `--ledger`: rank the device-time cost ledger (slo.ledger_dir —
        # mlops_tpu/slo/ledger.py) by cost_ms_per_row instead of
        # aggregating span files. Same print discipline: human table on
        # stderr, JSON on stdout, exit 2 when the ledger is empty.
        from mlops_tpu.slo import ledger_report
        from mlops_tpu.slo.ledger import format_ledger_report

        if not config.slo.ledger_dir:
            raise SystemExit(
                "trace-report --ledger needs slo.ledger_dir (the "
                "directory a served plane's cost ledger flushed into)"
            )
        report = ledger_report(config.slo.ledger_dir)
        print(format_ledger_report(report), file=sys.stderr)
        print(json.dumps(report))
        return 0 if report["entries"] else 2
    spans = load_spans(config.trace.dir)
    if config.trace.tenant:
        # Per-tenant slice (`--tenant` / trace.tenant): multi-tenant
        # planes stamp every span with its tenant label; spans written
        # before tenancy carry none and count as "default".
        spans = [
            span for span in spans
            if span.get("tenant", "default") == config.trace.tenant
        ]
    if config.trace.replica >= 0:
        # Per-replica slice (`--replica` / trace.replica): the ring
        # plane stamps every span with the engine replica that served
        # it (ISSUE 13); pre-replica spans count as replica 0.
        spans = [
            span for span in spans
            if int(span.get("replica", 0)) == config.trace.replica
        ]
    report = stage_report(spans)
    print(format_report(report), file=sys.stderr)
    print(json.dumps(report))
    return 0 if spans else 2


def _flightrec_paths(paths: list[str]) -> int:
    """`mlops-tpu flightrec <dump.json>...`: render flight-recorder
    dumps into human timelines (stderr) + a JSON summary (stdout — the
    CLI's one-JSON-line discipline). Exit 2 with no readable dumps."""
    import sys

    from mlops_tpu.slo.flightrec import format_timeline, load_dump

    summaries = []
    for path in paths:
        try:
            dump = load_dump(path)
        except (OSError, ValueError) as err:
            print(f"flightrec: unreadable dump {path}: {err}",
                  file=sys.stderr)
            continue
        print(format_timeline(dump), file=sys.stderr)
        summaries.append(
            {
                "path": str(path),
                "reason": dump.get("reason"),
                "source": dump.get("source"),
                "worker": dump.get("worker"),
                "pid": dump.get("pid"),
                "events": len(dump.get("events", [])),
            }
        )
    print(json.dumps(summaries))
    return 0 if summaries else 2


def _flightrec(config) -> int:
    """Handler-table entry for parser/handler sync (tests/test_cli.py);
    ``run()`` intercepts `flightrec` before config loading (it takes
    dump PATHS, not config), so this shim only runs when dispatched
    directly — nothing to render without paths."""
    raise SystemExit("flightrec takes dump paths: mlops-tpu flightrec "
                     "runs/flightrec-*.json")


def _analyze(config) -> int:
    """Handler-table entry for parser/handler sync (tests/test_cli.py);
    ``run()`` intercepts `analyze` before config loading, so this shim only
    runs when dispatched directly — lint the package with defaults."""
    from mlops_tpu.analysis.cli import run_analyze

    return run_analyze(argparse.Namespace())


_HANDLERS = {
    "synth": _synth,
    "analyze": _analyze,
    "train": _train,
    "pretrain": _pretrain,
    "tune": _tune,
    "register": _register,
    "promote": _promote,
    "versions": _versions,
    "gc": _gc,
    "validate": _validate,
    "predict-file": _predict_file,
    "score-batch": _score_batch,
    "bench": _bench,
    "serve": _serve,
    "lifecycle": _lifecycle,
    "autotune": _autotune,
    "warmup": _warmup,
    "trace-report": _trace_report,
    "flightrec": _flightrec,
}
