"""CLI subcommand implementations. Grows with the framework."""

from __future__ import annotations

import argparse

from mlops_tpu.config import load_config


def run(args: argparse.Namespace) -> int:
    config = load_config(args.config, overrides=getattr(args, "overrides", []))
    handler = _HANDLERS.get(args.command)
    if handler is None:
        raise SystemExit(f"subcommand {args.command!r} is not implemented yet")
    return handler(config) or 0


def _synth(config) -> int:
    from mlops_tpu.data import generate_synthetic, write_csv_columns

    path = config.data.train_path or "data/curated.csv"
    columns, labels = generate_synthetic(config.data.rows, seed=config.data.seed)
    write_csv_columns(path, columns, labels)
    print(f"wrote {config.data.rows} rows -> {path}")
    return 0


_HANDLERS = {
    "synth": _synth,
}
