"""Command-line entry point: train | tune | register | serve | bench | predict-file.

Replaces the reference's operational surface (Databricks bundle job runs,
`databricks bundle run train_register_model_job` — `deploy-kubernetes.yml:61`
— and ad-hoc notebook widgets) with one typed CLI.

Subcommands land with their subsystems; this module grows with the framework.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="mlops-tpu",
        description="TPU-native credit-default MLOps framework",
    )
    parser.add_argument(
        "--config", default=None, help="path to a TOML config file"
    )
    sub = parser.add_subparsers(dest="command")
    for name, help_text in [
        ("synth", "generate a synthetic schema-conforming CSV"),
        ("train", "train a model and write a bundle"),
        ("pretrain", "masked-feature pretraining on unlabeled rows (bert)"),
        ("tune", "hyperparameter search (vmapped + sharded trials)"),
        ("register", "register a bundle in the model registry"),
        ("promote", "move a registered version between stages"),
        ("versions", "list registered versions, stages, tags"),
        ("gc", "prune registry orphans (and old unstaged versions)"),
        ("validate", "schema-check a CSV (OOV / unparseable counts)"),
        ("serve", "serve a bundle over HTTP (lifecycle.enabled=true also "
                  "runs the drift-triggered retrain -> shadow -> gated "
                  "hot-promotion loop in-process)"),
        ("lifecycle", "one-shot offline lifecycle pass: retrain a "
                      "candidate from the labeled window "
                      "(lifecycle.labeled_path), grade it against the "
                      "incumbent through the AUC/calibration gates, and "
                      "register it when it passes"),
        ("bench", "run the inference benchmark"),
        ("predict-file", "batch-score a CSV offline"),
        ("score-batch", "bulk-score 1M-scale rows data-parallel over the mesh"),
        ("warmup", "pre-populate the AOT executable cache (compilecache/) "
                   "for every registered entry point — bake it into the "
                   "serving image so restarts deserialize instead of "
                   "recompiling"),
        ("trace-report", "aggregate a traced server's span JSONL "
                         "(trace.dir): p50/p99 per stage per compiled "
                         "entry — where each request spent its latency"),
        ("autotune", "one-shot offline gridtuner pass: fit the per-entry "
                     "dispatch cost model from the device-time ledger "
                     "(slo.ledger_dir), search bucket grids against the "
                     "observed traffic shape, and print the winning "
                     "warmup plan (exit 3 when the current grid already "
                     "wins)"),
    ]:
        p = sub.add_parser(name, help=help_text)
        p.add_argument(
            "overrides",
            nargs="*",
            help="config overrides, e.g. train.steps=500",
        )
        if name == "warmup":
            p.add_argument(
                "--cache-dir",
                default=None,
                help="cache directory (sugar for cache.dir=<dir>)",
            )
        if name == "serve":
            p.add_argument(
                "--workers",
                type=int,
                default=None,
                help="HTTP front-end processes (sugar for serve.workers=N): "
                "N >= 2 binds one port from N processes via SO_REUSEPORT, "
                "all feeding one engine process over the shared-memory "
                "ring; 0/1 = single-process server",
            )
            p.add_argument(
                "--tenants",
                default=None,
                help="multi-tenant fleet declaration (sugar for "
                "serve.tenants_path=<file>): a tenants.toml naming N "
                "tenants (name, bundle_dir, quota weight, default "
                "tenant) served from ONE engine process — requests "
                "route by the x-tenant header, ring-plane admission "
                "(--workers >= 2) is weighted max-min fair per tenant "
                "per slot class (the single-process plane reserves "
                "each tenant a fixed slice of the dispatch pool "
                "instead), and every per-tenant series and span "
                "carries a tenant label",
            )
        if name == "serve":
            p.add_argument(
                "--replicas",
                type=int,
                default=None,
                help="engine replica set (sugar for "
                "serve.engine_replicas=E): E engine processes behind "
                "the one shared-memory ring — front ends fan "
                "descriptors out least-loaded with small-class "
                "affinity, every replica warms from the same AOT "
                "cache, and a kill -9 of one replica is a brownout of "
                "1/E capacity (needs --workers >= 2)",
            )
        if name == "trace-report":
            p.add_argument(
                "--ledger",
                action="store_true",
                help="report the device-time cost ledger (slo.ledger_dir) "
                "ranked by cost_ms_per_row instead of aggregating span "
                "files — the measured per-entry cost model the "
                "traffic-shape autotuner consumes",
            )
            p.add_argument(
                "--tenant",
                default=None,
                help="only aggregate spans whose tenant label matches "
                "(multi-tenant planes stamp every span with its tenant)",
            )
            p.add_argument(
                "--replica",
                type=int,
                default=None,
                help="only aggregate spans served by this engine "
                "replica (the ring plane stamps every span with the "
                "router's choice; pre-replica spans count as 0)",
            )
    # `flightrec` takes dump paths, not config overrides: rendering a
    # post-mortem must work on any box with just the dump files.
    flightrec = sub.add_parser(
        "flightrec",
        help="render flight-recorder dumps (runs/flightrec-*.json — "
        "written on burn-rate alerts, engine respawns, error spikes, "
        "and incident-time drains) into a human timeline",
    )
    flightrec.add_argument(
        "paths",
        nargs="+",
        help="dump files to render (e.g. runs/flightrec-*.json)",
    )
    # `analyze` takes paths + flags, not config overrides: static analysis
    # must run identically with zero configuration (CI, pre-commit).
    analyze = sub.add_parser(
        "analyze",
        help="tpulint: static TPU-correctness lint (AST rules + jaxpr "
        "trace checks over the registered entry points)",
    )
    analyze.add_argument(
        "--strict",
        action="store_true",
        help="warnings gate the exit code too (the CI mode)",
    )
    analyze.add_argument(
        "--no-trace",
        action="store_true",
        help="skip the jaxpr trace layer (no JAX import; AST rules only)",
    )
    analyze.add_argument(
        "--concurrency",
        action="store_true",
        help="also run the Layer-3 concurrency rules (lock-order graph, "
        "guard inference, blocking-under-lock, semaphore pairing — "
        "TPU401-404; pure AST, no JAX import)",
    )
    analyze.add_argument(
        "--contracts",
        action="store_true",
        help="also run the Layer-4 cross-process contract rules (shm "
        "ownership, metric-series parity + alert/doc references, config "
        "knob liveness, fault-point liveness — TPU501-504; pure AST, "
        "no JAX import)",
    )
    analyze.add_argument(
        "--async",
        action="store_true",
        dest="async_rules",
        help="also run the Layer-5 async/event-loop discipline rules "
        "(blocking call in a loop-confined context, fire-and-forget "
        "tasks, cross-thread writes to loop state, await under a sync "
        "mutex — TPU601-604; pure AST, no JAX import)",
    )
    analyze.add_argument(
        "--list-suppressions",
        action="store_true",
        help="report every `# tpulint: disable` in the tree with file:line,"
        " rule ids, and live/stale status, then exit (no analysis gate)",
    )
    analyze.add_argument(
        "--fail-stale",
        action="store_true",
        help="suppressions that no longer suppress anything become gating "
        "TPU400 findings (the CI mode keeping old disables honest)",
    )
    analyze.add_argument(
        "--numeric",
        action="store_true",
        help="also run the checkify numeric audit on the serve entry "
        "point (executes on the current backend; not part of the "
        "abstract gate)",
    )
    analyze.add_argument(
        "paths",
        nargs="*",
        help="files/directories to lint (default: the mlops_tpu package)",
    )
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command is None:
        build_parser().print_help()
        return 1
    from mlops_tpu import commands

    return commands.run(args)


if __name__ == "__main__":
    sys.exit(main())
