"""Per-compiled-entry device-time cost ledger, persisted across runs.

Every packed dispatch through the serving engine accounts
``(entry key, requested rows, padded rows, device-path seconds)`` where
the device-path seconds run from device enqueue to host-copy complete —
the measured per-entry cost the traffic-shape autotuner (ROADMAP
item 2; the learned-TPU-cost-model line, PAPERS.md arXiv 2008.01040)
fits its model against.

Keys are ``<entry>@<model-fingerprint>`` — the compiled entry's shape
name plus the model-config fingerprint the compile cache hashes into
its own keys (`compilecache/keys.model_fingerprint`). The fingerprint
is what keeps the ledger honest across lifecycle events: a PROMOTION to
a different architecture compiles different programs under the same
shape names, and a REGRID changes the shape names under the same model
— either way the accounting lands in a fresh entry instead of
cross-polluting the old one's averages.

Persistence: the ledger directory holds one ``ledger.json``; totals are
loaded at construction and ACCUMULATED (two serve runs against one dir
produce monotone per-entry device-seconds), flushed atomically
(tmp+rename via `utils.io.atomic_write` — no torn ledger, ever) by a
background thread and on ``close()``.

Exported as ``mlops_tpu_entry_device_seconds_total`` /
``mlops_tpu_entry_cost_ms_per_row`` (+ dispatch/row counters) on both
planes — the multi-worker plane mirrors each replica's totals into a
fixed shm table exactly like the tracewire shape stats — and ranked by
``mlops-tpu trace-report --ledger``.

Jax-free; one leaf lock; JSON encode + file I/O run outside it.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from pathlib import Path
from typing import Any

import numpy as np

from mlops_tpu.utils.io import atomic_write

logger = logging.getLogger("mlops_tpu.slo")

TPULINT_LOCK_ORDER = {"CostLedger": ("_lock",)}

LEDGER_NAME = "ledger.json"
LEDGER_VERSION = 1


def _ledger_path(directory: Path, shard: str) -> Path:
    """One file per writer PROCESS: the single-process server (and a
    1-replica engine) own the bare ``ledger.json``; an E-replica fleet
    writes ``ledger-r<k>.json`` per replica so concurrent flushes never
    clobber a sibling's totals — `ledger_report` merges all shards."""
    return directory / (
        f"ledger-{shard}.json" if shard else LEDGER_NAME
    )

# shm mirror geometry (the trace/shapes.py table discipline): row keys
# are "<entry>@<8-hex>" ("group_64x128@abcdef12" = 21 bytes), vals are
# [device_s, dispatches, rows, padded_rows].
TABLE_ROWS = 32
TABLE_KEY_BYTES = 28
TABLE_VALS = 4


class CostLedger:
    def __init__(
        self,
        directory: str | Path,
        flush_interval_s: float = 30.0,
        shard: str = "",
    ) -> None:
        self.dir = Path(directory)
        self.path = _ledger_path(self.dir, shard)
        self._lock = threading.Lock()
        # key -> [device_s, dispatches, rows, padded_rows]
        self._entries: dict[str, list[float]] = {}
        # Stable first-seen shm rows (the ShapeStats rule: never
        # reshuffled, so a scrape racing the mirror can never pair one
        # entry's key with another's counters).
        self._table_rows: dict[str, int] = {}
        self._dirty = False
        self._closed = False
        self.load_errors = 0
        self._load()
        self._wake = threading.Event()
        self._flush_interval_s = max(0.5, float(flush_interval_s))
        self._writer = threading.Thread(
            target=self._run, name="cost-ledger", daemon=True
        )
        self._writer.start()

    def _load(self) -> None:
        """Seed totals from a prior run's file. A corrupt/torn file (only
        reachable by editing it by hand — writes are atomic) is counted
        and starts fresh rather than killing serving."""
        try:
            # Construction-time only (the writer thread starts after),
            # but held anyway: every _entries write sites under _lock.
            doc = json.loads(self.path.read_text())
            with self._lock:
                for key, vals in doc.get("entries", {}).items():
                    self._entries[str(key)] = [
                        float(vals.get("device_s", 0.0)),
                        float(vals.get("dispatches", 0)),
                        float(vals.get("rows", 0)),
                        float(vals.get("padded_rows", 0)),
                    ]
        except FileNotFoundError:
            pass
        except (OSError, ValueError, TypeError):
            self.load_errors += 1
            logger.exception(
                "cost ledger at %s unreadable; starting fresh", self.path
            )

    # ------------------------------------------------------------ hot path
    def observe(
        self,
        entry: str,
        model_tag: str,
        requested_rows: int,
        padded_rows: int,
        device_s: float,
    ) -> None:
        """One dispatch's accounting: a few float adds under a leaf lock
        (the engine's fetch path calls this — never I/O here)."""
        key = f"{entry}@{model_tag}" if model_tag else entry
        with self._lock:
            row = self._entries.get(key)
            if row is None:
                row = self._entries[key] = [0.0, 0.0, 0.0, 0.0]
            row[0] += float(device_s)
            row[1] += 1.0
            row[2] += float(requested_rows)
            row[3] += float(padded_rows)
            self._dirty = True

    # ------------------------------------------------------------ snapshots
    def snapshot(self) -> dict[str, list[float]]:
        with self._lock:
            return {k: list(v) for k, v in self._entries.items()}

    def render_lines(self) -> list[str]:
        return render_entry_lines(self.snapshot())

    # ----------------------------------------------------------- persistence
    def flush(self) -> None:
        """Atomic write of the current totals (tmp+rename): a crash —
        this process's or a sibling's kill -9 — never lands a torn
        ledger."""
        with self._lock:
            if not self._dirty:
                return
            snap = {k: list(v) for k, v in self._entries.items()}
            self._dirty = False
        payload = {
            "version": LEDGER_VERSION,
            "written_at": time.time(),
            "entries": {
                key: {
                    "device_s": round(vals[0], 6),
                    "dispatches": int(vals[1]),
                    "rows": int(vals[2]),
                    "padded_rows": int(vals[3]),
                }
                for key, vals in snap.items()
            },
        }
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            atomic_write(
                self.path, json.dumps(payload, indent=1).encode()
            )
        except OSError:
            # A full disk costs this flush; the totals stay in memory and
            # the next interval retries.
            logger.exception("cost ledger flush failed (%s)", self.path)
            with self._lock:
                self._dirty = True

    def _run(self) -> None:
        while not self._wake.wait(self._flush_interval_s):
            self.flush()

    def close(self) -> None:
        """Final flush + writer join. Safe to call twice."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        self._writer.join(timeout=10)
        self.flush()

    # ------------------------------------------------------------ shm mirror
    def write_table(self, keys: np.ndarray, vals: np.ndarray) -> None:
        """Engine-process single writer: mirror into the ring's fixed
        table (stable first-seen rows; vals before key on new rows — the
        trace/shapes.write_table contract)."""
        with self._lock:
            snap = {k: list(v) for k, v in self._entries.items()}
            for key in snap:
                if key not in self._table_rows and (
                    len(self._table_rows) < TABLE_ROWS
                ):
                    self._table_rows[key] = len(self._table_rows)
            rows = dict(self._table_rows)
        for key, i in rows.items():
            vals[i] = snap[key]
            raw = key.encode()[:TABLE_KEY_BYTES]
            key_row = np.zeros(TABLE_KEY_BYTES, np.uint8)
            key_row[: len(raw)] = np.frombuffer(raw, np.uint8)
            keys[i] = key_row


def read_table(keys: np.ndarray, vals: np.ndarray) -> dict[str, list[float]]:
    entries: dict[str, list[float]] = {}
    for i in range(keys.shape[0]):
        if vals[i, 1] <= 0:  # dispatches: the half-born-row guard
            continue
        raw = bytes(keys[i]).rstrip(b"\x00")
        if not raw:
            continue
        entries[raw.decode(errors="replace")] = [float(v) for v in vals[i]]
    return entries


def merge_entries(
    tables: list[dict[str, list[float]]]
) -> dict[str, list[float]]:
    """Fold several replicas' ledger tables (per-key elementwise sum —
    replicas warm identical entries, so the fold is exact)."""
    merged: dict[str, list[float]] = {}
    for table in tables:
        for key, vals in table.items():
            row = merged.get(key)
            if row is None:
                merged[key] = [float(v) for v in vals]
            else:
                for i, v in enumerate(vals):
                    row[i] += float(v)
    return merged


def _split_key(key: str) -> tuple[str, str]:
    entry, _, model = key.partition("@")
    return entry, model


def render_entry_lines(entries: dict[str, list[float]]) -> list[str]:
    """THE ledger exposition block — one formatter for both planes (the
    trace/shapes._lines discipline). ``entry`` carries the shape name,
    ``model`` the fingerprint that keys the compile cache."""
    if not entries:
        return []
    lines = ["# TYPE mlops_tpu_entry_device_seconds_total counter"]
    for key in sorted(entries):
        entry, model = _split_key(key)
        lines.append(
            f'mlops_tpu_entry_device_seconds_total{{entry="{entry}",'
            f'model="{model}"}} {round(entries[key][0], 6)}'
        )
    lines.append("# TYPE mlops_tpu_entry_dispatch_total counter")
    for key in sorted(entries):
        entry, model = _split_key(key)
        lines.append(
            f'mlops_tpu_entry_dispatch_total{{entry="{entry}",'
            f'model="{model}"}} {int(entries[key][1])}'
        )
    lines.append("# TYPE mlops_tpu_entry_rows_total counter")
    for key in sorted(entries):
        entry, model = _split_key(key)
        lines.append(
            f'mlops_tpu_entry_rows_total{{entry="{entry}",'
            f'model="{model}"}} {int(entries[key][2])}'
        )
    lines.append("# TYPE mlops_tpu_entry_cost_ms_per_row gauge")
    for key in sorted(entries):
        entry, model = _split_key(key)
        vals = entries[key]
        cost = 1e3 * vals[0] / vals[2] if vals[2] > 0 else 0.0
        lines.append(
            f'mlops_tpu_entry_cost_ms_per_row{{entry="{entry}",'
            f'model="{model}"}} {round(cost, 6)}'
        )
    return lines


def ledger_report(directory: str | Path) -> dict[str, Any]:
    """`mlops-tpu trace-report --ledger`: the on-disk ledger ranked by
    ``cost_ms_per_row`` (descending — the most expensive entry per
    useful row first, i.e. where a regrid buys the most). Merges every
    shard in the directory (an E-replica fleet writes one per
    replica)."""
    directory = Path(directory)
    merged: dict[str, dict[str, float]] = {}
    for path in sorted(directory.glob("ledger*.json")):
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError):
            continue
        for key, vals in doc.get("entries", {}).items():
            row = merged.setdefault(
                key,
                {"device_s": 0.0, "dispatches": 0, "rows": 0,
                 "padded_rows": 0},
            )
            row["device_s"] += float(vals.get("device_s", 0.0))
            row["dispatches"] += int(vals.get("dispatches", 0))
            row["rows"] += int(vals.get("rows", 0))
            row["padded_rows"] += int(vals.get("padded_rows", 0))
    rows = []
    for key, vals in merged.items():
        entry, model = _split_key(key)
        device_s = float(vals.get("device_s", 0.0))
        dispatches = int(vals.get("dispatches", 0))
        n_rows = int(vals.get("rows", 0))
        padded = int(vals.get("padded_rows", 0))
        rows.append(
            {
                "key": key,
                "entry": entry,
                "model": model,
                "device_s": round(device_s, 6),
                "dispatches": dispatches,
                "rows": n_rows,
                "padded_rows": padded,
                "cost_ms_per_row": round(
                    1e3 * device_s / n_rows if n_rows else 0.0, 6
                ),
                "cost_ms_per_dispatch": round(
                    1e3 * device_s / dispatches if dispatches else 0.0, 6
                ),
                "padding_waste_pct": round(
                    100.0 * (1.0 - n_rows / padded) if padded else 0.0, 3
                ),
            }
        )
    rows.sort(key=lambda r: -r["cost_ms_per_row"])
    return {"ledger": str(directory), "entries": rows}


def format_ledger_report(report: dict[str, Any]) -> str:
    lines = [f"ledger: {report['ledger']} ({len(report['entries'])} entries)"]
    for row in report["entries"]:
        lines.append(
            f"  {row['entry']:>16}@{row['model']:<10}"
            f" cost/row {row['cost_ms_per_row']:9.4f} ms"
            f"  device {row['device_s']:9.3f} s"
            f"  dispatches {row['dispatches']:>8}"
            f"  rows {row['rows']:>10}"
            f"  waste {row['padding_waste_pct']:5.1f}%"
        )
    return "\n".join(lines)
