"""Flight recorder: a bounded ring of recent evidence, dumped on anomaly.

Every serving process keeps the last ``capacity`` events — request
summaries (status / tenant / latency / request id) and, when tracewire
is armed, finished spans — in memory. When an anomaly trips (a
burn-rate alert firing, an engine respawn, a 5xx/504 spike, a lifecycle
breaker opening) the ring is DUMPED atomically (tmp+rename, the PR 9
persistence discipline via `utils.io.atomic_write`) to
``<dir>/flightrec-*.json``, so a post-mortem has the last N seconds of
evidence even after ``kill -9`` of a sibling process — a torn dump can
never land, proven by the same SIGKILL subprocess tests as the other
atomic writers.

Quiet planes write NOTHING: dumps happen only on triggers, a cooldown
bounds dump frequency under a sustained incident, and retention prunes
the directory to the newest ``keep`` files. The SIGTERM/fatal hook
(`dump_if_evidence`) dumps only when the ring actually holds errors or
an alert fired since the last dump — a clean drain leaves a clean
directory (the serve-smoke zero-dump contract).

Jax-free; one leaf lock; the JSON encode and the file write run OUTSIDE
it (TPU403 discipline).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any

from mlops_tpu.utils.io import atomic_write

logger = logging.getLogger("mlops_tpu.slo")

TPULINT_LOCK_ORDER = {"FlightRecorder": ("_lock",)}


class FlightRecorder:
    def __init__(
        self,
        directory: str | Path,
        capacity: int = 2048,
        cooldown_s: float = 30.0,
        keep: int = 8,
        source: str = "single",
        worker: int = 0,
        spike_errors: int = 8,
        spike_window_s: float = 5.0,
        on_dump=None,
    ) -> None:
        self.dir = Path(directory)
        self.capacity = max(1, int(capacity))
        self.cooldown_s = float(cooldown_s)
        self.keep = max(1, int(keep))
        self.source = source
        self.worker = int(worker)
        self.spike_errors = max(1, int(spike_errors))
        self.spike_window_s = float(spike_window_s)
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._err_times: collections.deque = collections.deque()
        self._last_dump = float("-inf")
        self._evidence = False  # errors/alerts noted since the last dump
        self.dumps = 0  # dump ATTEMPTS (filename sequence)
        self.landed = 0  # dumps that actually hit disk (the exported one)
        self.suppressed = 0  # triggers swallowed by the cooldown
        # Called with the landed path after each successful dump (the
        # ring plane mirrors its dump count into shm through this).
        self._on_dump = on_dump

    # ------------------------------------------------------------ hot path
    def note(self, kind: str, **fields: Any) -> None:
        """Append one event (bounded ring; never blocks, never a syscall)."""
        event = {"kind": kind, "t": time.monotonic(), "ts": time.time()}
        event.update(fields)
        with self._lock:
            self._events.append(event)

    def observe_request(
        self,
        route: str,
        status: int,
        latency_ms: float,
        tenant: str = "default",
        request_id: str = "",
    ) -> None:
        """One request summary. SERVING failures (5xx on ``/predict`` —
        the shed 503 and deadline 504 included) also feed the spike
        detector: reaching ``spike_errors`` failures inside
        ``spike_window_s`` trips a dump even when no burn-rate alert is
        armed to notice. Non-predict 5xx (a readiness probe's 503 while
        the plane warms) are recorded in the ring but are neither spike
        fuel nor drain-time evidence — the same scoping as the
        availability SLO."""
        now = time.monotonic()
        event = {
            "kind": "request",
            "t": now,
            "ts": time.time(),
            "route": route,
            "status": int(status),
            "latency_ms": round(float(latency_ms), 3),
            "tenant": tenant,
        }
        if request_id:
            event["request_id"] = request_id
        spike = False
        with self._lock:
            self._events.append(event)
            if status >= 500 and route == "/predict":
                self._evidence = True
                self._err_times.append(now)
                while (
                    self._err_times
                    and now - self._err_times[0] > self.spike_window_s
                ):
                    self._err_times.popleft()
                if len(self._err_times) >= self.spike_errors:
                    self._err_times.clear()  # re-arm for the next window
                    spike = True
        if spike:
            self.trigger("error_spike")

    def note_span(self, record: dict[str, Any]) -> None:
        """A finished tracewire span record (only when tracing is armed):
        the dump's timeline then names the compiled entry and per-stage
        milliseconds of the offending requests, not just their statuses."""
        with self._lock:
            self._events.append({"kind": "span", "t": time.monotonic(),
                                 **record})

    # ------------------------------------------------------------ triggers
    def note_alert(self, alert: str, tenant: str, severity: str) -> None:
        """An alert transition (the SLO engine's on_alert hook lands
        here, as does a front end watching shm flags): recorded into the
        ring — the dump shows WHEN the alert flipped relative to the
        requests around it — then trips a dump through the cooldown."""
        self.note("alert", alert=alert, tenant=tenant, severity=severity)
        with self._lock:
            self._evidence = True
        self.trigger(f"alert-{alert}")

    def trigger(self, reason: str) -> threading.Thread | None:
        """Anomaly trip: dump unless a dump landed inside the cooldown
        (a sustained incident produces a bounded file stream, not one
        per tick). The write runs on a short-lived DAEMON THREAD — the
        hottest trigger is the 5xx spike, which fires from the request
        path on the asyncio event loop, exactly when the plane is
        already burning; a slow disk must cost a late dump, never
        request tail latency. The cooldown slot is claimed here (so
        concurrent triggers cannot stack dumps) and restored by a
        failed write (`dump`). Returns the writer thread (joinable for
        tests), or None when suppressed."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump < self.cooldown_s:
                self.suppressed += 1
                return None
            self._last_dump = now
        writer = threading.Thread(
            target=self.dump, args=(reason,),
            name="flightrec-dump", daemon=True,
        )
        writer.start()
        return writer

    def dump_if_evidence(self, reason: str) -> Path | None:
        """The SIGTERM/fatal hook: dump only when the ring holds actual
        evidence (a 5xx/504 or an alert since the last dump) — a clean
        drain writes nothing, an incident-time drain preserves the tail."""
        with self._lock:
            if not self._evidence:
                return None
        return self.dump(reason)

    # ---------------------------------------------------------------- dump
    def dump(self, reason: str) -> Path | None:
        """Snapshot the ring and write it ATOMICALLY (tmp+rename): a
        reader — or a sibling's kill -9 landing mid-write — never sees a
        torn file; the failed-write temp never leaks
        (`utils.io.atomic_write`). Returns the path, or None when the
        write failed (a full disk costs the dump, never the serving
        path)."""
        with self._lock:
            events = list(self._events)
            self._evidence = False
            self.dumps += 1
            seq = self.dumps
        payload = {
            "kind": "flightrec",
            "reason": reason,
            "ts": time.time(),
            "t": time.monotonic(),
            "pid": os.getpid(),
            "source": self.source,
            "worker": self.worker,
            "events": events,
        }
        name = (
            f"flightrec-{int(payload['ts'] * 1e3)}-p{os.getpid()}"
            f"-{seq}-{_safe(reason)}.json"
        )
        path = self.dir / name
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            atomic_write(path, json.dumps(payload).encode())
            self._prune()
        except OSError:
            # A failed write (full disk, mid-incident — exactly when
            # dumps fire) must neither burn the cooldown slot nor eat
            # the evidence: restore both so the NEXT trigger (or an
            # operator's drain) retries instead of preserving nothing.
            logger.exception("flight-recorder dump failed (%s)", reason)
            with self._lock:
                self._evidence = True
                self._last_dump = float("-inf")
            return None
        logger.warning(
            "flight recorder dumped %d events -> %s (reason: %s)",
            len(events), path, reason,
        )
        with self._lock:
            self.landed += 1
        if self._on_dump is not None:
            self._on_dump(path)
        return path

    def _prune(self) -> None:
        """Retention: keep the newest ``keep`` dumps in the directory
        (fleet-wide — every process prunes the shared dir by mtime)."""
        dumps = sorted(
            self.dir.glob("flightrec-*.json"),
            key=lambda p: p.stat().st_mtime,
            reverse=True,
        )
        for stale in dumps[self.keep:]:
            try:
                stale.unlink()
            except OSError:
                pass  # a sibling pruned it first


def _safe(reason: str) -> str:
    return "".join(
        c if c.isalnum() or c in "-_" else "-" for c in reason
    )[:48] or "trigger"


# ------------------------------------------------------------- CLI render
def load_dump(path: str | Path) -> dict[str, Any]:
    return json.loads(Path(path).read_text())


def format_timeline(dump: dict[str, Any]) -> str:
    """Human timeline of one dump (`mlops-tpu flightrec <dump.json>`):
    events ordered by monotonic time, offsets relative to the dump
    moment (negative = before the dump)."""
    t_dump = float(dump.get("t", 0.0))
    head = (
        f"flightrec dump: reason={dump.get('reason')} "
        f"pid={dump.get('pid')} source={dump.get('source')}"
        f" worker={dump.get('worker')} events={len(dump.get('events', []))}"
    )
    lines = [head]
    for event in sorted(
        dump.get("events", []), key=lambda e: float(e.get("t", 0.0))
    ):
        offset = float(event.get("t", 0.0)) - t_dump
        kind = event.get("kind", "?")
        if kind == "request":
            detail = (
                f"{event.get('route', '?')} {event.get('status', '?')} "
                f"{event.get('latency_ms', '?')}ms "
                f"tenant={event.get('tenant', '?')}"
            )
            if event.get("request_id"):
                detail += f" id={event['request_id']}"
        elif kind == "span":
            stages = event.get("stages") or {}
            top = sorted(stages.items(), key=lambda kv: -kv[1])[:3]
            detail = (
                f"trace={event.get('trace_id', '?')} "
                f"status={event.get('status', '?')} "
                f"entry={event.get('entry', '-')} "
                f"wall={event.get('wall_ms', '?')}ms "
                + " ".join(f"{k}={v}ms" for k, v in top)
            )
        elif kind == "alert":
            detail = (
                f"{event.get('alert', '?')} tenant={event.get('tenant', '?')}"
                f" severity={event.get('severity', '?')}"
            )
        else:
            detail = " ".join(
                f"{k}={v}"
                for k, v in event.items()
                if k not in ("kind", "t", "ts")
            )
        lines.append(f"{offset:+9.3f}s  {kind:>7}  {detail}")
    return "\n".join(lines)
