"""SLO / error-budget engine: burn rates, alerts, and the health verdict.

The accounting model is the SRE-workbook multiwindow multi-burn-rate
alerting scheme over two SLO dimensions per tenant:

- ``availability`` — the fraction of ``/predict`` requests answered
  without a server-side failure (5xx: the 500 contract, shed 503s, and
  deadline 504s all spend budget — a shed request is not goodput, which
  is exactly the fleet-goodput framing of PAPERS.md arXiv 2502.06982);
- ``latency`` — the fraction of requests answered inside the configured
  threshold, measured against the existing latency histogram (the
  effective threshold is the smallest bucket edge >= the configured one;
  the gauges say which).

A burn rate is ``bad_fraction / (1 - target)`` over a trailing window:
1.0 means the error budget spends exactly at the rate that exhausts it
at the window's end; 14.4 (the classic page threshold) exhausts a
30-day budget in ~2 days. Each alert requires BOTH its windows over the
threshold — the long window filters blips, the short window ends the
alert quickly once the burn stops.

Everything here is jax-free and plane-agnostic: the single-process
server ticks an `SLOEngine` against `ServingMetrics.slo_counts`; the
multi-worker plane's LEAD engine replica ticks one against the shm
ring's fleet counters and mirrors the result into shm rows
(`write_slo_rows`) so any SO_REUSEPORT front end renders fleet verdicts
(`read_slo_view` + the ONE formatter `render_slo_lines` — the
`ServingMetrics.robustness_lines` discipline: identical series names on
both planes). The ``engine_down`` alert is the one exception: it is
computed at RENDER time by whoever answers the scrape, because a dead
engine cannot report its own death.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Callable

# tpulint Layer-3 manifest: one leaf lock guarding the sample deques and
# the computed view. `tick` calls its counter source OUTSIDE the lock
# (sources take their own leaf locks — ServingMetrics._lock); inside is
# pure host arithmetic, never I/O, never a device call.
TPULINT_LOCK_ORDER = {"SLOEngine": ("_lock",)}

SLO_NAMES = ("availability", "latency")

# Alerts the ENGINE evaluates per tenant (their flags live in the shm
# mirror). ``engine_down`` is deliberately absent: the renderer computes
# it from supervisor state. Order is the shm column order.
ENGINE_ALERTS = (
    "availability_fast_burn",
    "availability_slow_burn",
    "latency_fast_burn",
    "latency_slow_burn",
    "lifecycle_breaker",
)
ALERT_SEVERITY = {
    "availability_fast_burn": "page",
    "availability_slow_burn": "ticket",
    "latency_fast_burn": "page",
    "latency_slow_burn": "ticket",
    "lifecycle_breaker": "ticket",
    "engine_down": "page",
}

# Per-tenant shm row layout (serve/ipc.py ``slo_vals``): a HAS flag then
# 7 fields per SLO dimension, in SLO_NAMES order.
SLO_HAS = 0
_PER_SLO = 7  # good, total, budget_pct, burn x 4 windows
SLO_FIELDS = 1 + _PER_SLO * len(SLO_NAMES)
N_ENGINE_ALERTS = len(ENGINE_ALERTS)

# Per-tenant sample cap: at the default 1 s tick the 3-day slow window
# would otherwise retain ~259k samples per tenant and the per-tick
# reference scans would grow with uptime. Past the cap the OLDEST half
# thins to every-other sample (repeatedly, so resolution decays
# geometrically with age): the recent region stays tick-fine for the
# fast windows while a 3-day window's reference lands within ~minutes
# of its ideal position — a fraction-of-a-percent burn error on a
# 3-day number, for O(1) memory and O(log n) lookups (bisect; the list
# is time-sorted).
_MAX_SAMPLES = 4096


def window_label(seconds: float) -> str:
    """Human window label for the ``window=`` series dimension: 300 ->
    "5m", 3600 -> "1h", 259200 -> "3d"; anything non-round stays "Ns"
    (test-scale sub-minute windows render honestly)."""
    s = int(seconds)
    if s >= 86400 and s % 86400 == 0:
        return f"{s // 86400}d"
    if s >= 3600 and s % 3600 == 0:
        return f"{s // 3600}h"
    if s >= 60 and s % 60 == 0:
        return f"{s // 60}m"
    return f"{s}s"


def _zero_slo_block(windows: tuple[float, ...]) -> dict[str, Any]:
    return {
        "good": 0,
        "total": 0,
        "budget_pct": 100.0,
        "burn": {window_label(w): 0.0 for w in windows},
    }


def zero_view(
    tenants: tuple[str, ...], windows: tuple[float, ...]
) -> dict[str, Any]:
    """The always-emit baseline: every series exists (at zero / full
    budget) from the first scrape — "no series" must never be
    confusable with "no problem" (the PR 6 always-emit contract)."""
    return {
        tenant: {
            "slos": {slo: _zero_slo_block(windows) for slo in SLO_NAMES},
            "alerts": {alert: False for alert in ENGINE_ALERTS},
        }
        for tenant in tenants
    }


class SLOEngine:
    """Windowed SLO evaluation over cumulative good/total counters.

    ``source()`` returns ``{tenant_label: (avail_good, avail_total,
    lat_good, lat_total)}`` — CUMULATIVE counts since process start (the
    engine differences them itself). ``breaker_source()`` (optional)
    returns ``{tenant_label: bool}`` — the lifecycle circuit breaker's
    open flag, surfaced as the ``lifecycle_breaker`` alert so a broken
    retrain path pages through the same channel as a burn.
    ``on_alert(alert, tenant, severity)`` fires on each INACTIVE ->
    ACTIVE transition (the flight recorder's dump trigger).

    ``prior_counts`` (``{tenant: (avail_good, avail_total, lat_good,
    lat_total)}``) seeds the exported totals with a PREDECESSOR's
    published values — the ISSUE 11 respawn-base discipline: a
    respawned engine replica's fresh evaluator re-baselines against
    the (surviving) shm request counters, and without the seed its
    ``slo_*_total`` series would restart near zero, which Prometheus
    reads as a counter reset (and the chaos smoke flags as a monotone
    regression). Seeded, the first tick re-publishes exactly the dead
    incarnation's totals and growth continues from there.
    """

    def __init__(
        self,
        config: Any,  # config.SLOConfig (duck-typed: jax-free module)
        tenants: tuple[str, ...],
        source: Callable[[], dict[str, tuple[int, int, int, int]]],
        breaker_source: Callable[[], dict[str, bool]] | None = None,
        on_alert: Callable[[str, str, str], None] | None = None,
        prior_counts: dict[str, tuple[int, int, int, int]] | None = None,
    ) -> None:
        self.config = config
        self.tenants = tuple(tenants) or ("default",)
        self._source = source
        self._breaker_source = breaker_source
        self._on_alert = on_alert
        self.windows: tuple[float, ...] = (
            float(config.fast_short_s),
            float(config.fast_long_s),
            float(config.slow_short_s),
            float(config.slow_long_s),
        )
        self._targets = {
            "availability": float(config.availability_target),
            "latency": float(config.latency_target),
        }
        self._lock = threading.Lock()
        # tenant -> list of (t, avail_good, avail_total, lat_good,
        # lat_total) samples, pruned to the slowest window. The
        # CONSTRUCTION-TIME sample is kept separately as the budget
        # BASELINE: budgets measure what happened since sloscope armed,
        # so counters predating it never bill the budget — and window
        # pruning can never silently turn the budget into a rolling one.
        self._samples: dict[str, list[tuple[float, ...]]] = {}
        self._baseline: dict[str, tuple[float, ...]] = {}
        self._prior = dict(prior_counts or {})
        self._active: dict[tuple[str, str], bool] = {
            (alert, tenant): False
            for alert in ENGINE_ALERTS
            for tenant in self.tenants
        }
        self._view = zero_view(self.tenants, self.windows)
        self.ticks = 0
        self.tick()

    # -------------------------------------------------------------- tick
    def tick(self, now: float | None = None) -> None:
        """One evaluation: sample the cumulative counters, recompute every
        window's burn rate, update alert states (firing ``on_alert`` on
        rising edges). Cheap host arithmetic — safe at any cadence; the
        acceptance contract is "alerts flip within two ticks of the
        counters crossing the threshold"."""
        now = time.monotonic() if now is None else float(now)
        counts = self._source()  # outside the lock: sources self-lock
        breakers = (
            self._breaker_source() if self._breaker_source is not None else {}
        )
        fired: list[tuple[str, str]] = []
        with self._lock:
            horizon = now - max(self.windows) - 2.0 * float(
                self.config.tick_s
            )
            view: dict[str, Any] = {}
            for tenant in self.tenants:
                ag, at, lg, lt = (
                    int(x) for x in counts.get(tenant, (0, 0, 0, 0))
                )
                samples = self._samples.setdefault(tenant, [])
                samples.append((now, ag, at, lg, lt))
                if tenant not in self._baseline:
                    s0 = samples[0]
                    prior = self._prior.get(tenant)
                    if prior:
                        # Respawn-base seed: shift the baseline back by
                        # the predecessor's published totals so the
                        # exported counters continue instead of reset.
                        self._baseline[tenant] = (
                            s0[0],
                            s0[1] - int(prior[0]),
                            s0[2] - int(prior[1]),
                            s0[3] - int(prior[2]),
                            s0[4] - int(prior[3]),
                        )
                    else:
                        self._baseline[tenant] = s0
                while len(samples) > 2 and samples[1][0] <= horizon:
                    # Keep one sample older than the slowest window so
                    # every window has a reference to difference against.
                    samples.pop(0)
                if len(samples) > _MAX_SAMPLES:
                    # Bounded retention: thin the oldest half to
                    # every-other sample (see _MAX_SAMPLES).
                    half = len(samples) // 2
                    samples[:half] = samples[:half:2]
                blocks: dict[str, Any] = {}
                for s_i, slo in enumerate(SLO_NAMES):
                    gi, ti = 1 + 2 * s_i, 2 + 2 * s_i
                    base = self._baseline[tenant]
                    good = samples[-1][gi] - base[gi]
                    total = samples[-1][ti] - base[ti]
                    budget = 1.0 - self._targets[slo]
                    bad_frac = (
                        (total - good) / total if total > 0 else 0.0
                    )
                    budget_pct = (
                        100.0 * (1.0 - bad_frac / budget)
                        if budget > 0
                        else 100.0
                    )
                    burns: dict[str, float] = {}
                    for w in self.windows:
                        # Last sample at or before the window start,
                        # falling back to the oldest retained (a window
                        # older than the history uses what exists).
                        idx = bisect.bisect_right(
                            samples, now - w, key=lambda s: s[0]
                        )
                        ref = samples[idx - 1] if idx > 0 else samples[0]
                        d_total = samples[-1][ti] - ref[ti]
                        d_good = samples[-1][gi] - ref[gi]
                        frac = (
                            (d_total - d_good) / d_total
                            if d_total > 0
                            else 0.0
                        )
                        burns[window_label(w)] = round(
                            frac / budget if budget > 0 else 0.0, 4
                        )
                    blocks[slo] = {
                        "good": good,
                        "total": total,
                        "budget_pct": round(budget_pct, 3),
                        "burn": burns,
                    }
                alerts: dict[str, bool] = {}
                for slo in SLO_NAMES:
                    burns = blocks[slo]["burn"]
                    fast = float(self.config.fast_burn_threshold)
                    slow = float(self.config.slow_burn_threshold)
                    fs, fl = self.windows[0], self.windows[1]
                    ss, sl = self.windows[2], self.windows[3]
                    alerts[f"{slo}_fast_burn"] = (
                        burns[window_label(fs)] >= fast
                        and burns[window_label(fl)] >= fast
                    )
                    alerts[f"{slo}_slow_burn"] = (
                        burns[window_label(ss)] >= slow
                        and burns[window_label(sl)] >= slow
                    )
                alerts["lifecycle_breaker"] = bool(breakers.get(tenant))
                for alert, active in alerts.items():
                    key = (alert, tenant)
                    if active and not self._active[key]:
                        fired.append(key)
                    self._active[key] = active
                view[tenant] = {"slos": blocks, "alerts": alerts}
            self._view = view
            self.ticks += 1
        if self._on_alert is not None:
            for alert, tenant in fired:
                # Outside the lock: the hook may dump a flight recording.
                self._on_alert(alert, tenant, ALERT_SEVERITY[alert])

    # ------------------------------------------------------------- reads
    def view(self) -> dict[str, Any]:
        with self._lock:
            return self._view

    def any_alert_active(self) -> bool:
        with self._lock:
            return any(self._active.values())

    def render_lines(self, engine_down: bool = False) -> list[str]:
        return render_slo_lines(self.view(), engine_down=engine_down)

    # -------------------------------------------------------- shm mirror
    def write_rows(self, slo_vals, alert_vals) -> None:
        """Mirror the computed view into the ring's per-tenant rows
        (engine-process single writer; per-field f64 stores are
        individually atomic — the `write_monitor` tearing contract)."""
        view = self.view()
        for t, tenant in enumerate(self.tenants):
            block = view[tenant]
            row = slo_vals[t]
            for s_i, slo in enumerate(SLO_NAMES):
                b = block["slos"][slo]
                o = 1 + s_i * _PER_SLO
                row[o] = float(b["good"])
                row[o + 1] = float(b["total"])
                row[o + 2] = float(b["budget_pct"])
                for w_i, w in enumerate(self.windows):
                    row[o + 3 + w_i] = float(b["burn"][window_label(w)])
            for a_i, alert in enumerate(ENGINE_ALERTS):
                alert_vals[t, a_i] = 1.0 if block["alerts"][alert] else 0.0
            row[SLO_HAS] = 1.0


def read_slo_view(
    slo_vals,
    alert_vals,
    tenants: tuple[str, ...],
    windows: tuple[float, ...],
) -> dict[str, Any]:
    """Rebuild the view dict from the shm rows (any front end renders the
    fleet verdict the lead replica last published; rows never written —
    e.g. the engine died before its first tick — render the zero
    baseline, which is exactly the last-known-values contract)."""
    view = zero_view(tenants, windows)
    for t, tenant in enumerate(tenants):
        row = slo_vals[t]
        if not float(row[SLO_HAS]):
            continue
        block = view[tenant]
        for s_i, slo in enumerate(SLO_NAMES):
            o = 1 + s_i * _PER_SLO
            block["slos"][slo] = {
                "good": int(row[o]),
                "total": int(row[o + 1]),
                "budget_pct": round(float(row[o + 2]), 3),
                "burn": {
                    window_label(w): round(float(row[o + 3 + w_i]), 4)
                    for w_i, w in enumerate(windows)
                },
            }
        block["alerts"] = {
            alert: bool(alert_vals[t, a_i])
            for a_i, alert in enumerate(ENGINE_ALERTS)
        }
    return view


def render_slo_lines(
    view: dict[str, Any], engine_down: bool = False
) -> list[str]:
    """THE SLO exposition block — ONE definition shared by the
    single-process render and the ring render so both planes export
    identical series names. Every series is ALWAYS emitted for every
    tenant and every alert (zero baseline; an absent series would be
    indistinguishable from a healthy one)."""
    lines = ["# TYPE mlops_tpu_slo_good_total counter"]
    tenants = sorted(view)
    for tenant in tenants:
        for slo in SLO_NAMES:
            lines.append(
                f'mlops_tpu_slo_good_total{{slo="{slo}",tenant="{tenant}"}} '
                f"{int(view[tenant]['slos'][slo]['good'])}"
            )
    lines.append("# TYPE mlops_tpu_slo_total counter")
    for tenant in tenants:
        for slo in SLO_NAMES:
            lines.append(
                f'mlops_tpu_slo_total{{slo="{slo}",tenant="{tenant}"}} '
                f"{int(view[tenant]['slos'][slo]['total'])}"
            )
    lines.append("# TYPE mlops_tpu_error_budget_remaining_pct gauge")
    for tenant in tenants:
        for slo in SLO_NAMES:
            lines.append(
                "mlops_tpu_error_budget_remaining_pct"
                f'{{slo="{slo}",tenant="{tenant}"}} '
                f"{view[tenant]['slos'][slo]['budget_pct']}"
            )
    lines.append("# TYPE mlops_tpu_slo_burn_rate gauge")
    for tenant in tenants:
        for slo in SLO_NAMES:
            for label, burn in view[tenant]["slos"][slo]["burn"].items():
                lines.append(
                    f'mlops_tpu_slo_burn_rate{{slo="{slo}",'
                    f'tenant="{tenant}",window="{label}"}} {burn}'
                )
    lines.append("# TYPE mlops_tpu_alert_active gauge")
    for tenant in tenants:
        for alert in ENGINE_ALERTS:
            active = view[tenant]["alerts"].get(alert, False)
            lines.append(
                f'mlops_tpu_alert_active{{alert="{alert}",'
                f'severity="{ALERT_SEVERITY[alert]}",tenant="{tenant}"}} '
                f"{1 if active else 0}"
            )
        # engine_down is renderer-computed (a dead engine cannot report
        # its own death): the same value for every tenant — the outage
        # is plane-wide.
        lines.append(
            f'mlops_tpu_alert_active{{alert="engine_down",'
            f'severity="{ALERT_SEVERITY["engine_down"]}",'
            f'tenant="{tenant}"}} {1 if engine_down else 0}'
        )
    return lines


def health_verdict(
    view: dict[str, Any] | None,
    ready: bool,
    engine_down: bool = False,
) -> tuple[int, dict[str, Any], str]:
    """THE ``/healthz`` verdict wire shape, shared by both planes:

    - ``down`` (503) — the engine is dead (full outage) or the plane
      never became ready: probes and gateways should route away;
    - ``degraded`` (200) — serving, but at least one alert is active
      (the body names them): humans should look;
    - ``ok`` (200) — serving inside its SLOs.

    200-with-degraded rather than 503 is deliberate: a burn alert means
    the error budget is SPENDING, not that this instance should be
    pulled — pulling it would turn a burn into an outage."""
    alerts: list[dict[str, str]] = []
    if view:
        for tenant in sorted(view):
            for alert, active in view[tenant]["alerts"].items():
                if active:
                    alerts.append(
                        {
                            "alert": alert,
                            "tenant": tenant,
                            "severity": ALERT_SEVERITY.get(alert, "ticket"),
                        }
                    )
    if engine_down:
        alerts.insert(
            0,
            {
                "alert": "engine_down",
                "tenant": "*",
                "severity": ALERT_SEVERITY["engine_down"],
            },
        )
    if engine_down or not ready:
        verdict, status = "down", 503
    elif alerts:
        verdict, status = "degraded", 200
    else:
        verdict, status = "ok", 200
    return (
        status,
        {"verdict": verdict, "ready": bool(ready), "alerts": alerts},
        "application/json",
    )
