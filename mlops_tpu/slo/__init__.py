"""sloscope (ISSUE 14): the fleet-health layer — jax-free.

Three cooperating pieces, threaded through BOTH serving planes:

- `engine.SLOEngine` — declarative SLO accounting (availability +
  latency, per tenant) evaluated in-process from the existing request
  counters into multi-window multi-burn-rate gauges, alert flags, and a
  ``/healthz`` verdict. Shipped Prometheus alert rules live under
  ``configs/alerts/``.
- `flightrec.FlightRecorder` — a bounded in-memory ring of recent
  request summaries + spans, dumped atomically (tmp+rename) to
  ``runs/flightrec-*.json`` when an anomaly trips (burn-rate alert,
  engine respawn, 5xx/504 spike, breaker open) and on SIGTERM/fatal —
  the post-mortem evidence that survives the incident.
- `ledger.CostLedger` — per-compiled-entry cumulative device-time /
  dispatch / row accounting persisted across runs, keyed by
  entry + model fingerprint so a regrid or promotion never
  cross-pollutes entries: the measured cost model ROADMAP item 2's
  autotuner consumes.

Everything here follows the faultline discipline: disarmed, every hot
path pays one ``is None`` check (bench key ``slo_overhead_pct``).
"""

from mlops_tpu.slo.engine import (  # noqa: F401
    ENGINE_ALERTS,
    SLO_NAMES,
    SLOEngine,
    health_verdict,
    render_slo_lines,
)
from mlops_tpu.slo.flightrec import FlightRecorder  # noqa: F401
from mlops_tpu.slo.ledger import CostLedger, ledger_report  # noqa: F401
