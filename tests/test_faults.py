"""faultline (mlops_tpu/faults): determinism, modes, arming, and the
armed-off parity pin.

The subsystem's contract (ISSUE 9):

- seeded schedules are DETERMINISTIC — same seed + scenario -> the
  identical injection trace, on any process;
- disarmed (the product state) it is invisible: bit-identical serving
  responses and zero new lock-order findings;
- mid-write kill faults prove the tmp+rename persistence paths: a
  SIGKILL between write and rename never leaves a torn target file.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

from mlops_tpu import faults

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    faults.disarm()


# ------------------------------------------------------------ determinism
def test_seeded_schedule_is_deterministic():
    """Same seed + same hit sequence -> the IDENTICAL injection trace."""
    rules = [
        {"point": "serve.*", "mode": "raise", "probability": 0.3, "seed": 7},
        {"point": "cache.read", "mode": "corrupt", "seed": 7},
    ]
    traces = []
    for _ in range(2):
        plan = faults.FaultPlan.from_rules(rules, seed=7)
        faults.arm(plan)
        for i in range(100):
            with contextlib.suppress(faults.FaultInjected):
                faults.fire("serve.engine.dispatch")
        faults.corrupt("cache.read", b"payload-bytes")
        faults.disarm()
        traces.append(plan.trace())
    assert traces[0] == traces[1]
    assert any(point == "cache.read" for point, *_ in traces[0])
    fired = [t for t in traces[0] if t[0] == "serve.engine.dispatch"]
    # Bernoulli(0.3) over 100 hits: some fire, most don't — the schedule
    # is a real subset, not all-or-nothing.
    assert 5 < len(fired) < 70


def test_different_seed_changes_the_schedule():
    def trace_for(seed):
        plan = faults.FaultPlan.from_rules(
            [{"point": "p", "mode": "delay", "probability": 0.5,
              "delay_s": 0.0, "seed": seed}]
        )
        faults.arm(plan)
        for _ in range(64):
            faults.fire("p")
        faults.disarm()
        return [hit for _, hit, _, _ in plan.trace()]

    assert trace_for(1) != trace_for(2)


def test_corruption_is_deterministic_and_bounded():
    data = bytes(range(256)) * 4
    outs = []
    for _ in range(2):
        faults.arm(faults.FaultPlan.from_rules(
            [{"point": "r", "mode": "corrupt", "flip_bits": 4, "seed": 9}]
        ))
        outs.append(faults.corrupt("r", data))
        faults.disarm()
    assert outs[0] == outs[1]
    assert outs[0] != data
    flipped = sum(a != b for a, b in zip(outs[0], data))
    assert 1 <= flipped <= 4  # <=: two flips may land in one byte


def test_after_and_max_fires_windows():
    plan = faults.FaultPlan.from_rules(
        [{"point": "w", "mode": "raise", "after": 3, "max_fires": 2}]
    )
    faults.arm(plan)
    outcomes = []
    for _ in range(10):
        try:
            faults.fire("w")
            outcomes.append("ok")
        except faults.FaultInjected:
            outcomes.append("boom")
    assert outcomes == ["ok"] * 3 + ["boom"] * 2 + ["ok"] * 5


def test_plan_rejects_bad_rules():
    with pytest.raises(ValueError, match="mode"):
        faults.FaultRule(point="p", mode="explode")
    with pytest.raises(ValueError, match="probability"):
        faults.FaultRule(point="p", mode="raise", probability=2.0)
    with pytest.raises(ValueError, match="exc"):
        faults.FaultRule(point="p", mode="raise", exc="SystemExit")


def test_toml_plan_and_env_arming(tmp_path):
    """The chaos-smoke arming path: a TOML plan file named by
    MLOPS_TPU_FAULTS arms every process that imports the package."""
    plan_path = tmp_path / "chaos.toml"
    plan_path.write_text(
        'seed = 11\n'
        '[[fault]]\npoint = "x.y"\nmode = "raise"\nexc = "OSError"\n'
        'message = "injected-io"\n'
    )
    plan = faults.load_plan(plan_path)
    assert plan.seed == 11 and plan.rules[0].exc == "OSError"
    probe = subprocess.run(
        [sys.executable, "-c", textwrap.dedent("""
            from mlops_tpu import faults
            assert faults.armed(), "env plan did not arm at import"
            try:
                faults.fire("x.y")
                raise SystemExit("fault did not fire")
            except OSError as err:
                assert "injected-io" in str(err)
            print("ENV-ARMED-OK")
        """)],
        env={**os.environ, "MLOPS_TPU_FAULTS": str(plan_path),
             "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert "ENV-ARMED-OK" in probe.stdout, probe.stderr[-2000:]


def test_every_documented_point_is_compiled_in():
    """faults.POINTS is the operator contract: every documented injection
    point must appear as a fire()/corrupt() call site in the package."""
    import mlops_tpu

    root = Path(mlops_tpu.__file__).parent
    source = "\n".join(
        p.read_text()
        for p in root.rglob("*.py")
        if "__pycache__" not in p.parts
    )
    for point in faults.POINTS:
        assert f'"{point}"' in source, f"{point} has no call site"


# -------------------------------------------------------- armed-off parity
def test_armed_off_is_invisible_to_serving(warm_engine, sample_request):
    """The parity pin: responses are bit-identical across (never armed),
    (armed with a zero-match plan), and (armed then disarmed) — the
    subsystem's disarmed hot path cannot perturb serving."""
    records = sample_request * 3
    baseline = warm_engine.predict_records(records)
    faults.arm(faults.FaultPlan.from_rules(
        [{"point": "no.such.point", "mode": "raise"}]
    ))
    armed_noop = warm_engine.predict_records(records)
    faults.disarm()
    disarmed = warm_engine.predict_records(records)
    assert armed_noop == baseline
    assert disarmed == baseline


def test_faults_module_adds_no_concurrency_findings():
    """Zero new lock-order findings with the subsystem in the tree: the
    injection points introduce no locks into serving paths (the plan's
    one leaf lock is declared and clean)."""
    from mlops_tpu.analysis import analyze_concurrency_paths

    findings = analyze_concurrency_paths(
        [REPO / "mlops_tpu" / "faults", REPO / "mlops_tpu" / "serve"]
    )
    assert findings == [], [f.format() for f in findings]


# ------------------------------------------------------- mid-write kills
_RESERVOIR_KILL = """
import numpy as np
from mlops_tpu import faults
from mlops_tpu.lifecycle.retrain import SampleReservoir
from mlops_tpu.schema import SCHEMA

faults.arm(faults.FaultPlan.from_rules(
    [{"point": "lifecycle.reservoir.midwrite", "mode": "kill"}]
))
res = SampleReservoir(16, r"%s")
res.add_batch(
    np.ones((4, SCHEMA.num_categorical), np.int32),
    np.ones((4, SCHEMA.num_numeric), np.float32),
)
res.save()  # killed between write and rename
raise SystemExit("unreachable: the kill fault did not fire")
"""


def test_reservoir_midwrite_kill_never_leaves_a_torn_snapshot(tmp_path):
    """SIGKILL between the reservoir's tmp write and its rename: the
    snapshot path must simply not exist (first save) — and a restart
    must load cleanly from nothing."""
    state = tmp_path / "state"
    proc = subprocess.run(
        [sys.executable, "-c", _RESERVOIR_KILL % state],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    from mlops_tpu.lifecycle.retrain import SampleReservoir

    assert not (state / "reservoir.npz").exists()
    fresh = SampleReservoir(16, state)
    assert fresh.load() is False  # a torn tmp is never trusted
    assert fresh.rows == 0


_ATOMIC_KILL = """
from mlops_tpu import faults
from mlops_tpu.utils.io import atomic_write

target = r"%s"
atomic_write(target, b"GOOD" * 1024)  # intact prior generation
faults.arm(faults.FaultPlan.from_rules(
    [{"point": "io.atomic_write.midwrite", "mode": "kill"}]
))
atomic_write(target, b"TORN" * 4096)  # killed before the rename
raise SystemExit("unreachable: the kill fault did not fire")
"""


def test_atomic_write_midwrite_kill_keeps_the_prior_generation(tmp_path):
    """SIGKILL between atomic_write's write and rename (the checkpoint /
    registry discipline): the target keeps the PREVIOUS intact payload —
    never a torn mix, never the partial new one."""
    target = tmp_path / "ckpt.msgpack"
    proc = subprocess.run(
        [sys.executable, "-c", _ATOMIC_KILL % target],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    assert target.read_bytes() == b"GOOD" * 1024


def test_cache_corrupt_on_read_discards_and_recompiles(tmp_path):
    """Bit-corrupt-on-read at compilecache.read: the checksum gate turns
    seeded corruption into a counted discard + recompile — never a
    served garbled program, and the store self-heals (the recompile
    persists a fresh artifact)."""
    import jax
    import jax.numpy as jnp

    from mlops_tpu.compilecache.cache import CacheJob, CompileCache

    if not __import__("mlops_tpu.compilecache.cache", fromlist=["x"]) \
            .serialization_available():
        pytest.skip("no executable serialization on this jaxlib")

    def f(x):
        return x * 2.0 + 1.0

    job = CacheJob(
        entry_id="faults-test",
        jitted=jax.jit(f),
        abstract_args=(jax.ShapeDtypeStruct((8,), jnp.float32),),
    )
    cache = CompileCache(tmp_path)
    cache.load_or_compile(job)  # miss -> compile -> persist
    assert cache.stats()["misses"] == 1

    faults.arm(faults.FaultPlan.from_rules(
        [{"point": "compilecache.read", "mode": "corrupt", "flip_bits": 8}]
    ))
    try:
        cache2 = CompileCache(tmp_path)
        fn = cache2.load_or_compile(job)
    finally:
        faults.disarm()
    stats = cache2.stats()
    assert stats["discards"] == 1 and stats["misses"] == 1
    np.testing.assert_allclose(
        np.asarray(fn(jnp.arange(8, dtype=jnp.float32))),
        np.arange(8, dtype=np.float32) * 2.0 + 1.0,
    )
    # Self-healed: a third process (no corruption) hits clean.
    cache3 = CompileCache(tmp_path)
    cache3.load_or_compile(job)
    assert cache3.stats()["hits"] == 1


@pytest.mark.slow
def test_cache_persist_midwrite_kill_never_leaves_a_partial_artifact(
    tmp_path,
):
    """SIGKILL between the cache artifact's tmp write and its rename: no
    artifact lands, and the NEXT process compiles + persists cleanly —
    the tmp+rename discipline proven, not trusted."""
    script = textwrap.dedent("""
        import jax, jax.numpy as jnp, sys
        from mlops_tpu import faults
        from mlops_tpu.compilecache.cache import (
            CacheJob, CompileCache, serialization_available,
        )
        if not serialization_available():
            print("NO-SERIALIZATION"); raise SystemExit(0)
        faults.arm(faults.FaultPlan.from_rules(
            [{"point": "compilecache.persist.midwrite", "mode": "kill"}]
        ))
        cache = CompileCache(sys.argv[1])
        cache.load_or_compile(CacheJob(
            entry_id="kill-test",
            jitted=jax.jit(lambda x: x + 1.0),
            abstract_args=(jax.ShapeDtypeStruct((4,), jnp.float32),),
        ))
        raise SystemExit("unreachable: the kill fault did not fire")
    """)
    proc = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )
    if "NO-SERIALIZATION" in proc.stdout:
        pytest.skip("no executable serialization on this jaxlib")
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    assert list(tmp_path.rglob("*.jaxexe")) == []  # nothing torn landed

    import jax
    import jax.numpy as jnp

    from mlops_tpu.compilecache.cache import CacheJob, CompileCache

    cache = CompileCache(tmp_path)
    cache.load_or_compile(CacheJob(
        entry_id="kill-test",
        jitted=jax.jit(lambda x: x + 1.0),
        abstract_args=(jax.ShapeDtypeStruct((4,), jnp.float32),),
    ))
    stats = cache.stats()
    assert stats["misses"] == 1 and stats["discards"] == 0


# ------------------------------------------------ ring-plane dead work
def test_ring_expired_descriptor_completes_without_dispatch():
    """The engine side of deadline budgets on the shm ring: a descriptor
    whose slot deadline already passed is completed RESP_EXPIRED without
    the engine dispatching it, and the engine-side expiry counter
    moves."""
    import time

    from mlops_tpu.schema import SCHEMA
    from mlops_tpu.serve.ipc import RequestRing, RingClient, RingService
    from mlops_tpu.serve.metrics import ROB_EXPIRED_ENGINE
    from mlops_tpu.serve.wire import RESP_EXPIRED

    class NeverDispatch:
        supports_grouping = True
        monitor_accumulating = False

        def dispatch_arrays(self, cat, num):
            raise AssertionError("expired descriptor must not dispatch")

        dispatch_group_arrays = dispatch_arrays

    async def scenario():
        import asyncio

        ring = RequestRing(workers=1, slots_small=2, slots_large=1,
                           large_rows=8)
        service = RingService(NeverDispatch(), ring, monitor_fetch_every_s=0)
        try:
            client = RingClient(ring, 0)
            loop = asyncio.get_running_loop()
            loop.add_reader(
                ring.worker_doorbells[0].fileno(), client.on_doorbell
            )
            slot = client.claim(1)
            cat = np.zeros((1, SCHEMA.num_categorical), np.int32)
            num = np.zeros((1, SCHEMA.num_numeric), np.float32)
            future = client.submit(
                slot, cat, num, deadline=time.monotonic() - 0.5
            )
            service.start()
            status = await asyncio.wait_for(future, timeout=10)
            assert status == RESP_EXPIRED
            assert int(ring.rob_vals[0, ROB_EXPIRED_ENGINE]) == 1
            client.release(slot)
            loop.remove_reader(ring.worker_doorbells[0].fileno())
        finally:
            service.stop()
            ring.close()

    import asyncio

    asyncio.run(scenario())


def test_multiple_rules_on_one_point_compose():
    """A declined first rule (max_fires exhausted) must not shadow a
    later rule on the same point — 'stall N times, then escalate' plans
    compose, with each rule scheduling on its own counters."""
    plan = faults.FaultPlan.from_rules([
        {"point": "p", "mode": "raise", "exc": "ValueError",
         "max_fires": 2},
        {"point": "p", "mode": "raise", "exc": "OSError"},
    ])
    faults.arm(plan)
    kinds = []
    for _ in range(5):
        try:
            faults.fire("p")
            kinds.append("ok")
        except ValueError:
            kinds.append("first")
        except OSError:
            kinds.append("second")
    faults.disarm()
    assert kinds == ["first", "first", "second", "second", "second"]


def test_mode_mismatch_neither_fires_nor_burns_budget():
    """A raise-mode rule on a corrupt() point (and vice versa) is a plan
    misconfiguration that must test NOTHING rather than lie: no action,
    no trace entry, no max_fires burned."""
    plan = faults.FaultPlan.from_rules(
        [{"point": "read", "mode": "raise", "max_fires": 1}]
    )
    faults.arm(plan)
    out = faults.corrupt("read", b"payload")
    faults.disarm()
    assert out == b"payload"
    assert plan.fires() == 0 and plan.trace() == []

    plan2 = faults.FaultPlan.from_rules(
        [{"point": "p", "mode": "corrupt"}]
    )
    faults.arm(plan2)
    faults.fire("p")  # must not raise/delay/kill and must not count
    faults.disarm()
    assert plan2.fires() == 0 and plan2.trace() == []
