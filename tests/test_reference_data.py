"""Golden cross-check on the reference's REAL inference rows.

The reference ships 81 UCI-derived applicant rows
(`/root/reference/databricks/data/inference.csv`) used for ad-hoc testing
of its deployed endpoint. Everything else in this suite runs on the repo's
own synthetic generator, so this file is the proof that the schema,
categorical vocabularies, encoder, and serving path are compatible with
the reference's actual data — vocab mismatches fail loudly here instead
of silently scoring OOV garbage in production.
"""

from pathlib import Path

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.data.ingest import load_csv_columns
from mlops_tpu.schema import SCHEMA, FEATURE_NAMES, LoanApplicant
from mlops_tpu.serve import InferenceEngine

REFERENCE_CSV = Path("/root/reference/databricks/data/inference.csv")

pytestmark = pytest.mark.skipif(
    not REFERENCE_CSV.exists(), reason="reference mount not available"
)


@pytest.fixture(scope="module")
def reference_columns():
    columns, labels = load_csv_columns(REFERENCE_CSV, require_target=False)
    assert labels is None
    return columns


def test_reference_rows_load_and_cover_vocab(reference_columns):
    """All 81 rows parse; every categorical value is IN VOCAB (OOV on the
    reference's own data would mean the schema diverged from the task)."""
    n = len(next(iter(reference_columns.values())))
    assert n == 81  # 81 data rows (the file has no trailing newline)
    for feat in SCHEMA.categorical:
        values = set(reference_columns[feat.name])
        unknown = values - set(feat.vocab)
        assert not unknown, (
            f"reference data contains {feat.name} values outside the "
            f"schema vocabulary: {sorted(unknown)}"
        )
    for feat in SCHEMA.numeric:
        raw = np.asarray(reference_columns[feat.name], np.float32)
        assert np.isfinite(raw).all(), f"non-numeric cells in {feat.name}"


def test_reference_rows_validate_as_requests(reference_columns):
    """Row dicts pass the pydantic wire contract (`app/model.py:8-34`)."""
    n = len(next(iter(reference_columns.values())))
    for i in range(n):
        record = {name: reference_columns[name][i] for name in FEATURE_NAMES}
        LoanApplicant.model_validate(record)


def test_reference_rows_through_serving_path(tiny_pipeline, reference_columns):
    """encode -> engine -> full response contract on all 81 real rows."""
    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    engine = InferenceEngine(bundle, buckets=(1, 128), enable_grouping=False)
    engine.warmup()

    n = len(next(iter(reference_columns.values())))
    records = [
        {name: reference_columns[name][i] for name in FEATURE_NAMES}
        for i in range(n)
    ]
    response = engine.predict_records(records)

    predictions = np.asarray(response["predictions"])
    outliers = np.asarray(response["outliers"])
    assert predictions.shape == (len(records),)
    assert np.isfinite(predictions).all()
    assert ((predictions >= 0.0) & (predictions <= 1.0)).all()
    assert outliers.shape == (len(records),)
    assert set(np.unique(outliers)) <= {0.0, 1.0}
    drift = response["feature_drift_batch"]
    assert set(drift) == set(FEATURE_NAMES) and len(drift) == 23
    for score in drift.values():
        assert 0.0 <= score <= 1.0

    # Real rows are in-distribution-ish for the synthetic trainer, but the
    # contract here is softer: the monitors must not flag EVERYTHING.
    assert outliers.mean() < 1.0


def test_reference_csv_native_encoder_parity(reference_columns):
    """The C++ CSV kernel produces bit-identical encodings on the real file."""
    from mlops_tpu import native
    from mlops_tpu.data import Preprocessor

    if not native.native_available():
        pytest.skip("native encoder unavailable")
    prep = Preprocessor.fit(reference_columns)
    got = native.encode_csv_native(REFERENCE_CSV, prep, require_target=False)
    want = prep.encode(reference_columns)
    np.testing.assert_array_equal(got.cat_ids, want.cat_ids)
    np.testing.assert_allclose(got.numeric, want.numeric, atol=1e-5)
