"""Data pipeline tests: synthesis, ingest round-trip, encoding."""

import numpy as np

from mlops_tpu.data import (
    Preprocessor,
    generate_synthetic,
    load_csv_columns,
    write_csv_columns,
)
from mlops_tpu.schema import NUM_CATEGORICAL, NUM_NUMERIC, SCHEMA


def test_synthetic_shapes_and_signal(synth_small):
    columns, labels = synth_small
    assert set(columns) == set(SCHEMA.feature_names)
    assert len(labels) == 2000
    rate = labels.mean()
    # Default rate should be in a plausible band (UCI is ~22%).
    assert 0.05 < rate < 0.6
    # Signal check: customers with long repayment delays default more.
    delayed = np.array(
        [s.startswith("delay") for s in columns["repayment_status_1"]]
    )
    assert labels[delayed].mean() > labels[~delayed].mean()


def test_synthetic_deterministic():
    c1, l1 = generate_synthetic(100, seed=3)
    c2, l2 = generate_synthetic(100, seed=3)
    assert c1["education"] == c2["education"]
    assert (l1 == l2).all()


def test_csv_round_trip(tmp_path, synth_small):
    columns, labels = synth_small
    path = tmp_path / "data.csv"
    write_csv_columns(path, columns, labels)
    columns2, labels2 = load_csv_columns(path, require_target=True)
    assert (labels2 == labels).all()
    assert columns2["sex"] == columns["sex"]
    np.testing.assert_allclose(
        np.asarray(columns2["bill_amount_3"]),
        np.asarray(columns["bill_amount_3"]),
        rtol=1e-6,
    )


def test_encode_shapes_and_standardization(encoded_small):
    prep, ds = encoded_small
    assert ds.cat_ids.shape == (2000, NUM_CATEGORICAL)
    assert ds.numeric.shape == (2000, NUM_NUMERIC)
    assert ds.cat_ids.dtype == np.int32
    assert ds.numeric.dtype == np.float32
    # Standardized columns: ~zero mean, ~unit std.
    np.testing.assert_allclose(ds.numeric.mean(0), 0.0, atol=1e-2)
    np.testing.assert_allclose(ds.numeric.std(0), 1.0, atol=1e-2)
    # Ids within cardinality.
    for j, feat in enumerate(SCHEMA.categorical):
        assert ds.cat_ids[:, j].max() < feat.card


def test_encode_handles_oov_and_nan(encoded_small):
    prep, _ = encoded_small
    columns = {f.name: ["???"] for f in SCHEMA.categorical}
    columns |= {f.name: [float("nan")] for f in SCHEMA.numeric}
    ds = prep.encode(columns)
    for j, feat in enumerate(SCHEMA.categorical):
        assert ds.cat_ids[0, j] == feat.oov_id
    # NaN -> median -> finite standardized value.
    assert np.isfinite(ds.numeric).all()


def test_preprocessor_save_load(tmp_path, encoded_small):
    prep, _ = encoded_small
    path = tmp_path / "prep.npz"
    prep.save(path)
    prep2 = Preprocessor.load(path)
    np.testing.assert_array_equal(prep.numeric_mean, prep2.numeric_mean)
    np.testing.assert_array_equal(prep.numeric_median, prep2.numeric_median)
    np.testing.assert_array_equal(prep.numeric_std, prep2.numeric_std)
    assert prep2.schema_fingerprint == SCHEMA.fingerprint()


def test_validate_cli_reports_oov_bad_numerics_and_labels(tmp_path, capsys):
    """`validate` streams a CSV and counts schema violations; exit 2 when
    dirty, 0 when clean."""
    import json as _json

    from mlops_tpu.commands import _validate
    from mlops_tpu.config import Config
    from mlops_tpu.data import generate_synthetic, write_csv_columns

    columns, labels = generate_synthetic(200, seed=4)
    columns["sex"] = ["martian"] * 3 + columns["sex"][3:]
    columns["age"] = [float("nan")] * 2 + columns["age"][2:]
    path = tmp_path / "dirty.csv"
    write_csv_columns(path, columns, labels)

    config = Config()
    config.data.train_path = str(path)
    rc = _validate(config)
    report = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 2
    assert report["rows"] == 200
    assert report["oov_categorical"] == {"sex": 3}
    assert report["numeric_imputed"] == {"age": 2}
    assert report["labels"] == "ok"
    assert report["ok"] is False

    # corrupt a label: the pre-flight must surface training's error
    text = path.read_text().splitlines()
    text[10] = text[10].rsplit(",", 1)[0] + ",maybe"
    path.write_text("\n".join(text) + "\n")
    rc = _validate(config)
    report = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 2 and "unparseable" in report["labels"]
