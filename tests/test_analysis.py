"""tpulint: planted-violation fixtures, suppression, trace checks, CLI gate.

The fixture modules under tests/fixtures/tpulint/ are ANALYZED, never
imported: each violation line carries a ``# PLANT: <RULE>`` marker, and the
contract is exact — every planted rule fires at its marked line, and no
rule fires anywhere else.
"""

import re
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from mlops_tpu.analysis import analyze_paths, analyze_source
from mlops_tpu.analysis.astrules import RULES

FIXTURES = Path(__file__).parent / "fixtures" / "tpulint"
_PLANT = re.compile(r"#\s*PLANT:\s*(TPU\d+)")


def _planted(path: Path) -> set[tuple[int, str]]:
    out = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        m = _PLANT.search(line)
        if m:
            out.add((lineno, m.group(1)))
    return out


@pytest.mark.parametrize(
    "name",
    [
        "host_sync",
        "rng_clock",
        "tracer_branch",
        "config_arg",
        "missing_donate",
        "broad_except",
        "mutable_default",
        "serve/uncached_jit",
        "serve/swallowed_exception",
    ],
)
def test_each_planted_violation_fires_at_its_line(name):
    path = FIXTURES / f"{name}.py"
    planted = _planted(path)
    assert planted, f"fixture {name} has no PLANT markers"
    found = {
        (f.line, f.rule)
        for f in analyze_source(path.read_text(), path)
    }
    assert planted <= found, f"missed: {planted - found}"
    # No findings beyond the planted lines — the false-positive contract.
    extra = {(ln, r) for ln, r in found if (ln, r) not in planted}
    assert not extra, f"unexpected findings: {extra}"


def test_every_shipped_rule_is_exercised_by_a_fixture():
    """A rule without a fixture is a rule that can silently stop firing."""
    from mlops_tpu.analysis import (
        ASYNC_RULES,
        CONCURRENCY_RULES,
        CONTRACT_RULES,
    )

    shipped = (
        set(RULES)
        | set(CONCURRENCY_RULES)
        | set(CONTRACT_RULES)
        | set(ASYNC_RULES)
    )
    planted_rules = set()
    for path in FIXTURES.rglob("*.py"):
        planted_rules |= {rule for _, rule in _planted(path)}
    assert planted_rules == shipped, (
        f"fixture-less rules: {shipped - planted_rules}; "
        f"unknown planted: {planted_rules - shipped}"
    )


def test_suppression_comments_silence_findings():
    path = FIXTURES / "suppressed.py"
    findings = analyze_source(path.read_text(), path)
    assert findings == [], [f.format() for f in findings]


def test_clean_fixture_has_no_findings():
    path = FIXTURES / "clean.py"
    findings = analyze_source(path.read_text(), path)
    assert findings == [], [f.format() for f in findings]


def test_suppression_is_rule_specific():
    source = (
        "def f(x=[]):  # tpulint: disable=TPU101\n"
        "    return x\n"
    )
    findings = analyze_source(source, "inline.py")
    assert [f.rule for f in findings] == ["TPU202"]


def test_skip_file_pragma():
    source = "# tpulint: skip-file\ndef f(x=[]):\n    return x\n"
    assert analyze_source(source, "skipped.py") == []


def test_trailing_suppression_does_not_leak_to_next_line():
    """A disable comment trailing code on line N silences only line N; a
    STANDALONE comment line above silences the line below."""
    leaking = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = x.tolist()  # tpulint: disable=TPU101\n"
        "    b = x.tolist()\n"
        "    return a, b\n"
    )
    findings = analyze_source(leaking, "leak.py")
    assert [(f.rule, f.line) for f in findings] == [("TPU101", 5)]
    standalone = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # tpulint: disable=TPU101\n"
        "    return x.tolist()\n"
    )
    assert analyze_source(standalone, "standalone.py") == []


def test_cli_exit_2_on_missing_path(capsys):
    from mlops_tpu.cli import main

    assert main(["analyze", "--no-trace", "definitely/not/a/path.py"]) == 2
    assert "no such path" in capsys.readouterr().out


# ------------------------------------------------------------ Layer 3
CONCURRENCY_FIXTURES = FIXTURES / "concurrency"
# The planted-count contract per rule, pinned exactly: the fixture suite
# is the regression net for the analyzer's precision in BOTH directions —
# a rule firing fewer times silently went blind, firing more went noisy.
CONCURRENCY_COUNTS = {"TPU401": 4, "TPU402": 2, "TPU403": 6, "TPU404": 2}


def _concurrency_findings(path):
    from mlops_tpu.analysis import analyze_concurrency_source

    src = path.read_text()
    return analyze_source(src, path) + analyze_concurrency_source(src, path)


@pytest.mark.parametrize(
    "name",
    ["lock_order", "guard_inference", "blocking_under_lock", "ring_pairing"],
)
def test_each_planted_concurrency_violation_fires_at_its_line(name):
    path = CONCURRENCY_FIXTURES / f"{name}.py"
    planted = _planted(path)
    assert planted, f"fixture {name} has no PLANT markers"
    found = {(f.line, f.rule) for f in _concurrency_findings(path)}
    assert planted <= found, f"missed: {planted - found}"
    extra = {(ln, r) for ln, r in found if (ln, r) not in planted}
    assert not extra, f"unexpected findings: {extra}"


def test_concurrency_fixture_counts_pinned():
    """Exact per-rule finding counts over the whole fixture dir — and the
    CLI detects all of them through `analyze --concurrency`."""
    from collections import Counter

    from mlops_tpu.cli import main

    counts = Counter()
    for path in sorted(CONCURRENCY_FIXTURES.glob("*.py")):
        counts.update(f.rule for f in _concurrency_findings(path))
    assert dict(counts) == CONCURRENCY_COUNTS

    assert (
        main(
            ["analyze", "--no-trace", "--concurrency",
             str(CONCURRENCY_FIXTURES)]
        )
        == 1
    )


def test_concurrency_rules_respect_suppressions():
    from mlops_tpu.analysis import analyze_concurrency_source

    source = (
        "import threading\n"
        "import numpy as np\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def f(self, h):\n"
        "        with self._lock:\n"
        "            return np.asarray(h)  # tpulint: disable=TPU403\n"
    )
    assert analyze_concurrency_source(source, "inline.py") == []
    kept = analyze_concurrency_source(
        source, "inline.py", keep_suppressed=True
    )
    assert [f.rule for f in kept] == ["TPU403"]


def test_concurrency_layer_requires_flag():
    """Without --concurrency the fixtures raise no TPU40x findings (the
    planted files are Layer-1 clean by construction)."""
    from mlops_tpu.cli import main

    assert (
        main(["analyze", "--no-trace", str(CONCURRENCY_FIXTURES)]) == 0
    )


def test_lockless_class_methods_see_module_locks():
    """A class with no lock attributes of its own still gets walked: its
    methods holding a MODULE-level lock are in scope for TPU403 (regression
    — lock-less classes were skipped entirely, so shared-module-lock misuse
    inside them was invisible)."""
    from mlops_tpu.analysis import analyze_concurrency_source

    source = (
        "import threading\n"
        "import numpy as np\n"
        "_LOCK = threading.Lock()\n"
        "class NoLocks:\n"
        "    def f(self, h):\n"
        "        with _LOCK:\n"
        "            return np.asarray(h)\n"
    )
    findings = analyze_concurrency_source(source, "inline.py")
    assert [f.rule for f in findings] == ["TPU403"]


def test_annotated_manifest_is_read():
    """`TPULINT_LOCK_ORDER: dict = {...}` (an AnnAssign) must work like the
    bare assignment — regression: the annotated form was silently dropped,
    downgrading the scope to cycles-only while the runtime sanitizer still
    imported the manifest (the exact static/dynamic divergence the shared
    declaration exists to prevent)."""
    from mlops_tpu.analysis import analyze_concurrency_source

    source = (
        "import threading\n"
        'TPULINT_LOCK_ORDER: dict = {"C": ("_a", "_b")}\n'
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def inverted(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    findings = analyze_concurrency_source(source, "inline.py")
    assert [f.rule for f in findings] == ["TPU401"]


# ------------------------------------------------------------ Layer 4
CONTRACT_FIXTURES = FIXTURES / "contracts"
# Exact planted counts per contract rule — the precision net in both
# directions, same contract as CONCURRENCY_COUNTS above.
CONTRACT_COUNTS = {"TPU501": 5, "TPU502": 3, "TPU503": 1, "TPU504": 2}


@pytest.mark.parametrize(
    "name",
    ["shm_ownership", "series_parity", "dead_knob", "fault_points"],
)
def test_each_planted_contract_violation_fires_at_its_line(name):
    from mlops_tpu.analysis import analyze_contracts_source

    path = CONTRACT_FIXTURES / f"{name}.py"
    planted = _planted(path)
    assert planted, f"fixture {name} has no PLANT markers"
    found = {
        (f.line, f.rule)
        for f in analyze_contracts_source(path.read_text(), path)
    }
    assert planted <= found, f"missed: {planted - found}"
    extra = {(ln, r) for ln, r in found if (ln, r) not in planted}
    assert not extra, f"unexpected findings: {extra}"


def test_contract_fixture_counts_pinned():
    """Exact per-rule counts over the contracts dir analyzed as ONE
    project — including the alert-rules yml, whose typo'd series
    reference must land on its planted line — and the CLI detects all of
    them through `analyze --contracts`."""
    from collections import Counter

    from mlops_tpu.analysis import analyze_contracts_paths
    from mlops_tpu.cli import main

    findings = analyze_contracts_paths([CONTRACT_FIXTURES])
    assert dict(Counter(f.rule for f in findings)) == CONTRACT_COUNTS
    planted = {
        (path.as_posix(), lineno, rule)
        for path in sorted(CONTRACT_FIXTURES.iterdir())
        for lineno, rule in _planted(path)
    }
    found = {(f.path, f.line, f.rule) for f in findings}
    assert found == planted
    assert (
        main(["analyze", "--no-trace", "--contracts",
              str(CONTRACT_FIXTURES)])
        == 1
    )


def test_contract_layer_requires_flag():
    """Without --contracts the fixtures raise no TPU50x findings (the
    planted files are Layer-1 clean by construction)."""
    from mlops_tpu.cli import main

    assert main(["analyze", "--no-trace", str(CONTRACT_FIXTURES)]) == 0


def test_contract_rules_respect_suppressions():
    from mlops_tpu.analysis import analyze_contracts_source

    source = (
        'POINTS = {"a.b": "x"}\n'
        "def f():\n"
        '    fire("a.c")  # tpulint: disable=TPU504\n'
        '    return fire("a.b")\n'
    )
    assert analyze_contracts_source(source, "inline.py") == []
    kept = analyze_contracts_source(source, "inline.py", keep_suppressed=True)
    assert [f.rule for f in kept] == ["TPU504"]


def test_deleting_a_series_from_one_plane_fails_parity():
    """The acceptance scenario: drop one series from one renderer plane
    and TPU502 gates. Extraction is pinned by the fixtures; this pins the
    parity check against the REAL registry built from the shipped
    package."""
    from mlops_tpu.analysis.contracts import _check_series
    from mlops_tpu.analysis.seriesreg import registry_from_paths

    package = Path(__file__).parents[1] / "mlops_tpu"
    registry = registry_from_paths([package])
    assert registry is not None
    info = registry.series["mlops_tpu_requests_total"]
    assert info.planes == {"single", "ring"}
    info.planes.discard("ring")
    findings = _check_series(
        [], registry, alert_files=[], docs_file=None, extra_sources={}
    )
    assert any(
        f.rule == "TPU502" and "mlops_tpu_requests_total" in f.message
        for f in findings
    )


def test_renamed_alert_series_fails_gate(tmp_path):
    """The other acceptance scenario: rename one series in the alert
    rules and the reference-integrity check gates against the real
    registry."""
    from mlops_tpu.analysis.contracts import _check_series
    from mlops_tpu.analysis.seriesreg import registry_from_paths

    root = Path(__file__).parents[1]
    registry = registry_from_paths([root / "mlops_tpu"])
    rules = root / "configs" / "alerts" / "mlops_tpu_slo.rules.yml"
    bad = tmp_path / "rules.yml"
    bad.write_text(
        rules.read_text().replace(
            "mlops_tpu_alert_active", "mlops_tpu_alert_actve"
        )
    )
    findings = _check_series(
        [], registry, alert_files=[bad], docs_file=None, extra_sources={}
    )
    assert findings and all(f.rule == "TPU502" for f in findings)
    assert all("mlops_tpu_alert_actve" in f.message for f in findings)
    # The committed rules file itself is clean against the registry.
    assert (
        _check_series(
            [], registry, alert_files=[rules], docs_file=None,
            extra_sources={},
        )
        == []
    )


def test_contract_suppressions_count_in_ledger(tmp_path, capsys):
    """A disable covering a Layer-4 finding is LIVE in the ledger even
    though Layer 4 is cross-file: audit_paths computes the contract
    findings project-wide and slices them per file."""
    from mlops_tpu.cli import main

    mod = tmp_path / "faulty.py"
    mod.write_text(
        'POINTS = {"a.b": "x"}  # tpulint: disable=TPU504\n'
        "def f():\n"
        "    return 1\n"
    )
    assert main(["analyze", "--list-suppressions", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "faulty.py:1: disable=TPU504 [live]" in out


def test_repo_contract_gate_clean_at_head():
    """`analyze --contracts` over the shipped package exits clean: the
    shm ownership map, both metrics planes, the committed alert rules,
    the docs series table, every config knob and every fault point hold
    at HEAD."""
    from mlops_tpu.cli import main

    package = Path(__file__).parents[1] / "mlops_tpu"
    assert (
        main(["analyze", "--no-trace", "--contracts", str(package)]) == 0
    )


# ------------------------------------------------------------ Layer 5
ASYNC_FIXTURES = FIXTURES / "asyncio"
# Exact planted counts per async-discipline rule — the precision net in
# both directions, same contract as the Layer 3/4 count pins above.
ASYNC_COUNTS = {"TPU601": 9, "TPU602": 3, "TPU603": 2, "TPU604": 2}


@pytest.mark.parametrize(
    "name",
    [
        "blocking_in_coroutine",
        "fire_and_forget",
        "cross_thread_write",
        "await_under_lock",
    ],
)
def test_each_planted_async_violation_fires_at_its_line(name):
    from mlops_tpu.analysis import analyze_async_source

    path = ASYNC_FIXTURES / f"{name}.py"
    planted = _planted(path)
    assert planted, f"fixture {name} has no PLANT markers"
    found = {
        (f.line, f.rule)
        for f in analyze_async_source(path.read_text(), path)
    }
    assert planted <= found, f"missed: {planted - found}"
    extra = {(ln, r) for ln, r in found if (ln, r) not in planted}
    assert not extra, f"unexpected findings: {extra}"


def test_async_fixture_counts_pinned():
    """Exact per-rule counts over the asyncio dir analyzed as ONE project
    (cross-file confinement must not add or lose findings versus the
    per-file runs) — and the CLI detects all of them through
    `analyze --async`."""
    from collections import Counter

    from mlops_tpu.analysis import analyze_async_paths
    from mlops_tpu.cli import main

    findings = analyze_async_paths([ASYNC_FIXTURES])
    assert dict(Counter(f.rule for f in findings)) == ASYNC_COUNTS
    planted = {
        (path.as_posix(), lineno, rule)
        for path in sorted(ASYNC_FIXTURES.iterdir())
        for lineno, rule in _planted(path)
    }
    found = {(f.path, f.line, f.rule) for f in findings}
    assert found == planted
    assert (
        main(["analyze", "--no-trace", "--async", str(ASYNC_FIXTURES)])
        == 1
    )


def test_async_layer_requires_flag():
    """Without --async the fixtures raise no TPU60x findings (the planted
    files are Layer-1 clean by construction)."""
    from mlops_tpu.cli import main

    assert main(["analyze", "--no-trace", str(ASYNC_FIXTURES)]) == 0


def test_async_rules_respect_suppressions():
    from mlops_tpu.analysis import analyze_async_source

    source = (
        "import time\n"
        "async def tick():\n"
        "    time.sleep(0.1)  # tpulint: disable=TPU601\n"
    )
    assert analyze_async_source(source, "inline.py") == []
    kept = analyze_async_source(source, "inline.py", keep_suppressed=True)
    assert [f.rule for f in kept] == ["TPU601"]


def test_async_suppressions_count_in_ledger(tmp_path, capsys):
    """A disable covering a Layer-5 finding is LIVE in the ledger even
    though Layer 5 is cross-file: audit_paths computes the async findings
    project-wide and slices them per file, exactly like Layer 4's."""
    from mlops_tpu.cli import main

    mod = tmp_path / "looped.py"
    mod.write_text(
        "import time\n"
        "async def tick():\n"
        "    time.sleep(0.1)  # tpulint: disable=TPU601\n"
    )
    assert main(["analyze", "--list-suppressions", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "looped.py:3: disable=TPU601 [live]" in out


def test_repo_async_gate_clean_at_head():
    """`analyze --async` over the shipped package exits clean: the serve
    plane's executor-offload discipline (every blocking call rides
    run_in_executor, no fire-and-forget tasks, no unmarshalled
    cross-thread writes, no await under a sync mutex) holds at HEAD."""
    from mlops_tpu.analysis import analyze_async_paths
    from mlops_tpu.cli import main

    package = Path(__file__).parents[1] / "mlops_tpu"
    assert analyze_async_paths([package]) == []
    assert main(["analyze", "--no-trace", "--async", str(package)]) == 0


def test_removing_executor_offload_yields_one_tpu601():
    """The mutation scenario: strip ONE executor offload from the serve
    plane in memory (the monitor fetch — the exact /metrics-wedging bug
    class Layer 5 exists for) and the gate must produce exactly one
    TPU601 at the de-offloaded call."""
    import re as _re

    from mlops_tpu.analysis import analyze_async_source

    server_py = (
        Path(__file__).parents[1] / "mlops_tpu" / "serve" / "server.py"
    )
    source = server_py.read_text()
    assert analyze_async_source(source, server_py) == []
    pattern = (
        r"await loop\.run_in_executor\(\s*"
        r"self\._executor, eng\.monitor_snapshot\s*\)"
    )
    mutated, n = _re.subn(
        pattern,
        "jax.device_get(eng.monitor_snapshot())",
        source,
    )
    assert n == 1, "the monitor-fetch offload moved; update the pattern"
    findings = analyze_async_source(mutated, server_py)
    assert [f.rule for f in findings] == ["TPU601"]
    assert "jax.device_get()" in findings[0].message


# ------------------------------------------- suppression ledger (TPU400)
def test_list_suppressions_reports_live_and_stale(tmp_path, capsys):
    from mlops_tpu.cli import main

    live = tmp_path / "live.py"
    live.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.tolist()  # tpulint: disable=TPU101\n"
    )
    stale = tmp_path / "stale.py"
    stale.write_text(
        "def g(x):\n"
        "    return x  # tpulint: disable=TPU101\n"
    )
    assert main(["analyze", "--list-suppressions", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "live.py:4: disable=TPU101 [live]" in out
    assert "stale.py:2: disable=TPU101 [STALE]" in out
    assert "2 suppression(s), 1 stale" in out
    # --fail-stale flips the exit code in list mode...
    assert (
        main(["analyze", "--list-suppressions", "--fail-stale",
              str(tmp_path)])
        == 1
    )
    capsys.readouterr()
    # ...and in gate mode the stale comment is a TPU400 finding that a
    # disable comment can NOT silence (it must not hide its own report).
    assert main(["analyze", "--no-trace", "--fail-stale", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "TPU400" in out and "stale.py:2" in out


def test_suppression_examples_in_docstrings_are_not_counted(tmp_path, capsys):
    """The audit reads real COMMENT tokens (tokenize): the disable syntax
    quoted inside a docstring is documentation, not a suppression."""
    from mlops_tpu.cli import main

    doc = tmp_path / "doc.py"
    doc.write_text(
        '"""Suppress with ``# tpulint: disable=TPU101`` on the line."""\n'
        "X = 1\n"
    )
    assert main(["analyze", "--list-suppressions", str(tmp_path)]) == 0
    assert "0 suppression(s), 0 stale" in capsys.readouterr().out


def test_untokenizable_file_does_not_crash_the_audit(tmp_path, capsys):
    """A file tokenize rejects (unterminated triple-quote, bad dedent) must
    degrade to 'nothing to audit' — Layer 1 owns the syntax-error report.
    Regression: the except clause once named the nonexistent
    ``tokenize.TokenizeError``, so any such file killed the whole
    ``--fail-stale`` gate with an AttributeError (exit 2)."""
    from mlops_tpu.cli import main

    bad = tmp_path / "bad.py"
    bad.write_text("x = '''unterminated\n")
    dedent = tmp_path / "dedent.py"
    dedent.write_text("def f():\n        x = 1\n    return x\n")
    assert main(["analyze", "--list-suppressions", str(tmp_path)]) == 0
    assert "0 suppression(s), 0 stale" in capsys.readouterr().out
    # Gate mode still reports the syntax errors (Layer 1 TPU000), exit 1
    # not an internal-failure exit 2.
    assert main(["analyze", "--no-trace", "--fail-stale", str(tmp_path)]) == 1
    assert "TPU000" in capsys.readouterr().out


def test_package_suppressions_all_live():
    """The PR 1/3/4 disables stay honest: every suppression in the shipped
    package still suppresses a real finding (the CI --fail-stale gate)."""
    from mlops_tpu.analysis.suppressions import audit_paths

    package = Path(__file__).parents[1] / "mlops_tpu"
    stale = [
        s.describe()
        for s in audit_paths([package])
        if not s.live and not s.skipped_file
    ]
    assert stale == []


# ------------------------------------------------- runtime lock sanitizer
def test_lockcheck_detects_declared_order_inversion():
    import threading

    from mlops_tpu.analysis.lockcheck import LockSanitizer

    san = LockSanitizer(order=("a", "b"))
    a = san.wrap(threading.Lock(), "a")
    b = san.wrap(threading.Lock(), "b")
    with a:
        with b:
            pass
    assert san.violations == []
    with b:
        with a:
            pass
    assert len(san.violations) == 1
    v = san.violations[0]
    assert (v.acquiring, v.holding) == ("a", ("b",))
    assert "inverts the declared order" in str(v)


def test_lockcheck_flags_undeclared_lock_in_nesting():
    import threading

    from mlops_tpu.analysis.lockcheck import LockSanitizer

    san = LockSanitizer(order=("a",))
    a = san.wrap(threading.Lock(), "a")
    rogue = san.wrap(threading.Lock(), "rogue")
    with a:
        with rogue:
            pass
    assert len(san.violations) == 1
    assert "not in the declared order" in san.violations[0].note


def test_lockcheck_accounts_contended_wait():
    import threading

    from mlops_tpu.analysis.lockcheck import LockSanitizer

    san = LockSanitizer()
    lock = san.wrap(threading.Lock(), "l")
    started = threading.Event()

    def holder():
        with lock:
            started.set()
            import time

            time.sleep(0.05)

    t = threading.Thread(target=holder)
    t.start()
    started.wait()
    with lock:
        pass
    t.join()
    assert san.total_wait_s >= 0.02
    assert san.acquired["l"] == 2
    assert san.violations == []


def test_lockcheck_cross_thread_semaphore_release():
    """A permit acquired on one thread and released on another (the
    two-phase dispatch/fetch handoff) must be popped from the ACQUIRER's
    held stack — regression: the stale entry manufactured bogus order
    violations on every later acquisition and grew the stack forever."""
    import threading

    from mlops_tpu.analysis.lockcheck import LockSanitizer

    san = LockSanitizer(order=("lock", "sem"))
    sem = san.wrap(threading.Semaphore(2), "sem")
    lock = san.wrap(threading.Lock(), "lock")
    sem.acquire()
    t = threading.Thread(target=sem.release)
    t.start()
    t.join()
    with lock:  # must NOT report "lock after sem" — sem was handed back
        pass
    assert san.violations == [], [str(v) for v in san.violations]
    assert san._stacks[threading.get_ident()] == []


def test_instrument_locks_skips_asyncio_primitives():
    """asyncio locks/semaphores duck-type acquire/release but acquire() is
    a coroutine — a sync wrapper would return it un-awaited (truthy!) and
    the permit count would never move, silently unbounding the batcher's
    rings. They must not be swapped."""
    import asyncio
    import threading

    from mlops_tpu.analysis.lockcheck import (
        InstrumentedLock,
        instrument_locks,
    )

    class Mixed:
        def __init__(self):
            self._ring = asyncio.Semaphore(2)
            self._mutex = threading.Lock()

    obj = Mixed()
    ring = obj._ring
    with instrument_locks(obj):
        assert obj._ring is ring  # untouched
        assert isinstance(obj._mutex, InstrumentedLock)


def test_instrument_locks_swaps_and_restores(warm_engine):
    import threading

    from mlops_tpu.analysis.lockcheck import (
        InstrumentedLock,
        instrument_locks,
    )

    original = warm_engine._acc_lock
    with instrument_locks(warm_engine) as san:
        assert isinstance(warm_engine._acc_lock, InstrumentedLock)
        assert isinstance(warm_engine._compile_lock, InstrumentedLock)
        warm_engine.monitor_snapshot()
        assert san.acquired.get("_acc_lock", 0) >= 1
        assert san.violations == []
    assert warm_engine._acc_lock is original
    assert isinstance(original, type(threading.Lock()))


# ------------------------------------------- runtime loop-lag sanitizer
def test_loopcheck_times_slow_callback_with_attribution():
    """A coroutine that blocks the loop is timed with its qualname — the
    runtime counterpart of TPU601."""
    import asyncio
    import time

    from mlops_tpu.analysis.loopcheck import instrument_loop

    async def stall():
        time.sleep(0.03)  # deliberate: the bug class under test

    async def main(san_holder):
        loop = asyncio.get_running_loop()
        with instrument_loop(loop, slow_ms=10.0) as san:
            await asyncio.create_task(stall())
            san_holder.append(san)
        # detached: the loop's own scheduling methods are restored
        assert "call_soon" not in vars(loop)

    holder = []
    asyncio.run(main(holder))
    san = holder[0]
    assert san.max_lag_ms >= 25.0
    assert san.callbacks > 0
    slow = [r for r in san.slow if "stall" in r.label]
    assert slow and slow[0].label.startswith("task:")
    assert "held the event loop" in str(slow[0])
    assert slow[0].schedule_site  # capture_stacks defaults on here


def test_loopcheck_assert_max_lag_and_window_reset():
    import asyncio
    import time

    from mlops_tpu.analysis.loopcheck import LoopLagSanitizer

    san = LoopLagSanitizer(slow_ms=10.0)

    async def main():
        loop = asyncio.get_running_loop()
        san.attach(loop)
        try:
            await asyncio.sleep(0)
            time.sleep(0.02)  # rides the coroutine step: seen as lag
            await asyncio.sleep(0)
        finally:
            san.detach()

    asyncio.run(main())
    # Gauge semantics: the first snapshot drains the window's max, a
    # quiet window then reads 0.0 — while the all-time max still gates.
    assert san.snapshot_ms() >= 15.0
    assert san.snapshot_ms() == 0.0
    san.assert_max_lag(1000.0)  # under the bar: no raise
    with pytest.raises(AssertionError) as err:
        san.assert_max_lag(10.0)
    assert "event-loop lag" in str(err.value)
    assert "held the event loop" in str(err.value)


def test_loopcheck_attach_is_exclusive_and_detach_idempotent():
    import asyncio

    from mlops_tpu.analysis.loopcheck import LoopLagSanitizer

    san = LoopLagSanitizer()

    async def main():
        loop = asyncio.get_running_loop()
        san.attach(loop)
        with pytest.raises(RuntimeError):
            san.attach(loop)
        san.detach()
        san.detach()  # no-op, like lockcheck's restore
        assert "call_soon" not in vars(loop)
        assert "call_later" not in vars(loop)

    asyncio.run(main())


def test_loopcheck_seeded_perturbation_is_deterministic():
    """The SchedulePerturber discipline from lockcheck: a seeded
    perturbation shifts the interleaving without changing results —
    the same seed replays the same schedule, and the workload's output
    stays bit-identical to the unperturbed run."""
    import asyncio

    from mlops_tpu.analysis.loopcheck import instrument_loop

    async def workload():
        out = []

        async def step(i):
            await asyncio.sleep(0)
            out.append(i)

        await asyncio.gather(*(step(i) for i in range(8)))
        return out

    def run(seed):
        async def main():
            loop = asyncio.get_running_loop()
            with instrument_loop(
                loop, slow_ms=1000.0, perturb_seed=seed
            ) as san:
                result = await workload()
            return result, san.callbacks

        return asyncio.run(main())

    baseline = asyncio.run(workload())
    r7a, calls7a = run(7)
    r7b, calls7b = run(7)
    assert r7a == r7b == baseline
    assert calls7a == calls7b > 0


# ------------------------------------------------------------ Layer 2
def test_trace_layer_clean_on_registered_entry_points():
    """The acceptance gate: every registered entry point traces abstractly
    (no device execution) and raises no findings on the real framework."""
    from mlops_tpu.analysis.traces import run_trace_checks

    findings, notes = run_trace_checks()
    assert findings == [], [f.format() for f in findings]
    traced = [n for n in notes if n.startswith("traced ")]
    # conftest forces an 8-device mesh, so nothing may be skipped. 9 =
    # dense + TP train steps, exact packed solo/group, quant packed
    # solo/group (ISSUE 17), gbm packed solo/group (ISSUE 19), bulk
    # chunk.
    assert len(traced) == 9, notes
    assert any("serve-predict-quant-packed" in n for n in traced)
    assert any("serve-predict-quant-group-packed" in n for n in traced)
    assert any("serve-predict-gbm-packed" in n for n in traced)
    assert any("serve-predict-gbm-group-packed" in n for n in traced)
    assert all("no device code executed" in n for n in traced)


def test_float64_leak_detected():
    from mlops_tpu.analysis.traces import check_dtypes

    from jax.experimental import enable_x64

    with enable_x64():
        jaxpr = jax.make_jaxpr(lambda x: jnp.sin(x) * 2.0)(
            jax.ShapeDtypeStruct((4,), jnp.float64)
        )
    findings = check_dtypes("fixture", 4, jaxpr)
    assert any(f.rule == "TPU301" for f in findings)


def test_convert_round_trip_detected():
    from mlops_tpu.analysis.traces import check_dtypes

    def roundtrip(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    jaxpr = jax.make_jaxpr(roundtrip)(jax.ShapeDtypeStruct((4,), jnp.float32))
    findings = check_dtypes("fixture", 4, jaxpr)
    assert any(f.rule == "TPU303" for f in findings)


def test_weak_type_output_detected():
    from mlops_tpu.analysis.traces import check_weak_types

    def weak_out(x):
        return x.sum(), jnp.asarray(1.0) * 2.0  # second output weak f32

    jaxpr = jax.make_jaxpr(weak_out)(jax.ShapeDtypeStruct((4,), jnp.float32))
    findings = check_weak_types("fixture", 4, jaxpr)
    assert any(f.rule == "TPU302" for f in findings), [
        (a, getattr(a, "weak_type", None)) for a in jaxpr.out_avals
    ]


def test_bucket_polymorphism_detected_and_families_respected():
    from mlops_tpu.analysis.traces import check_bucket_stability

    def polymorphic(x):
        # Different program per size: the shape branch changes the ops.
        if x.shape[0] <= 4:
            return jnp.sort(x)
        return x * 2.0

    jaxprs = {
        n: jax.make_jaxpr(polymorphic)(jax.ShapeDtypeStruct((n,), jnp.float32))
        for n in (2, 8)
    }
    assert any(
        f.rule == "TPU304" for f in check_bucket_stability("fixture", jaxprs)
    )
    # The same divergence DECLARED as two families passes.
    assert (
        check_bucket_stability("fixture", jaxprs, families=((2,), (8,))) == []
    )


def test_sharding_link_mismatch_detected():
    from jax.sharding import PartitionSpec as P

    from mlops_tpu.analysis.traces import (
        EntryPoint,
        ShardingLink,
        check_sharding_links,
    )

    entries = {
        "producer": EntryPoint(
            name="producer",
            build=lambda: None,
            params_out_spec={"w": P("model", None)},
        ),
        "consumer": EntryPoint(
            name="consumer",
            build=lambda: None,
            params_in_spec={"w": P()},
        ),
    }
    links = [ShardingLink("producer", "consumer")]
    findings = check_sharding_links(entries, links)
    assert [f.rule for f in findings] == ["TPU305"]
    # Matching specs pass.
    entries["consumer"].params_in_spec = {"w": P("model", None)}
    assert check_sharding_links(entries, links) == []


# ------------------------------------------------------------ CLI gate
def test_cli_analyze_nonzero_on_fixtures_and_zero_on_package(capsys):
    from mlops_tpu.cli import main

    assert main(["analyze", "--no-trace", str(FIXTURES)]) == 1
    out = capsys.readouterr().out
    assert "TPU101" in out and "gating" in out

    package = Path(__file__).parents[1] / "mlops_tpu"
    assert main(["analyze", "--no-trace", "--strict", str(package)]) == 0
    # The CI gate shape minus the (slow) trace layer: concurrency rules,
    # the async/event-loop rules, and the stale-suppression audit are
    # clean on the shipped package.
    assert (
        main(
            ["analyze", "--no-trace", "--strict", "--concurrency",
             "--async", "--fail-stale", str(package)]
        )
        == 0
    )


@pytest.mark.slow
def test_cli_analyze_full_gate(capsys):
    """`mlops-tpu analyze --strict --concurrency --contracts --async
    --fail-stale mlops_tpu/` — the exact CI invocation — exits 0 with
    every entry point traced."""
    from mlops_tpu.cli import main

    package = Path(__file__).parents[1] / "mlops_tpu"
    assert (
        main(
            ["analyze", "--strict", "--concurrency", "--contracts",
             "--async", "--fail-stale", str(package)]
        )
        == 0
    )
    out = capsys.readouterr().out
    # One note per registered entry point (analysis/entrypoints.py) —
    # keep in lockstep with the trace-layer test's count above.
    assert out.count("traced ") == 9


def test_rule_catalog_documented():
    """Every rule ID (all five layers + the suppression audit) appears in
    docs/static-analysis.md."""
    from mlops_tpu.analysis import (
        ASYNC_RULES,
        CONCURRENCY_RULES,
        CONTRACT_RULES,
    )
    from mlops_tpu.analysis.suppressions import STALE_RULE
    from mlops_tpu.analysis.traces import TRACE_RULES

    doc = (Path(__file__).parents[1] / "docs" / "static-analysis.md").read_text()
    for rule in [
        *RULES, *CONCURRENCY_RULES, *CONTRACT_RULES, *ASYNC_RULES,
        STALE_RULE, *TRACE_RULES,
    ]:
        assert rule in doc, f"{rule} missing from docs/static-analysis.md"
