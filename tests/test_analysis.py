"""tpulint: planted-violation fixtures, suppression, trace checks, CLI gate.

The fixture modules under tests/fixtures/tpulint/ are ANALYZED, never
imported: each violation line carries a ``# PLANT: <RULE>`` marker, and the
contract is exact — every planted rule fires at its marked line, and no
rule fires anywhere else.
"""

import re
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from mlops_tpu.analysis import analyze_paths, analyze_source
from mlops_tpu.analysis.astrules import RULES

FIXTURES = Path(__file__).parent / "fixtures" / "tpulint"
_PLANT = re.compile(r"#\s*PLANT:\s*(TPU\d+)")


def _planted(path: Path) -> set[tuple[int, str]]:
    out = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        m = _PLANT.search(line)
        if m:
            out.add((lineno, m.group(1)))
    return out


@pytest.mark.parametrize(
    "name",
    [
        "host_sync",
        "rng_clock",
        "tracer_branch",
        "config_arg",
        "missing_donate",
        "broad_except",
        "mutable_default",
        "serve/uncached_jit",
    ],
)
def test_each_planted_violation_fires_at_its_line(name):
    path = FIXTURES / f"{name}.py"
    planted = _planted(path)
    assert planted, f"fixture {name} has no PLANT markers"
    found = {
        (f.line, f.rule)
        for f in analyze_source(path.read_text(), path)
    }
    assert planted <= found, f"missed: {planted - found}"
    # No findings beyond the planted lines — the false-positive contract.
    extra = {(ln, r) for ln, r in found if (ln, r) not in planted}
    assert not extra, f"unexpected findings: {extra}"


def test_every_shipped_rule_is_exercised_by_a_fixture():
    """A rule without a fixture is a rule that can silently stop firing."""
    planted_rules = set()
    for path in FIXTURES.rglob("*.py"):
        planted_rules |= {rule for _, rule in _planted(path)}
    assert planted_rules == set(RULES), (
        f"fixture-less rules: {set(RULES) - planted_rules}; "
        f"unknown planted: {planted_rules - set(RULES)}"
    )


def test_suppression_comments_silence_findings():
    path = FIXTURES / "suppressed.py"
    findings = analyze_source(path.read_text(), path)
    assert findings == [], [f.format() for f in findings]


def test_clean_fixture_has_no_findings():
    path = FIXTURES / "clean.py"
    findings = analyze_source(path.read_text(), path)
    assert findings == [], [f.format() for f in findings]


def test_suppression_is_rule_specific():
    source = (
        "def f(x=[]):  # tpulint: disable=TPU101\n"
        "    return x\n"
    )
    findings = analyze_source(source, "inline.py")
    assert [f.rule for f in findings] == ["TPU202"]


def test_skip_file_pragma():
    source = "# tpulint: skip-file\ndef f(x=[]):\n    return x\n"
    assert analyze_source(source, "skipped.py") == []


def test_trailing_suppression_does_not_leak_to_next_line():
    """A disable comment trailing code on line N silences only line N; a
    STANDALONE comment line above silences the line below."""
    leaking = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = x.tolist()  # tpulint: disable=TPU101\n"
        "    b = x.tolist()\n"
        "    return a, b\n"
    )
    findings = analyze_source(leaking, "leak.py")
    assert [(f.rule, f.line) for f in findings] == [("TPU101", 5)]
    standalone = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # tpulint: disable=TPU101\n"
        "    return x.tolist()\n"
    )
    assert analyze_source(standalone, "standalone.py") == []


def test_cli_exit_2_on_missing_path(capsys):
    from mlops_tpu.cli import main

    assert main(["analyze", "--no-trace", "definitely/not/a/path.py"]) == 2
    assert "no such path" in capsys.readouterr().out


# ------------------------------------------------------------ Layer 2
def test_trace_layer_clean_on_registered_entry_points():
    """The acceptance gate: every registered entry point traces abstractly
    (no device execution) and raises no findings on the real framework."""
    from mlops_tpu.analysis.traces import run_trace_checks

    findings, notes = run_trace_checks()
    assert findings == [], [f.format() for f in findings]
    traced = [n for n in notes if n.startswith("traced ")]
    # conftest forces an 8-device mesh, so nothing may be skipped.
    assert len(traced) == 5, notes
    assert all("no device code executed" in n for n in traced)


def test_float64_leak_detected():
    from mlops_tpu.analysis.traces import check_dtypes

    from jax.experimental import enable_x64

    with enable_x64():
        jaxpr = jax.make_jaxpr(lambda x: jnp.sin(x) * 2.0)(
            jax.ShapeDtypeStruct((4,), jnp.float64)
        )
    findings = check_dtypes("fixture", 4, jaxpr)
    assert any(f.rule == "TPU301" for f in findings)


def test_convert_round_trip_detected():
    from mlops_tpu.analysis.traces import check_dtypes

    def roundtrip(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    jaxpr = jax.make_jaxpr(roundtrip)(jax.ShapeDtypeStruct((4,), jnp.float32))
    findings = check_dtypes("fixture", 4, jaxpr)
    assert any(f.rule == "TPU303" for f in findings)


def test_weak_type_output_detected():
    from mlops_tpu.analysis.traces import check_weak_types

    def weak_out(x):
        return x.sum(), jnp.asarray(1.0) * 2.0  # second output weak f32

    jaxpr = jax.make_jaxpr(weak_out)(jax.ShapeDtypeStruct((4,), jnp.float32))
    findings = check_weak_types("fixture", 4, jaxpr)
    assert any(f.rule == "TPU302" for f in findings), [
        (a, getattr(a, "weak_type", None)) for a in jaxpr.out_avals
    ]


def test_bucket_polymorphism_detected_and_families_respected():
    from mlops_tpu.analysis.traces import check_bucket_stability

    def polymorphic(x):
        # Different program per size: the shape branch changes the ops.
        if x.shape[0] <= 4:
            return jnp.sort(x)
        return x * 2.0

    jaxprs = {
        n: jax.make_jaxpr(polymorphic)(jax.ShapeDtypeStruct((n,), jnp.float32))
        for n in (2, 8)
    }
    assert any(
        f.rule == "TPU304" for f in check_bucket_stability("fixture", jaxprs)
    )
    # The same divergence DECLARED as two families passes.
    assert (
        check_bucket_stability("fixture", jaxprs, families=((2,), (8,))) == []
    )


def test_sharding_link_mismatch_detected():
    from jax.sharding import PartitionSpec as P

    from mlops_tpu.analysis.traces import (
        EntryPoint,
        ShardingLink,
        check_sharding_links,
    )

    entries = {
        "producer": EntryPoint(
            name="producer",
            build=lambda: None,
            params_out_spec={"w": P("model", None)},
        ),
        "consumer": EntryPoint(
            name="consumer",
            build=lambda: None,
            params_in_spec={"w": P()},
        ),
    }
    links = [ShardingLink("producer", "consumer")]
    findings = check_sharding_links(entries, links)
    assert [f.rule for f in findings] == ["TPU305"]
    # Matching specs pass.
    entries["consumer"].params_in_spec = {"w": P("model", None)}
    assert check_sharding_links(entries, links) == []


# ------------------------------------------------------------ CLI gate
def test_cli_analyze_nonzero_on_fixtures_and_zero_on_package(capsys):
    from mlops_tpu.cli import main

    assert main(["analyze", "--no-trace", str(FIXTURES)]) == 1
    out = capsys.readouterr().out
    assert "TPU101" in out and "gating" in out

    package = Path(__file__).parents[1] / "mlops_tpu"
    assert main(["analyze", "--no-trace", "--strict", str(package)]) == 0


@pytest.mark.slow
def test_cli_analyze_full_two_layer_gate(capsys):
    """`mlops-tpu analyze --strict mlops_tpu/` — the exact CI invocation —
    exits 0 with every entry point traced."""
    from mlops_tpu.cli import main

    package = Path(__file__).parents[1] / "mlops_tpu"
    assert main(["analyze", "--strict", str(package)]) == 0
    out = capsys.readouterr().out
    # One note per registered entry point (analysis/entrypoints.py) —
    # keep in lockstep with the trace-layer test's count above.
    assert out.count("traced ") == 5


def test_rule_catalog_documented():
    """Every rule ID (both layers) appears in docs/static-analysis.md."""
    from mlops_tpu.analysis.traces import TRACE_RULES

    doc = (Path(__file__).parents[1] / "docs" / "static-analysis.md").read_text()
    for rule in [*RULES, *TRACE_RULES]:
        assert rule in doc, f"{rule} missing from docs/static-analysis.md"
