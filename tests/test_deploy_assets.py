"""Deployment-asset validation: the rendered K8s manifests are wellformed
and carry the contracts CI depends on.

The reference has no manifest validation at all (its only infra check is
`az bicep build`, SURVEY.md §4.2); here the serving Deployment and the
remote-training Job are parsed after envsubst-style substitution and
their load-bearing fields asserted, so a manifest typo fails in unit
tests instead of mid-release.
"""

import re
from pathlib import Path

import yaml

REPO = Path(__file__).resolve().parent.parent

SUBSTITUTIONS = {
    "CONTAINER_IMAGE": "registry.example/creditdefaultapi:123",
    "TRAIN_IMAGE": "registry.example/creditdefaulttrain:123",
    "JOB_NAME": "train-register-123",
    "DATA_URI": "gs://bucket/data/curated.csv",
    "REGISTRY_ROOT": "gs://bucket/registry",
    "NUM_HOSTS": "4",
    "TPU_TOPOLOGY": "4x4",
    "ACCELERATOR": "tpu-v5-lite-podslice",
}


def _render(path: Path) -> list[dict]:
    text = path.read_text()
    rendered = re.sub(
        r"\$\{(\w+)\}", lambda m: SUBSTITUTIONS[m.group(1)], text
    )
    assert "${" not in rendered, "unsubstituted variable left in manifest"
    return [d for d in yaml.safe_load_all(rendered) if d]


def test_serving_manifest_contracts():
    docs = _render(REPO / "kubernetes" / "manifest.yml")
    by_kind = {d["kind"]: d for d in docs}
    deploy = by_kind["Deployment"]
    spec = deploy["spec"]["template"]["spec"]
    container = spec["containers"][0]
    # TPU scheduling: pool selectors + chip request must agree (infra/gke.tf).
    assert spec["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
    assert container["resources"]["requests"]["google.com/tpu"] == "1"
    assert container["image"] == SUBSTITUTIONS["CONTAINER_IMAGE"]
    # Probe contract: /healthz/* served by serve/server.py.
    assert container["readinessProbe"]["httpGet"]["path"] == "/healthz/ready"
    assert deploy["spec"]["replicas"] >= 2
    # Service must route to the container port the server binds (5000,
    # reference parity `app/Dockerfile:22-24`).
    service = by_kind["Service"]
    assert service["spec"]["ports"][0]["port"] == 5000
    assert container["ports"][0]["containerPort"] == 5000


def test_train_job_manifest_contracts():
    docs = _render(REPO / "kubernetes" / "train-job.yml")
    (job,) = docs
    assert job["kind"] == "Job"
    spec = job["spec"]["template"]["spec"]
    container = spec["containers"][0]
    assert job["metadata"]["name"] == SUBSTITUTIONS["JOB_NAME"]
    assert spec["restartPolicy"] == "Never"
    assert job["spec"]["backoffLimit"] >= 1
    # Lands on the TPU pool with a chip.
    assert spec["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
    assert container["resources"]["requests"]["google.com/tpu"] == "1"
    assert container["image"] == SUBSTITUTIONS["TRAIN_IMAGE"]
    # The tuner consumes the staged dataset and the gs:// registry — the
    # two contracts the workflow's envsubst provides.
    args = " ".join(container["args"])
    assert "tune" in args
    assert "data.train_path=gs://bucket/data/curated.csv" in args
    assert "registry.root=gs://bucket/registry" in args
    # The config the args reference must exist with the right sections.
    try:
        import tomllib
    except ModuleNotFoundError:  # Python < 3.11 (mlops_tpu/config.py parity)
        import tomli as tomllib

    config = tomllib.loads(
        (REPO / "configs" / "train_register_job.toml").read_text()
    )
    assert {"data", "model", "train", "hpo", "registry"} <= config.keys()


def test_train_jobset_multihost_contracts():
    """The multi-host JobSet forms a correct jax.distributed cohort: the
    env contract matches what `parallel/distributed.py` consumes (and
    what tests/test_multihost_smoke.py live-tests cross-process)."""
    docs = _render(REPO / "kubernetes" / "train-jobset.yml")
    (jobset,) = docs
    assert jobset["kind"] == "JobSet"
    job = jobset["spec"]["replicatedJobs"][0]["template"]["spec"]
    assert job["parallelism"] == 4 and job["completions"] == 4
    assert job["completionMode"] == "Indexed"
    # Whole-cohort restarts only: a per-pod retry would rejoin a dead
    # handshake.
    assert job["backoffLimit"] == 0
    assert jobset["spec"]["failurePolicy"]["maxRestarts"] >= 1
    pod = job["template"]["spec"]
    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    assert set(env) >= {
        "MLOPS_TPU_COORDINATOR",
        "MLOPS_TPU_NUM_PROCESSES",
        "MLOPS_TPU_PROCESS_ID",
    }
    # Coordinator points at pod 0's stable DNS name inside the headless
    # service domain; every pod derives its rank from the completion index.
    assert env["MLOPS_TPU_COORDINATOR"]["value"].startswith(
        "train-register-123-workers-0-0.train-register-123:"
    )
    assert env["MLOPS_TPU_NUM_PROCESSES"]["value"] == "4"
    index_path = env["MLOPS_TPU_PROCESS_ID"]["valueFrom"]["fieldRef"]["fieldPath"]
    assert "job-completion-index" in index_path
    assert pod["subdomain"] == "train-register-123"
    assert pod["containers"][0]["resources"]["requests"]["google.com/tpu"] == "4"


def test_workflow_train_job_wiring():
    """The workflow submits THIS manifest and parses the tuner's JSON
    model_uri line (the notebook.exit analogue, SURVEY.md §3.2)."""
    text = (REPO / ".github" / "workflows" / "deploy-kubernetes.yml").read_text()
    assert "kubernetes/train-job.yml" in text
    assert "kubectl apply" in text
    assert "condition=complete" in text
    assert "model_uri" in text
    # Containerize resolves from the same registry root the Job wrote to.
    assert text.count("gs://${{ vars.DATA_BUCKET }}/registry") >= 2
