"""Engine replica set tests (mlops_tpu/replicaset/ + the ipc replica axis).

The correctness bar for ISSUE 13:

- `ReplicaRouter` units: least-loaded with a DETERMINISTIC tie-break,
  small-class affinity that holds inside the slack and re-picks beyond
  it, and routing AROUND a dead replica;
- E-replica fan-out parity: responses bit-identical to the single-engine
  plane (same programs, same slabs, same formatter — the router only
  chooses WHERE, never WHAT);
- per-replica re-attach: replica k's respawn replays exactly the busy
  slots tagged k, never a sibling's in-flight work;
- the render fix: every per-replica series is emitted for ALL configured
  replicas on every scrape — a never-dispatched replica exports zeros,
  because "no series" is indistinguishable from "dead replica";
- lock discipline: the PR 5 runtime sanitizer over an E-replica plane
  (per-replica queue locks wrapped explicitly — subscripted lock lists
  are invisible to the attribute-based instrumenter) across seeded
  schedule perturbations;
- partition-rule sharding: a large family (moe) served through
  SHARDED-not-replicated params with a bit-identical parity pin.
"""

import asyncio
import json

import numpy as np
import pytest

from mlops_tpu.replicaset import ReplicaRouter
from mlops_tpu.serve.ipc import LARGE, SMALL, RequestRing, RingClient, RingService


@pytest.fixture(scope="module")
def engine(warm_engine):
    return warm_engine  # session-shared warmed engine (conftest)


# ----------------------------------------------------------- router units
def _bare_ring(replicas: int, workers: int = 2) -> RequestRing:
    return RequestRing(
        workers=workers, slots_small=4, slots_large=1, large_rows=8,
        replicas=replicas,
    )


def test_router_least_loaded_tie_break_is_deterministic():
    ring = _bare_ring(3)
    try:
        ring.set_ready(True)
        router = ReplicaRouter(ring)
        # All depths equal: the tie breaks to the LOWEST index, every
        # time (two workers observing the same gauges agree).
        assert [router.route(0, LARGE) for _ in range(5)] == [0] * 5
        ring.rep_inflight[0, 0] = 3
        assert router.route(0, LARGE) == 1
        ring.rep_inflight[1, 1] = 3
        assert router.route(0, LARGE) == 2
        # Depth sums ACROSS workers: worker 0 and 1 each holding one on
        # replica 2 outweighs a single-slot replica.
        ring.rep_inflight[0, 2] = 2
        ring.rep_inflight[1, 2] = 2
        ring.rep_inflight[0, 0] = 1
        ring.rep_inflight[1, 1] = 0
        assert router.route(0, LARGE) == 1
    finally:
        ring.close()


def test_router_small_class_affinity_under_skewed_mix():
    ring = _bare_ring(2)
    try:
        ring.set_ready(True)
        router = ReplicaRouter(ring, affinity_slack=4)
        first = router.route(7, SMALL)
        assert first == 0
        # Inside the slack the sticky replica keeps winning even while
        # it is strictly deeper — that is the coalescing-company bet.
        ring.rep_inflight[0, 0] = 4
        assert router.route(7, SMALL) == 0
        # Beyond the slack the router re-picks least-loaded and the
        # stickiness moves with it.
        ring.rep_inflight[0, 0] = 5
        assert router.route(7, SMALL) == 1
        ring.rep_inflight[0, 1] = 2  # deeper, but inside the slack again
        assert router.route(7, SMALL) == 1
        # A DIFFERENT tenant's small traffic sticks independently.
        assert router.route(8, SMALL) == 1  # least-loaded now: 1? no —
        # depths: r0=5, r1=2 -> least is 1; tenant 8 sticks there.
        # The LARGE class never consults affinity: pure least-loaded.
        ring.rep_inflight[0, 1] = 9
        assert router.route(7, LARGE) == 0
    finally:
        ring.close()


def test_router_routes_around_dead_replica():
    ring = _bare_ring(3)
    try:
        ring.set_ready(True)
        router = ReplicaRouter(ring)
        sticky = router.route(0, SMALL)
        assert sticky == 0
        # Replica 0 dies: the supervisor clears its ready word — both
        # classes must route around the hole, sticky or not.
        ring.set_ready(False, 0)
        assert router.route(0, SMALL) != 0
        assert router.route(0, LARGE) != 0
        # Full outage: nothing ready. The router still names a concrete
        # replica (admissions PARK on its queue; the first replacement
        # to attach replays them) instead of refusing.
        ring.set_ready(False)
        assert router.route(0, LARGE) in (0, 1, 2)
    finally:
        ring.close()


def test_serveconfig_rejects_replicas_without_ring_plane():
    from mlops_tpu.config import ServeConfig, ServeConfigError

    with pytest.raises(ServeConfigError, match="engine_replicas"):
        ServeConfig(workers=0, engine_replicas=2).validate()
    assert ServeConfig(workers=2, engine_replicas=2).validate()


# ------------------------------------------------- render fix (satellite)
def test_render_emits_every_replica_series_on_every_scrape():
    """A never-dispatched replica must still export ALL its per-replica
    series (zeros): on a dashboard, an absent series is indistinguishable
    from a dead replica — the same always-emit contract PR 6 pinned for
    the per-worker depth/shed series."""
    from mlops_tpu.serve.metrics import render_ring_metrics

    ring = _bare_ring(3)
    try:
        ring.set_ready(True, 0)  # replicas 1 and 2 never served anything
        text = render_ring_metrics(ring)
        for r in range(3):
            for series, value in (
                ("mlops_tpu_replica_ready", 1 if r == 0 else 0),
                ("mlops_tpu_replica_ring_depth", 0),
                ("mlops_tpu_replica_incarnation", 0),
                ("mlops_tpu_replica_respawn_total", 0),
                ("mlops_tpu_replica_replayed_slots_total", 0),
                ("mlops_tpu_replica_rows_scored_total", 0),
            ):
                line = f'{series}{{replica="{r}"}} {value}'
                assert line in text, line
    finally:
        ring.close()


# ------------------------------------------------ per-replica re-attach
def test_reattach_replays_only_own_replica_slots(engine, sample_request):
    """Replica 0's respawn must replay exactly the busy slots tagged
    replica 0 — a sibling's in-flight slot is the sibling's live work
    (or its own successor's replay) and double-answering it would serve
    one slab twice."""
    from mlops_tpu.schema import records_to_columns
    from mlops_tpu.serve.wire import RESP_OK

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=4, slots_large=1, large_rows=8,
            replicas=2,
        )
        try:
            client = RingClient(ring, 0)
            ds = engine.bundle.preprocessor.encode(
                records_to_columns(sample_request)
            )
            slot0 = client.claim(len(sample_request))
            fut0 = client.submit(slot0, ds.cat_ids, ds.numeric, replica=0)
            slot1 = client.claim(len(sample_request))
            fut1 = client.submit(slot1, ds.cat_ids, ds.numeric, replica=1)
            # Both replicas' dead incarnations popped their descriptors
            # and died mid-batch.
            assert [s for s, _ in ring.pop_submissions(replica=0)] == [slot0]
            assert [s for s, _ in ring.pop_submissions(replica=1)] == [slot1]
            service0 = RingService(
                engine, ring, max_inflight=2, threads=2, replica=0
            )
            try:
                stats = service0.reattach()
            finally:
                service0.stop()
            assert stats["replayed_slots"] == 1
            client.on_doorbell(0)
            client.on_doorbell(1)
            assert fut0.done() and int(fut0.result()) == RESP_OK
            assert not fut1.done(), "a sibling's slot was double-served"
            # Replica 1's own successor answers its slot.
            service1 = RingService(
                engine, ring, max_inflight=2, threads=2, replica=1
            )
            try:
                stats1 = service1.reattach()
            finally:
                service1.stop()
            assert stats1["replayed_slots"] == 1
            client.on_doorbell(1)
            assert fut1.done() and int(fut1.result()) == RESP_OK
            client.release(slot0)
            client.release(slot1)
            assert int(ring.rep_inflight.sum()) == 0
        finally:
            ring.close()

    asyncio.run(scenario())


# ------------------------------------------------------- fan-out parity
def test_two_replica_fanout_responses_bit_identical(engine, sample_request):
    """Distinct payloads fanned out across two replica services must come
    back byte-identical to solo predicts — the router chooses WHERE, the
    shared programs and the one formatter decide WHAT."""
    from mlops_tpu.schema import records_to_columns
    from mlops_tpu.serve.wire import RESP_OK, format_response

    base = dict(sample_request[0])
    variants = []
    for i in range(8):
        record = dict(base)
        record["credit_limit"] = 1000.0 + 500.0 * i
        variants.append(record)
    expected = [
        json.loads(json.dumps(engine.predict_records([r])))
        for r in variants
    ]

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=16, slots_large=2, large_rows=8,
            replicas=2,
        )
        services = [
            RingService(engine, ring, max_inflight=2, threads=4, replica=r)
            for r in range(2)
        ]
        try:
            for r, service in enumerate(services):
                service.reattach()
                service.start()
                ring.set_ready(True, r)
            loop = asyncio.get_running_loop()
            client = RingClient(ring, 0)
            for r in range(2):
                loop.add_reader(
                    ring.worker_doorbell(0, r).fileno(),
                    client.on_doorbell,
                    r,
                )

            async def one(i: int) -> dict:
                ds = engine.bundle.preprocessor.encode(
                    records_to_columns([variants[i]])
                )
                slot = client.claim(1)
                assert slot is not None
                # Force the spread: even -> replica 0, odd -> replica 1,
                # so BOTH replicas provably serve (the router's own
                # spread is covered by its units).
                future = client.submit(
                    slot, ds.cat_ids, ds.numeric, replica=i % 2
                )
                status = await asyncio.wait_for(future, 30)
                assert status == RESP_OK
                pred, out, drift = client.response_arrays(slot)
                got = format_response(
                    np.array(pred), np.array(out), np.array(drift)
                )
                client.release(slot)
                return got

            results = await asyncio.gather(
                *(one(i) for i in range(len(variants)))
            )
            for r in range(2):
                loop.remove_reader(ring.worker_doorbell(0, r).fileno())
            for i, got in enumerate(results):
                assert json.loads(json.dumps(got)) == expected[i], f"req {i}"
            # Both replicas actually dispatched (each row's dispatch
            # telemetry is written by that replica's pool threads only).
            from mlops_tpu.serve.metrics import ENG_ROWS_DISPATCHED

            served = [
                int(ring.eng_vals[r, ENG_ROWS_DISPATCHED]) for r in range(2)
            ]
            assert all(s > 0 for s in served), served
        finally:
            for service in services:
                service.stop()
            ring.close()

    asyncio.run(scenario())


# --------------------------------------------------------- lock hygiene
@pytest.mark.parametrize(
    "seed",
    [0, pytest.param(1, marks=pytest.mark.slow),
     pytest.param(2, marks=pytest.mark.slow)],
)
def test_replica_plane_lock_discipline_under_perturbed_schedules(seed):
    """The PR 5 runtime sanitizer over router + E-replica RingService:
    the per-replica queue-lock LISTS are wrapped explicitly (the
    attribute instrumenter only sees scalar lock attrs) under the names
    the ipc manifest declares; zero order violations across seeded
    schedules, and every simulated response stays correct."""
    from mlops_tpu.analysis.lockcheck import LockSanitizer, instrument_locks
    from mlops_tpu.replicaset.sim import build_sim_plane, drive_grouped_load

    plane = build_sim_plane(
        replicas=2, device_ms=1.0, slots_small=32, max_group=8,
        max_inflight=2,
    )
    ring = plane.ring
    ring_san = LockSanitizer(
        order=("_submit_locks", "_complete_locks", "_profile_lock"),
        perturb_seed=seed,
    )
    saved_submit = ring._submit_locks
    saved_complete = ring._complete_locks
    ring._submit_locks = [
        ring_san.wrap(lock, "_submit_locks") for lock in saved_submit
    ]
    ring._complete_locks = [
        ring_san.wrap(lock, "_complete_locks") for lock in saved_complete
    ]
    try:
        with instrument_locks(
            plane.services[0], perturb_seed=seed
        ) as san0, instrument_locks(
            plane.services[1], perturb_seed=seed
        ) as san1:
            out = asyncio.run(
                drive_grouped_load(plane, duration_s=1.0, concurrency=24)
            )
        assert out["wrong"] == 0
        assert out["served"] > 0
        for sanitizer in (ring_san, san0, san1):
            assert not sanitizer.violations, [
                str(v) for v in sanitizer.violations
            ]
        assert ring_san.acquired.get("_submit_locks"), (
            "per-replica submit locks never exercised"
        )
        assert ring_san.acquired.get("_complete_locks")
    finally:
        ring._submit_locks = saved_submit
        ring._complete_locks = saved_complete
        plane.stop()


# ----------------------------------------------------- bench key contract
@pytest.mark.slow  # drives three sim planes (~8 s): CI's parallel job
def test_bench_replica_stage_key_contract():
    """BENCH_r07+ rounds carry the replica-scaling keys: per-E grouped
    req/s, the headline efficiency, per-replica goodput/depth splits at
    E=4, and the zero-wrong-responses pin."""
    import bench

    out = bench._replica_stage()
    for e in (1, 2, 4):
        assert out[f"replica_req_per_s_e{e}"] > 0
    assert 0.0 < out["replica_scaling_efficiency"] <= 1.5
    assert out["replica_wrong_responses"] == 0
    for r in range(4):
        assert out[f"replica_rows_r{r}_e4"] > 0
        assert out[f"replica_ring_depth_peak_r{r}_e4"] > 0


# ------------------------------------------------ partition-rule sharding
def test_mlp_engine_serves_through_sharded_params(tiny_pipeline, sample_request):
    """Fast tier-1 pin: serve.model_shards=2 lays the mlp trunk out over
    a ('model',) mesh (column/row cuts from PARAM_RULES) and responses
    stay bit-identical to the unsharded engine — same masked packed
    programs, layouts differ, XLA inserts the psums."""
    import jax

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (simulated) devices")
    _, result = tiny_pipeline
    baseline = InferenceEngine(
        load_bundle(result.bundle_dir), buckets=(1, 8), enable_grouping=False
    )
    baseline.warmup()
    expected = baseline.predict_records(sample_request)
    sharded = InferenceEngine(
        load_bundle(result.bundle_dir),
        buckets=(1, 8),
        enable_grouping=False,
        model_shards=2,
    )
    sharded.warmup()
    leaves = jax.tree_util.tree_leaves(sharded._variables)
    assert any(not leaf.sharding.is_fully_replicated for leaf in leaves), (
        "no leaf actually sharded — the rules matched nothing"
    )
    got = sharded.predict_records(sample_request)
    assert json.loads(json.dumps(got)) == json.loads(json.dumps(expected))


# Heaviest path (tiny moe train ~45 s serial): CI's parallel job runs it.
@pytest.mark.slow
def test_moe_large_family_served_sharded_not_replicated(tmp_path):
    """ISSUE 13 acceptance parity pin: a LARGE family (moe) trains,
    bundles, and serves through EXPERT-SHARDED params (stacked [E, ...]
    expert weights split over the model axis, attention heads too) with
    responses bit-identical to the unsharded engine."""
    import jax

    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.schema import LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_training

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 (simulated) devices")
    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(
        family="moe", token_dim=16, depth=1, heads=2, num_experts=2
    )
    config.train = TrainConfig(steps=30, eval_every=30, batch_size=256)
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    record = [LoanApplicant().model_dump()]
    baseline = InferenceEngine(
        load_bundle(result.bundle_dir), buckets=(1, 8), enable_grouping=False
    )
    baseline.warmup()
    expected = baseline.predict_records(record)
    sharded = InferenceEngine(
        load_bundle(result.bundle_dir),
        buckets=(1, 8),
        enable_grouping=False,
        model_shards=2,
    )
    sharded.warmup()
    # The EXPERT axis is what shards — stacked [E, D, F] weights split
    # across the model mesh instead of replicating per device.
    from jax.tree_util import tree_leaves_with_path

    expert_leaves = [
        (path, leaf)
        for path, leaf in tree_leaves_with_path(sharded._variables)
        if "experts_" in str(path)
    ]
    assert expert_leaves
    assert any(
        not leaf.sharding.is_fully_replicated for _, leaf in expert_leaves
    ), "expert weights replicated — partition rules missed the family"
    got = sharded.predict_records(record)
    assert json.loads(json.dumps(got)) == json.loads(json.dumps(expected))
