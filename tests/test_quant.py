"""ISSUE 17 quantized-tier contracts: the int8/bf16 student's Pallas
kernel is BIT-IDENTICAL to its jnp composite at every serve bucket and
group geometry, the distilled tier's fidelity sits numerically inside the
promotion gates it shipped with, bundles round-trip the quant tree
losslessly (and refuse foreign packing formats), and the serving/bulk
tier selectors honor demand-vs-preference semantics end to end.
"""

import dataclasses
import json
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.monitor import init_accumulator
from mlops_tpu.ops.predict import packed_layout
from mlops_tpu.ops.quant import (
    QUANT_EMBED_DIM,
    QUANT_FORMAT,
    QUANT_HIDDEN,
    abstract_quant_params,
    dequantize_dense,
    quant_params_from_arrays,
    quant_params_geometry,
    quant_params_to_arrays,
    quantize_dense,
)
from mlops_tpu.ops.quant_kernel import (
    QUANT_KERNEL_MAX_ROWS,
    make_quant_grouped_base,
    make_quant_packed_base,
    quant_kernel_available,
)
from mlops_tpu.schema import SCHEMA, records_to_columns
from mlops_tpu.serve.engine import (
    GROUP_ROW_BUCKET,
    GROUP_ROW_BUCKETS,
    GROUP_SLOT_BUCKETS,
    InferenceEngine,
)
from mlops_tpu.serve.wire import format_response


@pytest.fixture(scope="module")
def quant_pipeline(tmp_path_factory):
    """One training run with the quant tier opted in (the tiny_pipeline
    geometry + ``train.distill_quant``): teacher, monitors, AND the
    graded int8/bf16 student in one bundle."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    root = tmp_path_factory.mktemp("quant_pipeline")
    config = Config()
    config.data.rows = 3000
    config.model = ModelConfig(family="mlp", hidden_dims=(32, 32), embed_dim=4)
    config.train = TrainConfig(
        steps=100, eval_every=100, batch_size=256, distill_quant=True
    )
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    result = run_training(config)
    return config, result


@pytest.fixture(scope="module")
def quant_bundle(quant_pipeline):
    from mlops_tpu.bundle import load_bundle

    _, result = quant_pipeline
    return load_bundle(result.bundle_dir)


@pytest.fixture(scope="module")
def quant_engine(quant_bundle):
    """Quant-tier serving engine, warmed on demand (novel shapes compile
    into the exec table on first sight — no warmup() needed)."""
    return InferenceEngine(quant_bundle, buckets=(1, 8), serve_tier="quant")


@pytest.fixture(scope="module")
def encoded_batch(quant_bundle):
    """A held-out encoded batch through the BUNDLE's preprocessor (the
    arrays every tier consumes)."""
    from mlops_tpu.data import generate_synthetic

    columns, labels = generate_synthetic(512, seed=3)
    return quant_bundle.preprocessor.encode(columns, labels)


# ----------------------------------------------------------- quantization
def test_quantize_dense_roundtrip_properties():
    """Per-output-channel symmetric int8: dequant error is bounded by half
    a quantization step per column, the column absmax maps to the ±127
    rail exactly, and all-zero columns stay exactly zero (scale 1)."""
    rng = np.random.default_rng(0)
    w = rng.normal(size=(40, 8)).astype(np.float32) * rng.uniform(
        0.1, 30.0, size=(1, 8)
    ).astype(np.float32)
    w[:, 3] = 0.0
    q, s = quantize_dense(w)
    assert q.dtype == np.int8 and s.dtype == np.float32
    assert s[3] == 1.0 and not q[:, 3].any()
    live = [j for j in range(8) if j != 3]
    assert all(np.abs(q[:, j]).max() == 127 for j in live)
    deq = np.asarray(dequantize_dense(jnp.asarray(q), jnp.asarray(s)))
    assert np.all(np.abs(deq - w) <= s[None, :] * 0.5 + 1e-6)
    assert not deq[:, 3].any()


def test_quant_tree_matches_abstract_twin(quant_bundle):
    """The fitted tree's shapes/dtypes ARE the abstract cache-key twin
    (`abstract_quant_params`) — a drift here silently forks the AOT cache
    keys from the programs production dispatches."""
    qp = quant_bundle.quant_params
    twin = abstract_quant_params()
    assert set(qp) == set(twin)
    for key, aval in twin.items():
        assert qp[key].shape == aval.shape, key
        assert qp[key].dtype == aval.dtype, key
    assert quant_params_geometry(qp) == (QUANT_EMBED_DIM, QUANT_HIDDEN)


def test_quant_serialization_roundtrip_bitwise(quant_bundle):
    """npz arrays -> jnp tree -> npz arrays is lossless: bf16 -> f32 is
    exact and the f32 -> bf16 cast returns the original bits."""
    qp = quant_bundle.quant_params
    back = quant_params_from_arrays(quant_params_to_arrays(qp))
    assert set(back) == set(qp)
    for key in qp:
        assert back[key].dtype == qp[key].dtype, key
        np.testing.assert_array_equal(
            np.asarray(back[key].astype(jnp.float32)),
            np.asarray(qp[key].astype(jnp.float32)),
            err_msg=key,
        )


# ------------------------------------------------- kernel/composite parity
def _padded_solo(ds, n, bucket):
    cat = np.zeros((bucket, SCHEMA.num_categorical), np.int32)
    num = np.zeros((bucket, SCHEMA.num_numeric), np.float32)
    cat[:n] = ds.cat_ids[:n]
    num[:n] = ds.numeric[:n]
    return cat, num, np.arange(bucket) < n


def _assert_trees_bitwise(got, want, label):
    flat_g, _ = jax.tree_util.tree_flatten(got)
    flat_w, _ = jax.tree_util.tree_flatten(want)
    assert len(flat_g) == len(flat_w)
    for g, w in zip(flat_g, flat_w):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=label
        )


def test_kernel_vs_composite_bit_parity_every_bucket(
    quant_bundle, encoded_batch
):
    """The ISSUE 17 parity pin, solo family: the forced pallas_call
    (interpret mode off-TPU) and the jnp composite produce BIT-IDENTICAL
    packed buffers and accumulator folds at every serve bucket up to the
    kernel's row ceiling — partial masks included. Both routes are jitted
    (eager-vs-jit reassociation differs at B>=64; the serving comparison
    is compiled-vs-compiled)."""
    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = np.float32(quant_bundle.quant_temperature)
    kernel = jax.jit(make_quant_packed_base(use_kernel=True))
    composite = jax.jit(make_quant_packed_base(use_kernel=False))
    for bucket in (1, 8, 64, QUANT_KERNEL_MAX_ROWS):
        n = 1 if bucket == 1 else bucket - 3
        cat, num, mask = _padded_solo(encoded_batch, n, bucket)
        got = kernel(qp, mon, init_accumulator(), t, cat, num, mask)
        want = composite(qp, mon, init_accumulator(), t, cat, num, mask)
        _assert_trees_bitwise(got, want, f"bucket {bucket}")
        # The packed buffer is the exact tier's layout: finite, probs in
        # [0, 1], flags in {0, 1}, padding rows zero-masked.
        arr = np.asarray(got[0])
        p, o, _ = packed_layout(bucket)
        assert np.isfinite(arr).all()
        assert (0.0 <= arr[p][:n]).all() and (arr[p][:n] <= 1.0).all()
        assert set(np.unique(arr[o])) <= {0.0, 1.0}


def test_kernel_vs_composite_bit_parity_every_group_geometry(
    quant_bundle, encoded_batch
):
    """Grouped family: every (slots, rows) shape the engine's group grid
    serves, with per-slot partial masks — the vmapped pallas_call against
    the vmapped composite, bitwise on the [S, 2R+D] packed stack AND the
    grouped accumulator fold."""
    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = np.float32(quant_bundle.quant_temperature)
    kernel = jax.jit(make_quant_grouped_base(use_kernel=True))
    composite = jax.jit(make_quant_grouped_base(use_kernel=False))
    ds = encoded_batch
    for slots in GROUP_SLOT_BUCKETS:
        for rows in GROUP_ROW_BUCKETS:
            cat = np.zeros(
                (slots, rows, SCHEMA.num_categorical), np.int32
            )
            num = np.zeros((slots, rows, SCHEMA.num_numeric), np.float32)
            mask = np.zeros((slots, rows), bool)
            for i in range(slots):
                k = (i % rows) + 1
                lo = (i * rows) % (ds.n - rows)
                cat[i, :k] = ds.cat_ids[lo : lo + k]
                num[i, :k] = ds.numeric[lo : lo + k]
                mask[i, :k] = True
            got = kernel(qp, mon, init_accumulator(), t, cat, num, mask)
            want = composite(
                qp, mon, init_accumulator(), t, cat, num, mask
            )
            _assert_trees_bitwise(got, want, f"group {slots}x{rows}")


def test_capability_gate_auto_routes_composite_off_tpu(
    quant_bundle, encoded_batch
):
    """`use_kernel=None` is the production route: off-TPU it must take the
    composite — and therefore equal the explicit composite bitwise."""
    assert not quant_kernel_available()  # this suite runs on the CPU mesh
    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = np.float32(quant_bundle.quant_temperature)
    cat, num, mask = _padded_solo(encoded_batch, 5, 8)
    auto = jax.jit(make_quant_packed_base())(
        qp, mon, init_accumulator(), t, cat, num, mask
    )
    composite = jax.jit(make_quant_packed_base(use_kernel=False))(
        qp, mon, init_accumulator(), t, cat, num, mask
    )
    _assert_trees_bitwise(auto, composite, "auto-vs-composite")


# ------------------------------------------------------------ fidelity pin
def test_quant_fidelity_pinned_inside_promotion_gates(quant_bundle):
    """The numeric acceptance pin: the shipped tier's held-out AUC delta
    and ECE sit inside the SAME promotion-gate thresholds the engine
    admits it by (`lifecycle/promote.py quant_tier_gates`), and those
    thresholds are pinned numerically so a config drift cannot quietly
    loosen the tier."""
    from mlops_tpu.config import LifecycleConfig

    gates = LifecycleConfig()
    assert gates.max_auc_drop == 0.01
    assert gates.max_ece == 0.1
    assert quant_bundle.has_quant
    assert quant_bundle.quant_gates_passed
    fidelity = quant_bundle.quant_fidelity
    assert fidelity["roc_auc_delta"] >= -gates.max_auc_drop
    assert 0.0 <= fidelity["ece"] <= gates.max_ece
    # The tier carries its OWN refit temperature (quantization shifts the
    # logit scale) — a positive, finite calibration scalar.
    assert 0.0 < quant_bundle.quant_temperature < 100.0


def test_bundle_refuses_foreign_quant_format(quant_pipeline, tmp_path):
    """A quant blob written by a different packing scheme must refuse to
    load (wrong-format params would serve garbage bit patterns), naming
    the format it found."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.bundle.bundle import MANIFEST_NAME

    _, result = quant_pipeline
    clone = tmp_path / "foreign"
    shutil.copytree(result.bundle_dir, clone)
    manifest = json.loads((clone / MANIFEST_NAME).read_text())
    assert manifest["quant"]["format"] == QUANT_FORMAT
    manifest["quant"]["format"] = "int4-blockwise/v9"
    (clone / MANIFEST_NAME).write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="int4-blockwise/v9"):
        load_bundle(clone)


# ------------------------------------------------------------ serving tier
def test_engine_tier_resolution_demand_vs_preference(quant_bundle):
    """`serve_tier` semantics: "quant" on a gated bundle takes the tier,
    "auto" prefers it, a demand against an ineligible bundle RAISES
    (never a silent downgrade), "auto" falls back to exact, and an
    unknown tier name is rejected."""
    assert (
        InferenceEngine(
            quant_bundle, buckets=(1,), enable_grouping=False,
            serve_tier="auto",
        ).serve_tier
        == "quant"
    )
    with pytest.raises(ValueError, match="serve_tier"):
        InferenceEngine(quant_bundle, buckets=(1,), serve_tier="int8")
    naked = dataclasses.replace(quant_bundle, quant_params=None)
    with pytest.raises(ValueError, match="no quant params"):
        InferenceEngine(naked, buckets=(1,), serve_tier="quant")
    assert (
        InferenceEngine(
            naked, buckets=(1,), enable_grouping=False, serve_tier="auto"
        ).serve_tier
        == "exact"
    )
    # Present but ungated: the stamp is the admission check, not presence.
    ungated_manifest = json.loads(json.dumps(quant_bundle.manifest))
    ungated_manifest["quant"]["gates"]["passed"] = False
    ungated = dataclasses.replace(quant_bundle, manifest=ungated_manifest)
    with pytest.raises(ValueError, match="promotion"):
        InferenceEngine(ungated, buckets=(1,), serve_tier="quant")


def test_quant_engine_solo_bit_identical_to_composite(
    quant_engine, quant_bundle, sample_request
):
    """The quant ENGINE's wire responses (padded packed path, both warmed
    buckets) equal the jitted composite reference bit for bit — same
    packed layout, same f64 cast and round(6) drift discipline as the
    exact tier."""
    assert quant_engine.serve_tier == "quant"
    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = np.float32(quant_bundle.quant_temperature)
    reference = jax.jit(make_quant_packed_base(use_kernel=False))
    for bucket, n in ((1, 1), (8, 5)):
        records = []
        for i in range(n):
            rec = dict(sample_request[0])
            rec["age"] = 25.0 + 3.0 * i + bucket
            rec["bill_amount_1"] = 200.0 * (i + 1)
            records.append(rec)
        ds = quant_bundle.preprocessor.encode(records_to_columns(records))
        got = quant_engine.predict_arrays(ds.cat_ids, ds.numeric)
        cat, num, mask = (
            np.pad(ds.cat_ids, ((0, bucket - n), (0, 0))),
            np.pad(ds.numeric, ((0, bucket - n), (0, 0))),
            np.arange(bucket) < n,
        )
        packed, _ = reference(qp, mon, init_accumulator(), t, cat, num, mask)
        arr = np.asarray(jax.device_get(packed))
        p, o, d = packed_layout(bucket)
        want = format_response(
            arr[p][:n].astype(float),
            arr[o][:n].astype(float),
            arr[d].astype(float).round(6),
        )
        assert got == want, f"bucket {bucket} diverged"


def test_quant_engine_grouped_bit_identical_to_composite(
    quant_engine, quant_bundle, sample_request
):
    """Grouped quant serving: mixed-size concurrent requests through
    `predict_group` equal the vmapped composite reference assembly — per
    request, drift over each slot's OWN rows."""
    import bisect

    sizes = (1, 3, 2)
    requests = []
    for i, size in enumerate(sizes):
        rec = dict(sample_request[0])
        rec["age"] = 30.0 + 7.0 * i
        rec["credit_limit"] = 5000.0 + 900.0 * i
        requests.append([rec] * size)
    got = quant_engine.predict_group(requests)

    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = np.float32(quant_bundle.quant_temperature)
    slots = GROUP_SLOT_BUCKETS[
        bisect.bisect_left(GROUP_SLOT_BUCKETS, len(requests))
    ]
    rows = GROUP_ROW_BUCKETS[0] if max(sizes) == 1 else GROUP_ROW_BUCKET
    cat = np.zeros((slots, rows, SCHEMA.num_categorical), np.int32)
    num = np.zeros((slots, rows, SCHEMA.num_numeric), np.float32)
    mask = np.zeros((slots, rows), bool)
    flat = [record for records in requests for record in records]
    ds = quant_bundle.preprocessor.encode(records_to_columns(flat))
    offset = 0
    for i, k in enumerate(sizes):
        cat[i, :k] = ds.cat_ids[offset : offset + k]
        num[i, :k] = ds.numeric[offset : offset + k]
        mask[i, :k] = True
        offset += k
    packed, _ = jax.jit(make_quant_grouped_base(use_kernel=False))(
        qp, mon, init_accumulator(), t, cat, num, mask
    )
    arr = np.asarray(jax.device_get(packed))
    p, o, d = packed_layout(rows)
    want = [
        format_response(
            arr[i, p][:k].astype(float),
            arr[i, o][:k].astype(float),
            arr[i, d].astype(float).round(6),
        )
        for i, k in enumerate(sizes)
    ]
    assert got == want


# --------------------------------------------------------------- bulk tier
def test_use_quant_bulk_demand_vs_preference(quant_bundle):
    from mlops_tpu.parallel.bulk import use_quant_bulk

    assert use_quant_bulk(quant_bundle, "quant")
    assert use_quant_bulk(quant_bundle, "auto")
    assert not use_quant_bulk(quant_bundle, "exact")
    naked = dataclasses.replace(quant_bundle, quant_params=None)
    assert not use_quant_bulk(naked, "auto")
    with pytest.raises(ValueError, match="refused"):
        use_quant_bulk(naked, "quant")
    with pytest.raises(ValueError, match="tier"):
        use_quant_bulk(quant_bundle, "int8")


def test_bulk_quant_sweep_bit_identical_to_reference(
    quant_bundle, encoded_batch
):
    """`score_dataset(tier="quant")` equals the raw jitted quant chunk
    program applied chunk by chunk (int8 cat transport, padded tail) —
    and the "auto" route takes the identical path on a gated bundle."""
    from mlops_tpu.parallel.bulk import make_bulk_quant_fused, score_dataset

    ds = encoded_batch
    chunk = 256
    result = score_dataset(quant_bundle, ds, chunk_rows=chunk, tier="quant")
    assert result.path == "quant"
    assert result.rows == ds.n

    fn = jax.jit(make_bulk_quant_fused())
    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = np.float32(quant_bundle.quant_temperature)
    want = np.empty(ds.n, np.float32)
    for start in range(0, ds.n, chunk):
        stop = min(start + chunk, ds.n)
        cat = np.zeros((chunk, SCHEMA.num_categorical), np.int8)
        num = np.zeros((chunk, SCHEMA.num_numeric), np.float32)
        cat[: stop - start] = ds.cat_ids[start:stop].astype(np.int8)
        num[: stop - start] = ds.numeric[start:stop]
        mask = np.arange(chunk) < (stop - start)
        probs, _ = fn(qp, mon, t, cat, num, mask)
        want[start:stop] = np.asarray(probs)[: stop - start]
    np.testing.assert_array_equal(result.predictions, want)

    auto = score_dataset(quant_bundle, ds, chunk_rows=chunk, tier="auto")
    assert auto.path == "quant"
    np.testing.assert_array_equal(auto.predictions, result.predictions)
    exact = score_dataset(quant_bundle, ds, chunk_rows=chunk, tier="exact")
    assert exact.path == "exact"  # mlp teacher: no bulk student distilled


# ----------------------------------------------------- compile-cache jobs
def test_quant_warmup_jobs_carry_their_entry_ids(quant_bundle):
    """The quant tier's cache-entry family: registered ids, per-bucket
    serve jobs, grouped-grid jobs, and the bulk chunk job keyed apart
    from the exact path by the quant format + geometry fingerprint."""
    from mlops_tpu.compilecache.registry import CACHE_ENTRY_IDS
    from mlops_tpu.compilecache.warmup import (
        bulk_quant_chunk_job,
        serve_quant_group_jobs,
        serve_quant_jobs,
    )

    assert "serve-predict-quant-packed" in CACHE_ENTRY_IDS
    assert "serve-predict-quant-group-packed" in CACHE_ENTRY_IDS
    qp, mon = quant_bundle.quant_params, quant_bundle.monitor
    t = quant_bundle.quant_temperature

    jobs = serve_quant_jobs(qp, mon, buckets=(1, 8), temperature=t)
    assert [j.entry_id for j in jobs] == ["serve-predict-quant-packed"] * 2
    assert len({j.config_hash for j in jobs}) == 1  # one geometry, one key

    gjobs = serve_quant_group_jobs(qp, mon, grid=[(2, 8)], temperature=t)
    assert [j.entry_id for j in gjobs] == ["serve-predict-quant-group-packed"]

    bulk = bulk_quant_chunk_job(qp, mon, chunk_rows=4096)
    assert bulk.entry_id == "bulk-score-chunk"
    assert bulk.label == "bulk-score-chunk/quant-c4096"
    assert bulk.meta == {"chunk_rows": 4096, "path": "quant"}
    # Keyed apart from the serve family AND from any exact-path chunk job
    # (the exact path fingerprints the flax model config; quant
    # fingerprints the packing format + geometry).
    assert bulk.config_hash != jobs[0].config_hash
