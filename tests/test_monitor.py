"""Monitor tests: scipy parity for the statistics, drift/outlier semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import MonitorConfig
from mlops_tpu.monitor import MonitorState, drift_scores, fit_monitor, outlier_flags
from mlops_tpu.ops.drift import chi2_two_sample, ks_two_sample
from mlops_tpu.ops.outlier import fit_mahalanobis, mahalanobis_sq
from mlops_tpu.schema import NUM_FEATURES


def test_chi2_matches_scipy():
    scipy_stats = pytest.importorskip("scipy.stats")
    rng = np.random.default_rng(0)
    ref = rng.multinomial(5000, [0.5, 0.3, 0.15, 0.05]).astype(float)
    batch = rng.multinomial(300, [0.4, 0.35, 0.15, 0.10]).astype(float)
    stat, p = chi2_two_sample(jnp.asarray(ref), jnp.asarray(batch))
    ref_stat, ref_p, _, _ = scipy_stats.chi2_contingency(
        np.stack([ref, batch]), correction=False
    )
    assert abs(float(stat) - ref_stat) < 1e-3
    assert abs(float(p) - ref_p) < 1e-5


def test_chi2_empty_categories_masked():
    # Categories observed in neither sample must not poison the statistic.
    ref = jnp.asarray([100.0, 50.0, 0.0, 0.0])
    batch = jnp.asarray([40.0, 20.0, 0.0, 0.0])
    stat, p = chi2_two_sample(ref, batch)
    assert np.isfinite(float(stat))
    assert float(p) > 0.9  # same distribution -> no drift


def test_ks_matches_scipy_asymp():
    scipy_stats = pytest.importorskip("scipy.stats")
    rng = np.random.default_rng(1)
    ref = np.sort(rng.normal(size=2048)).astype(np.float32)
    batch = rng.normal(0.3, 1.0, size=256).astype(np.float32)
    stat, p = ks_two_sample(jnp.asarray(ref), jnp.asarray(batch))
    res = scipy_stats.ks_2samp(ref, batch, method="asymp")
    assert abs(float(stat) - res.statistic) < 1e-6
    # Asymptotic formulas differ slightly (Stephens correction) — tight but
    # not exact.
    assert abs(float(p) - res.pvalue) < 5e-3


def test_ks_identical_distribution_high_p():
    rng = np.random.default_rng(2)
    sample = rng.normal(size=2048).astype(np.float32)
    stat, p = ks_two_sample(jnp.asarray(np.sort(sample)), jnp.asarray(sample))
    assert float(stat) < 1e-6
    assert float(p) > 0.99


def test_mahalanobis_flags_quantile():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5000, 14)).astype(np.float32)
    mean, precision, threshold = fit_mahalanobis(x, quantile=0.95)
    d = mahalanobis_sq(jnp.asarray(x), jnp.asarray(mean), jnp.asarray(precision))
    frac = float((np.asarray(d) > threshold).mean())
    assert abs(frac - 0.05) < 0.01  # ~5% of training data flagged


def test_monitor_fit_and_score_in_distribution(encoded_small):
    _, ds = encoded_small
    state = fit_monitor(ds, MonitorConfig())
    scores = drift_scores(state, jnp.asarray(ds.cat_ids), jnp.asarray(ds.numeric))
    assert scores.shape == (NUM_FEATURES,)
    # Scoring the training data against itself: no drift anywhere.
    assert float(np.max(np.asarray(scores))) < 0.95
    flags = outlier_flags(state, jnp.asarray(ds.numeric))
    assert set(np.unique(np.asarray(flags))) <= {0.0, 1.0}
    assert 0.01 < float(np.mean(np.asarray(flags))) < 0.10


def test_monitor_detects_shift(encoded_small):
    from mlops_tpu.data import Preprocessor, generate_synthetic

    prep, ds = encoded_small
    state = fit_monitor(ds, MonitorConfig())
    shifted_cols, _ = generate_synthetic(1000, seed=99, drift=1.5)
    shifted = prep.encode(shifted_cols)
    scores = drift_scores(
        state, jnp.asarray(shifted.cat_ids), jnp.asarray(shifted.numeric)
    )
    # The drifted generator shifts age/credit distributions and repayment
    # behavior: a majority of features should cross 1 - p_val > 0.95.
    assert float(np.mean(np.asarray(scores) > 0.95)) > 0.5


def test_monitor_state_save_load(tmp_path, encoded_small):
    _, ds = encoded_small
    state = fit_monitor(ds, MonitorConfig())
    state.save(tmp_path / "monitor")
    state2 = MonitorState.load(tmp_path / "monitor")
    np.testing.assert_array_equal(
        np.asarray(state.cat_ref_counts), np.asarray(state2.cat_ref_counts)
    )
    np.testing.assert_array_equal(
        np.asarray(state.out_precision), np.asarray(state2.out_precision)
    )


def test_ks_small_masked_matches_pooled():
    """The dense-comparison small-batch K-S (grouped serving hot path) is
    bit-equivalent to the pooled sort/searchsorted form — incl. ties,
    padding, duplicate reference values, and the all-padded guard."""
    import numpy as np

    from mlops_tpu.monitor.state import _ref_cdf
    from mlops_tpu.ops.drift import (
        ks_two_sample_masked,
        ks_two_sample_small_masked,
    )

    rng = np.random.default_rng(5)
    ref = np.sort(
        np.round(rng.normal(size=256), 1).astype(np.float32)
    )  # rounding forces ties
    ref_cdf = _ref_cdf(ref[None, :])[0]
    for n_valid in (0, 1, 3, 8):
        batch = np.round(rng.normal(size=8), 1).astype(np.float32)
        batch[0:1] = ref[10]  # tie against the reference
        mask = np.arange(8) < n_valid
        s1, p1 = ks_two_sample_masked(ref, batch, mask)
        s2, p2 = ks_two_sample_small_masked(ref, ref_cdf, batch, mask)
        np.testing.assert_allclose(float(s1), float(s2), atol=1e-6)
        np.testing.assert_allclose(float(p1), float(p2), atol=1e-6)


def test_monitor_state_backcompat_without_ref_cdf(encoded_small):
    """Bundles saved before num_ref_cdf existed load and score identically."""
    import numpy as np

    from mlops_tpu.monitor.state import MonitorState, fit_monitor

    _, ds = encoded_small
    state = fit_monitor(ds)
    arrays = state.to_arrays()
    arrays.pop("num_ref_cdf")
    revived = MonitorState.from_arrays(arrays)
    np.testing.assert_allclose(
        np.asarray(revived.num_ref_cdf), np.asarray(state.num_ref_cdf)
    )
