"""gbm-tensor serving parity: the Hummingbird-style tensorization of the
HistGBM family (`ops/gbm_tensor.py`) must reproduce the sklearn host
path BIT-FOR-BIT at every bucket and group geometry (ISSUE 19 — the
sklearn floor is the mandatory parity reference; anything weaker would
let the packed hot path silently serve different probabilities than the
family's own `predict_proba`)."""

import numpy as np
import pytest

from mlops_tpu.schema import SCHEMA
from mlops_tpu.serve.engine import InferenceEngine
from mlops_tpu.serve.tierroute import SLO_ACCURATE, SLO_CHEAP


@pytest.fixture(scope="module")
def gbm_pipeline(tmp_path_factory):
    """One gbm training run (HistGBM + calibration temperature) shared by
    the parity pins below."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    root = tmp_path_factory.mktemp("gbm_tensor")
    config = Config()
    config.data.rows = 3000
    config.model = ModelConfig(
        family="gbm", n_estimators=40, max_tree_depth=4
    )
    config.train = TrainConfig(seed=0)
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    result = run_training(config)
    return config, result


@pytest.fixture(scope="module")
def gbm_bundle(gbm_pipeline):
    from mlops_tpu.bundle import load_bundle

    _, result = gbm_pipeline
    return load_bundle(result.bundle_dir)


@pytest.fixture(scope="module")
def gbm_engine(gbm_bundle):
    engine = InferenceEngine(gbm_bundle, buckets=(1, 8, 64))
    engine.warmup()
    return engine


@pytest.fixture(scope="module")
def batch(gbm_bundle):
    """64 encoded rows with unknown-category pokes (ids past the vocab —
    the tensor program's gather clamp must agree with sklearn's own
    unknown-bucket handling) plus the sklearn-floor reference."""
    from mlops_tpu.train.calibrate import apply_temperature

    rng = np.random.default_rng(7)
    cat = rng.integers(
        0, 4, size=(64, SCHEMA.num_categorical)
    ).astype(np.int32)
    num = rng.normal(size=(64, SCHEMA.num_numeric)).astype(np.float32)
    cat[3, 0] = 200
    cat[9, 2] = 255
    ref = apply_temperature(
        gbm_bundle.estimator.predict_proba(cat, num),
        gbm_bundle.temperature,
    ).astype(np.float32)
    return cat, num, ref


def _solo(engine, cat, num):
    handle = engine.dispatch_arrays(cat, num)
    handle.start_copy()
    preds, _, _ = engine.fetch_arrays_raw(handle)
    return preds.astype(np.float32)


def test_bit_parity_every_bucket_geometry(gbm_engine, batch):
    """Exact (1, 8, 64) bucket hits AND every padded residency class
    (n < bucket pads up) reproduce the sklearn floor bit-for-bit."""
    cat, num, ref = batch
    for n in (1, 2, 5, 8, 9, 40, 64):
        got = _solo(gbm_engine, cat[:n], num[:n])
        assert (got == ref[:n]).all(), f"parity broke at n={n}"


def test_bit_parity_every_group_geometry(gbm_engine, batch):
    """Grouped dispatches (the scatter/slice path) return the same bits
    as the sklearn floor for every slot, across slot counts and padded
    row geometries."""
    cat, num, ref = batch
    geometries = (
        [8, 8],  # exact rows, 2 slots
        [1, 4, 8],  # mixed padded rows, 3 slots
        [2, 2, 2, 2, 2],  # 5 slots (pads up the slot bucket too)
    )
    for sizes in geometries:
        parts, offset = [], 0
        for n in sizes:
            parts.append((cat[offset : offset + n], num[offset : offset + n]))
            offset += n
        handle = gbm_engine.dispatch_group_arrays(parts)
        got_sizes, preds, _, _ = gbm_engine.fetch_group_raw(handle)
        assert list(got_sizes) == sizes
        offset = 0
        for i, n in enumerate(sizes):
            got = preds[i, :n].astype(np.float32)
            assert (got == ref[offset : offset + n]).all(), (
                f"group parity broke at geometry {sizes} slot {i}"
            )
            offset += n


def test_predict_records_matches_sklearn_floor(gbm_engine, gbm_bundle):
    """The record-level serving surface (encode -> packed dispatch ->
    response formatting) agrees with the host hybrid to the packed
    pipeline's f32 precision."""
    from mlops_tpu.train.calibrate import apply_temperature

    records = [
        {"age": 30.0, "credit_limit": 2000.0},
        {"age": 55.0, "credit_limit": 90000.0, "education": "graduate"},
    ]
    response = gbm_engine.predict_records(records)
    from mlops_tpu.schema import records_to_columns

    ds = gbm_bundle.preprocessor.encode(records_to_columns(records))
    ref = apply_temperature(
        gbm_bundle.estimator.predict_proba(ds.cat_ids, ds.numeric),
        gbm_bundle.temperature,
    ).astype(np.float32)
    assert (np.asarray(response["predictions"], np.float32) == ref).all()


def test_gbm_is_a_single_tier_grouping_family(gbm_engine):
    """The tensorized gbm family serves through the packed contract: it
    grows a group path, names its own tier, and (being single-tier)
    collapses every SLO class onto the default program."""
    assert gbm_engine.supports_grouping
    assert gbm_engine.default_tier == "gbm"
    assert gbm_engine.available_tiers == ("gbm",)
    assert gbm_engine.route_tier(SLO_CHEAP) is None
    assert gbm_engine.route_tier(SLO_ACCURATE) is None


def test_gbm_serve_entries_ride_the_compile_cache(gbm_engine):
    """The tensorized programs register their own AOT cache entry
    families (serve-predict-gbm-packed / -group-packed), so a respawned
    engine deserializes them instead of re-tracing."""
    from mlops_tpu.compilecache.registry import CACHE_ENTRY_IDS

    assert "serve-predict-gbm-packed" in CACHE_ENTRY_IDS
    assert "serve-predict-gbm-group-packed" in CACHE_ENTRY_IDS
