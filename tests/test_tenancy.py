"""Multi-tenant model multiplexing tests (mlops_tpu/tenancy/, ISSUE 12).

The correctness bar for serving N portfolios from one plane:

- per-tenant responses BIT-IDENTICAL to each tenant's solo engine on
  BOTH planes (>=3 tenants, mixed architectures), with the `x-tenant`
  header routing and untagged traffic landing on the declared default;
- architecture-identical tenants PROVABLY share compiled executables
  (`shared_exec_count`, shared exec table + compile lock identity);
- admission is weighted max-min fair: a hot tenant past its share sheds
  503 against ITS OWN quota while a cold tenant's floor stays claimable
  (the starvation guarantee, deterministic at the governor and live on
  the ring plane);
- an engine kill -9 replay lands each busy slot under the CORRECT
  tenant's bundle with per-tenant monitor counters staying monotone;
- the ring/engine lock discipline holds under the runtime sanitizer
  with multi-tenant traffic, and the tenancy modules' declared-lock-free
  manifests (TPULINT_LOCK_ORDER) match reality;
- the fleet config rejects broken tenants.toml shapes with every
  problem named, and the single-tenant config degrades to the
  pre-tenancy plane.
"""

import contextlib
import dataclasses
import json
import socket
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from mlops_tpu.config import ServeConfig
from mlops_tpu.serve.frontend import reuseport_socket, start_frontends
from mlops_tpu.serve.ipc import RequestRing, RingService
from mlops_tpu.tenancy import (
    QuotaGovernor,
    TenancyConfig,
    TenancyConfigError,
    TenantRouter,
    TenantSpec,
    UNKNOWN_TENANT_LABEL,
    load_tenants_toml,
    single_tenant_config,
)

# ------------------------------------------------------------ unit: config
def _spec(name, bundle_dir="b", weight=1.0):
    return TenantSpec(name=name, bundle_dir=bundle_dir, weight=weight)


def test_tenancy_config_validate_names_every_problem():
    with pytest.raises(TenancyConfigError, match="at least one"):
        TenancyConfig().validate(check_bundles=False)
    with pytest.raises(TenancyConfigError, match="duplicate tenant name"):
        TenancyConfig(
            tenants=(_spec("emea"), _spec("emea"))
        ).validate(check_bundles=False)
    with pytest.raises(TenancyConfigError, match="weight=0.0"):
        TenancyConfig(
            tenants=(_spec("emea", weight=0.0),)
        ).validate(check_bundles=False)
    with pytest.raises(TenancyConfigError, match="no bundle_dir"):
        TenancyConfig(
            tenants=(_spec("emea", bundle_dir=""),)
        ).validate(check_bundles=False)
    with pytest.raises(TenancyConfigError, match="is not a directory"):
        TenancyConfig(
            tenants=(_spec("emea", bundle_dir="/definitely/not/here"),)
        ).validate(check_bundles=True)
    with pytest.raises(TenancyConfigError, match="Prometheus label"):
        TenancyConfig(
            tenants=(_spec('bad"name{}'),)
        ).validate(check_bundles=False)
    with pytest.raises(TenancyConfigError, match="names no"):
        TenancyConfig(
            tenants=(_spec("emea"),), default_tenant="apac"
        ).validate(check_bundles=False)
    # every problem in ONE error, not just the first
    with pytest.raises(TenancyConfigError) as err:
        TenancyConfig(
            tenants=(_spec("a", weight=-1.0), _spec("a")),
            default_tenant="zz",
        ).validate(check_bundles=False)
    text = str(err.value)
    assert "weight=-1.0" in text
    assert "duplicate" in text
    assert "names no" in text


def test_tenants_toml_round_trip_and_shape_errors(tmp_path):
    path = tmp_path / "tenants.toml"
    path.write_text(
        'default_tenant = "apac"\n'
        "[[tenant]]\n"
        'name = "emea"\n'
        'bundle_dir = "reg/emea/3"\n'
        "weight = 2.0\n"
        "[[tenant]]\n"
        'name = "apac"\n'
        'bundle_dir = "reg/apac/1"\n'
    )
    fleet = load_tenants_toml(path)
    assert fleet.names == ("emea", "apac")
    assert fleet.weights == (2.0, 1.0)
    assert fleet.default_tenant == "apac"
    assert fleet.default_index == 1
    fleet.validate(check_bundles=False)

    path.write_text("[[tenant]]\nname = 'x'\nbundel_dir = 'typo'\n")
    with pytest.raises(TenancyConfigError, match="unknown keys"):
        load_tenants_toml(path)
    # A misspelled TOP-LEVEL key is named too: `default-tenant` would
    # otherwise parse cleanly, fall back to the first tenant, and
    # silently misroute all untagged traffic.
    path.write_text(
        '"default-tenant" = "apac"\n[[tenant]]\nname = "x"\n'
        'bundle_dir = "reg/x/1"\n'
    )
    with pytest.raises(TenancyConfigError, match="unknown top-level keys"):
        load_tenants_toml(path)
    path.write_text("tenant = 3\n")
    with pytest.raises(TenancyConfigError, match="array of tables"):
        load_tenants_toml(path)
    path.write_text("not [valid toml\n")
    with pytest.raises(TenancyConfigError, match="not valid TOML"):
        load_tenants_toml(path)
    with pytest.raises(TenancyConfigError, match="cannot read"):
        load_tenants_toml(tmp_path / "missing.toml")


def test_single_tenant_config_is_the_default_fleet(tmp_path):
    fleet = single_tenant_config(str(tmp_path))
    fleet.validate(check_bundles=True)
    assert fleet.names == ("default",)
    assert fleet.default_index == 0
    assert fleet.weights == (1.0,)


# ------------------------------------------------------------- unit: quota
def test_quota_floors_are_fractional_and_sum_to_capacity():
    gov = QuotaGovernor(10, (1.0, 3.0))
    assert gov.floors == (2.5, 7.5)
    assert sum(gov.floors) == pytest.approx(10.0)
    with pytest.raises(ValueError, match="capacity"):
        QuotaGovernor(0, (1.0,))
    with pytest.raises(ValueError, match="weights"):
        QuotaGovernor(4, (1.0, 0.0))


def test_quota_hot_tenant_sheds_against_its_own_share():
    """Weighted max-min with reserved floors: a flood from one tenant
    occupies at most C - sum(other floors), every rejection past that is
    the 'quota' verdict (counted per tenant), and the cold tenant's
    floor admits its whole reservation afterwards."""
    gov = QuotaGovernor(10, (1.0, 1.0))
    verdicts = [gov.try_acquire(0) for _ in range(10)]
    # floor admits 5 (used < 5.0 for used in 0..4); the borrow path is
    # blocked by the cold tenant's fully-unmet 5.0 reservation.
    assert verdicts.count("ok") == 5
    assert verdicts.count("quota") == 5
    # The starvation guarantee: the cold tenant's first request (and its
    # whole floor) always succeeds while the hot tenant floods.
    cold = [gov.try_acquire(1) for _ in range(5)]
    assert cold == ["ok"] * 5
    # Now the pool is physically exhausted: NOT a quota event.
    assert gov.try_acquire(1) == "full"
    assert gov.try_acquire(0) == "full"


def test_quota_reservations_rearm_on_release():
    gov = QuotaGovernor(8, (1.0, 3.0))  # floors 2.0 / 6.0
    # The light tenant is capped at its floor while the heavy tenant's
    # 6.0 reservation is unmet.
    assert [gov.try_acquire(0) for _ in range(3)] == ["ok", "ok", "quota"]
    # The heavy tenant's whole floor admits.
    assert [gov.try_acquire(1) for _ in range(6)] == ["ok"] * 6
    assert gov.try_acquire(0) == "full"
    # A release that drops the heavy tenant below its floor RE-ARMS its
    # reservation: the light tenant still cannot take that capacity (the
    # guarantee is stateless per admission — a cold tenant's floor is
    # reachable at every instant, not only before its first burst).
    gov.release(1)
    assert gov.try_acquire(0) == "quota"
    assert gov.try_acquire(1) == "ok"  # the floor's owner reclaims it
    assert gov.used == [2, 6]


def test_quota_release_clamps_at_zero():
    gov = QuotaGovernor(4, (1.0,))
    gov.release(0)  # release bug: must clamp, never go negative
    assert gov.used == [0]
    assert gov.try_acquire(0) == "ok"
    gov.release(0)
    gov.release(0)
    assert gov.used == [0]


def test_quota_fractional_floors_cannot_be_flooded_away():
    """capacity=8, five equal tenants -> fractional floors 1.6, integer
    reservations 1. Four flooders must NOT be able to fill the pool by
    each overshooting to 2 via a floor fast-path: every admission holds
    back every other tenant's unmet integer floor, so the cold fifth
    tenant's slot is claimable at every instant of the flood."""
    gov = QuotaGovernor(8, (1.0,) * 5)
    for flooder in range(4):
        while gov.try_acquire(flooder) == "ok":
            pass
    # The flood saturated everything EXCEPT the cold tenant's integer
    # reservation.
    assert gov.total_used == 7
    assert gov.try_acquire(4) == "ok"  # the cold tenant's held-back slot
    # Tiny pools never deadlock: one slab, two tenants (integer floors
    # 0) — the first comer takes it, the other waits on "full", and a
    # release hands it over.
    one = QuotaGovernor(1, (1.0, 1.0))
    assert one.try_acquire(0) == "ok"
    assert one.try_acquire(1) == "full"
    one.release(0)
    assert one.try_acquire(1) == "ok"


def test_claim_overflow_gated_on_multi_tenant_planes():
    """The per-class governors admit against the class the ROW COUNT
    names, so a multi-tenant claim may not cross classes: a small
    request overflowing into a large slab would hold capacity the
    large-class governor never accounted (hot tenant starves cold large
    floors with no quota signal). The 1-tenant plane keeps the
    opportunistic overflow (allow_overflow default)."""
    from mlops_tpu.serve.ipc import RequestRing, RingClient

    ring = RequestRing(
        workers=1, slots_small=1, slots_large=1, large_rows=8,
        tenant_names=("emea", "apac"),
    )
    try:
        client = RingClient(ring, 0)
        first = client.claim(1, tenant=0, allow_overflow=False)
        assert first is not None
        assert ring.slot_class(first) == 0  # the small slab
        # Small class exhausted: a governed claim must NOT take the
        # large slab...
        assert client.claim(1, tenant=0, allow_overflow=False) is None
        # ...while the 1-tenant overflow still may, and a large request
        # can always reach the slab a governed small request left free.
        overflow = client.claim(1, tenant=0)
        assert overflow is not None
        assert ring.slot_class(overflow) == 1  # the large slab
    finally:
        ring.close()


# ------------------------------------------------------------ unit: router
def test_router_resolves_default_known_and_unknown():
    router = TenantRouter(("emea", "apac"), default_index=1)
    assert router.resolve("") == 1  # untagged -> declared default
    assert router.resolve("emea") == 0
    assert router.resolve("apac") == 1
    assert router.resolve("latam") is None  # unknown -> caller 404s
    assert router.label("") == "apac"
    assert router.label("emea") == "emea"
    # Arbitrary header text never becomes a label value (bounded set).
    assert router.label('inject",x="y') == UNKNOWN_TENANT_LABEL
    empty = TenantRouter(())
    assert empty.names == ("default",)
    assert empty.resolve("") == 0


def test_tenancy_modules_declare_lock_free_manifests():
    """The ISSUE's concurrency contract: router/registry/quota are
    single-owner or immutable state with NO locks — declared, so the
    static layer and the runtime sanitizer both check the claim."""
    from mlops_tpu.tenancy import quota, registry, router

    assert quota.TPULINT_LOCK_ORDER == {"QuotaGovernor": ()}
    assert router.TPULINT_LOCK_ORDER == {"TenantRouter": ()}
    assert registry.TPULINT_LOCK_ORDER == {"TenantRegistry": ()}


# ------------------------------------------------------------ fleet fixture
@pytest.fixture(scope="module")
def fleet(tiny_pipeline, tmp_path_factory):
    """Three tenant bundles, two distinct architectures:

    - ``emea``: the shared tiny_pipeline bundle (mlp 32x32);
    - ``apac``: a param-perturbed COPY of emea's bundle — identical
      architecture (the executable-sharing twin), different params, so
      its responses must differ from emea's;
    - ``latam``: a freshly trained mlp 16 — a different architecture
      that must get its own compiled entries.
    """
    import jax
    import jax.numpy as jnp

    from mlops_tpu.bundle import load_bundle, save_bundle
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    _, result = tiny_pipeline
    root = tmp_path_factory.mktemp("tenants")

    base = load_bundle(result.bundle_dir)
    # save_bundle serializes the INNER "params" subtree (the same
    # contract run_training uses); load_bundle rewraps it.
    perturbed = jax.tree_util.tree_map(
        lambda x: (
            x * 1.01
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x
        ),
        base.variables["params"],
    )
    apac_dir = save_bundle(
        root / "apac",
        base.model_config,
        perturbed,
        base.preprocessor,
        base.monitor,
        calibration=dict(base.manifest.get("calibration", {})),
    )

    config = Config()
    config.data.rows = 2500
    config.model = ModelConfig(family="mlp", hidden_dims=(16,), embed_dim=4)
    config.train = TrainConfig(steps=60, eval_every=60, batch_size=256)
    config.registry.root = str(root / "latam-registry")
    config.registry.run_root = str(root / "latam-runs")
    latam = run_training(config)

    return TenancyConfig(
        tenants=(
            TenantSpec("emea", str(result.bundle_dir), weight=2.0),
            TenantSpec("apac", str(apac_dir), weight=1.0),
            TenantSpec("latam", str(latam.bundle_dir), weight=1.0),
        ),
        default_tenant="emea",
    )


@pytest.fixture(scope="module")
def registry(fleet):
    from mlops_tpu.tenancy import TenantRegistry

    reg = TenantRegistry(fleet, buckets=(1, 8, 64))
    reg.warmup()
    return reg


@pytest.fixture(scope="module")
def prep_paths(fleet):
    paths = [
        str(Path(spec.bundle_dir) / "preprocess.npz")
        for spec in fleet.tenants
    ]
    for path in paths:
        assert Path(path).is_file(), path
    return paths


# --------------------------------------------------------------- harnesses
@contextlib.contextmanager
def multi_tenant_plane(
    engines,
    prep_paths,
    tenancy,
    workers=2,
    slots_small=8,
    slots_large=2,
    service_kwargs=None,
    **cfg_kwargs,
):
    """The production multi-tenant topology with the engine half hosted in
    this process (what `serve_multi_worker` builds from a tenants.toml,
    minus the bundle loads): forked SO_REUSEPORT front ends with the
    tenant router + per-worker quota governors, a tenant-dimensioned
    ring, and one RingService dispatching against N engines."""
    import os
    import signal

    cfg_kwargs.setdefault("max_batch", 64)
    cfg = ServeConfig(
        host="127.0.0.1",
        port=0,
        workers=workers,
        ring_slots_small=slots_small,
        ring_slots_large=slots_large,
        **cfg_kwargs,
    ).validate()
    ring = RequestRing(
        workers=workers,
        slots_small=slots_small,
        slots_large=slots_large,
        large_rows=cfg.max_batch,
        tenant_names=tenancy.names,
    )
    placeholder = reuseport_socket(cfg.host, cfg.port)
    child_cfg = dataclasses.replace(cfg, port=placeholder.getsockname()[1])
    procs = start_frontends(child_cfg, ring, list(prep_paths), None, tenancy)
    service = RingService(
        engines[0],
        ring,
        max_group=cfg.max_group,
        max_inflight=cfg.max_inflight,
        threads=cfg.max_workers,
        engines=list(engines),
        **(service_kwargs or {}),
    )
    service.start()
    ring.set_ready(True)
    _wait_accepting(child_cfg.port)
    try:
        yield child_cfg.port, ring, procs, service
    finally:
        ring.set_draining()
        ring.set_ready(False)
        for proc in procs:
            if proc.is_alive() and proc.pid:
                with contextlib.suppress(ProcessLookupError):
                    os.kill(proc.pid, signal.SIGTERM)
        for proc in procs:
            proc.join(timeout=15)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5)
        service.stop()
        placeholder.close()
        ring.close()


@contextlib.contextmanager
def registry_server(registry, **cfg_kwargs):
    """The single-process plane over a tenant fleet: HttpServer with the
    registry installed (what `_serve` builds from serve.tenants_path)."""
    import asyncio

    from mlops_tpu.serve.server import HttpServer

    cfg_kwargs.setdefault("max_batch", 64)
    holder: dict = {}
    started = threading.Event()

    async def main():
        server = HttpServer(
            registry.default_engine,
            ServeConfig(host="127.0.0.1", port=0, **cfg_kwargs),
            registry=registry,
        )
        srv = await server.start()
        holder["port"] = srv.sockets[0].getsockname()[1]
        holder["stop"] = asyncio.Event()
        holder["loop"] = asyncio.get_running_loop()
        started.set()
        await holder["stop"].wait()
        srv.close()
        server.stop_telemetry()
        await srv.wait_closed()

    thread = threading.Thread(target=lambda: asyncio.run(main()), daemon=True)
    thread.start()
    assert started.wait(15), "registry server did not start"
    try:
        yield holder["port"]
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        thread.join(timeout=10)


def _wait_accepting(port, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.05)
    raise TimeoutError(f"no front end accepting on :{port}")


def _recv_response(sock_file):
    status_line = sock_file.readline()
    if not status_line:
        return None
    status = int(status_line.split(b" ")[1])
    headers = {}
    while True:
        line = sock_file.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode("latin1").partition(":")
        headers[name.strip().lower()] = value.strip()
    body = sock_file.read(int(headers.get("content-length", 0)))
    return status, headers, body


def http_exchange(port, method, path, body=None, headers=None):
    data = b"" if body is None else json.dumps(body).encode()
    head = [f"{method} {path} HTTP/1.1", "host: t",
            f"content-length: {len(data)}"]
    for name, value in (headers or {}).items():
        head.append(f"{name}: {value}")
    head.append("connection: close")
    raw = ("\r\n".join(head) + "\r\n\r\n").encode() + data
    with socket.create_connection(("127.0.0.1", port), timeout=60) as sock:
        sock.sendall(raw)
        with sock.makefile("rb") as f:
            return _recv_response(f)


def predict(port, records, tenant=None):
    headers = {"x-tenant": tenant} if tenant else None
    status, resp_headers, body = http_exchange(
        port, "POST", "/predict", records, headers
    )
    return status, resp_headers, (json.loads(body) if body else None)


# ------------------------------------------------------- executable sharing
def test_registry_shares_executables_across_architecture_twins(registry):
    """emea/apac (identical architecture, different params) must share
    ONE exec table + compile lock; latam (different architecture) must
    not. Params-as-args is what makes the sharing sound — proven by the
    parity tests below, where the twins' responses differ."""
    emea, apac, latam = registry.engines
    assert registry.shared_exec_count == 1
    assert apac._exec is emea._exec
    assert apac._compile_lock is emea._compile_lock
    assert apac.warmup_stats["mode"] == "shared"
    assert latam._exec is not emea._exec
    assert latam._compile_lock is not emea._compile_lock
    assert registry.ready
    assert len(registry) == 3
    assert registry.names == ("emea", "apac", "latam")
    # The twins serve DIFFERENT portfolios through the shared programs.
    import jax

    assert not np.allclose(
        np.asarray(jax.tree_util.tree_leaves(emea._variables)[0]),
        np.asarray(jax.tree_util.tree_leaves(apac._variables)[0]),
    )


def test_adopt_executables_rejects_unwarmed_donor(registry):
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    bundle = load_bundle(registry.tenancy.tenants[0].bundle_dir)
    cold_donor = InferenceEngine(bundle, buckets=(1,))
    adopter = InferenceEngine(bundle, buckets=(1,))
    with pytest.raises(ValueError, match="not warmed"):
        adopter.adopt_executables(cold_donor)


# ----------------------------------------------------------- parity: planes
def test_per_tenant_parity_single_process_plane(
    registry, fleet, sample_request
):
    """Every tenant's plane response is byte-identical to ITS engine's
    solo answer; untagged traffic rides the declared default; an unknown
    tenant answers 404 before any scoring work."""
    sizes = [1, 8, 20]
    with registry_server(registry) as port:
        for name, engine in zip(registry.names, registry.engines):
            for n in sizes:
                records = sample_request * n
                status, _, got = predict(port, records, tenant=name)
                assert status == 200, got
                solo = engine.predict_records(records)
                assert got == json.loads(json.dumps(solo)), (name, n)
        # Untagged -> default tenant (emea).
        status, _, untagged = predict(port, sample_request)
        assert status == 200
        assert untagged == json.loads(
            json.dumps(registry.default_engine.predict_records(sample_request))
        )
        # The twins are genuinely different portfolios.
        emea = predict(port, sample_request, tenant="emea")[2]
        apac = predict(port, sample_request, tenant="apac")[2]
        assert emea["predictions"] != apac["predictions"]
        # Unknown tenant: 404 before any scoring work — never the
        # default tenant's quota or monitors.
        status, _, payload = predict(port, sample_request, tenant="nosuch")
        assert status == 404
        assert "unknown tenant" in payload["detail"]
        # /metrics: header text never becomes a label. The stranger's
        # 404 REQUEST COUNT bills the default tenant's row on BOTH
        # planes (the ring's shm counters have one fixed row per
        # declared tenant, and the series must stay bit-compatible
        # across planes); spans keep the distinct `<unknown>` marker.
        status, _, body = http_exchange(port, "GET", "/metrics")
        text = body.decode()
        assert status == 200
        for name in registry.names:
            assert (
                f'mlops_tpu_requests_total{{route="/predict",status="200",'
                f'tenant="{name}"}}' in text
            )
        assert (
            'mlops_tpu_requests_total{route="/predict",status="404",'
            'tenant="emea"}' in text
        )
        assert f'tenant="{UNKNOWN_TENANT_LABEL}"' not in text
        assert 'tenant="nosuch"' not in text


def test_per_tenant_parity_ring_plane(
    registry, fleet, prep_paths, sample_request
):
    """The multi-worker plane: 3 tenants on 2 forked workers, per-tenant
    bit-identity vs solo, tenant-labeled ring metrics, 404 contract."""
    with multi_tenant_plane(
        registry.engines, prep_paths, fleet, workers=2, slots_small=16
    ) as (port, ring, _, _svc):
        for name, engine in zip(registry.names, registry.engines):
            for n in (1, 8):
                records = sample_request * n
                status, _, got = predict(port, records, tenant=name)
                assert status == 200, got
                solo = engine.predict_records(records)
                assert got == json.loads(json.dumps(solo)), (name, n)
        status, _, untagged = predict(port, sample_request)
        assert status == 200
        assert untagged == json.loads(
            json.dumps(registry.default_engine.predict_records(sample_request))
        )
        status, _, payload = predict(port, sample_request, tenant="nosuch")
        assert status == 404
        assert "unknown tenant" in payload["detail"]
        status, _, body = http_exchange(port, "GET", "/metrics")
        text = body.decode()
        assert status == 200
        for name in registry.names:
            assert f'tenant="{name}"' in text
            assert (
                f'mlops_tpu_tenant_quota_shed_total{{worker="0",'
                f'tenant="{name}"}}' in text
            )
        for worker in (0, 1):
            assert (
                f'mlops_tpu_ring_depth{{worker="{worker}",class="small",'
                'tenant="emea"}' in text
            )


# ---------------------------------------------------- quota contract (ring)
class _SlowStubEngine:
    """Engine-API stub with controllable latency and a per-stub constant
    prediction — jax-free, deterministic: the constant proves WHICH
    tenant's engine served a slot, the latency holds slots in flight."""

    ready = True
    max_bucket = 64
    supports_grouping = False
    monitor_accumulating = False

    class _Handle:
        def __init__(self, n):
            self.n = n

        def start_copy(self):
            pass

    def __init__(self, delay_s: float, value: float):
        self.delay_s = delay_s
        self.value = value

    def dispatch_arrays(self, cat, num):
        return self._Handle(cat.shape[0])

    def fetch_arrays_raw(self, handle):
        time.sleep(self.delay_s)
        n = handle.n
        return (
            np.full(n, self.value, float),
            np.zeros(n, float),
            np.zeros(23, float),
        )


def test_quota_shed_503_contract_per_tenant(prep_paths):
    """Hot tenant floods the SMALL class (4 slots, weights 1:1, floor
    2.0 — the governor is per slot class, so the lone large slab's
    capacity never pads the small-class floors): exactly 2 admitted,
    the rest shed 503 naming the tenant's own quota with Retry-After —
    while the COLD tenant's floor admits its request to the right
    engine. The fairness observable lands per tenant in
    mlops_tpu_tenant_quota_shed_total, and quota sheds do NOT count
    into the physical mlops_tpu_shed_total."""
    fleet = TenancyConfig(
        tenants=(_spec("hot", "x"), _spec("cold", "x")),
        default_tenant="hot",
    )
    hot_stub = _SlowStubEngine(delay_s=1.0, value=0.25)
    cold_stub = _SlowStubEngine(delay_s=0.1, value=0.75)
    with multi_tenant_plane(
        [hot_stub, cold_stub],
        [prep_paths[0], prep_paths[0]],
        fleet,
        workers=1,
        slots_small=4,
        slots_large=1,
    ) as (port, ring, _, _svc):
        results = []
        lock = threading.Lock()

        def hot_call():
            r = predict(port, [{}], tenant="hot")
            with lock:
                results.append(r)

        threads = [threading.Thread(target=hot_call) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # hot admissions in flight (1.0s dispatch)
        # The cold tenant's floor is reachable DURING the flood, and its
        # answer comes from the cold engine (value pins the tenant).
        status, _, cold_payload = predict(port, [{}], tenant="cold")
        assert status == 200, cold_payload
        assert cold_payload["predictions"] == [0.75]
        for t in threads:
            t.join(timeout=30)
        statuses = [s for s, _, _ in results]
        assert statuses.count(200) == 2, statuses
        sheds = [r for r in results if r[0] == 503]
        assert len(sheds) == 6, statuses
        for status, headers, payload in sheds:
            assert headers.get("retry-after") == "1"
            assert "'hot' over quota" in payload["detail"]
        for _, _, payload in results:
            if isinstance(payload, dict) and payload.get("predictions"):
                assert payload["predictions"] == [0.25]
        assert int(ring.quota_shed[0, 0]) == 6
        assert int(ring.quota_shed[0, 1]) == 0
        # Quota rejections are NOT physical sheds: the slot-exhaustion
        # counter stays untouched by the whole flood (the counters are
        # disjoint so operators can difference them).
        assert int(ring.shed.sum()) == 0
        status, _, body = http_exchange(port, "GET", "/metrics")
        text = body.decode()
        assert (
            'mlops_tpu_tenant_quota_shed_total{worker="0",tenant="hot"} 6'
            in text
        )
        assert (
            'mlops_tpu_tenant_quota_shed_total{worker="0",tenant="cold"} 0'
            in text
        )


@pytest.mark.slow  # 10x-load timing measurement: CI's parallel job runs it
def test_hot_tenant_at_10x_cannot_starve_cold_tenant(
    registry, prep_paths, sample_request
):
    """The ISSUE acceptance: hot tenant at 10x load, the cold tenant's
    p99 stays within 2x its solo p99 AND it never sheds (its weighted
    max-min floor keeps slots reachable through the flood)."""
    fleet = TenancyConfig(
        tenants=(
            _spec("hot", registry.tenancy.tenants[0].bundle_dir),
            _spec("cold", registry.tenancy.tenants[1].bundle_dir),
        ),
        default_tenant="hot",
    )
    engines = [registry.engines[0], registry.engines[1]]

    def cold_pass(port, n=80):
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            status, _, payload = predict(port, sample_request, tenant="cold")
            lat.append(time.perf_counter() - t0)
            assert status == 200, payload
        return float(np.percentile(np.asarray(lat), 99))

    with multi_tenant_plane(
        engines, prep_paths[:2], fleet, workers=1, slots_small=8,
        slots_large=2,
    ) as (port, ring, _, _svc):
        solo_p99 = cold_pass(port)
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                with contextlib.suppress(OSError):
                    predict(port, sample_request, tenant="hot")

        hammers = [threading.Thread(target=hammer) for _ in range(10)]
        for t in hammers:
            t.start()
        try:
            time.sleep(0.5)  # the flood is established
            hot_p99 = cold_pass(port)
        finally:
            stop.set()
            for t in hammers:
                t.join(timeout=30)
        assert int(ring.quota_shed[0, 1]) == 0, "cold tenant was quota-shed"
        assert hot_p99 <= max(2.0 * solo_p99, solo_p99 + 0.025), (
            f"cold p99 {hot_p99 * 1e3:.1f}ms vs solo "
            f"{solo_p99 * 1e3:.1f}ms under 10x hot load"
        )


# ------------------------------------------------------- kill -9 per tenant
def test_engine_kill9_replay_lands_under_correct_tenant(
    registry, sample_request
):
    """A busy slot a dead engine popped-but-never-answered must be
    replayed UNDER ITS SHM-TAGGED TENANT: the replayed answer is the
    tagged tenant's engine's bit-identical solo answer (the twins'
    params differ, so a wrong-tenant replay would produce different
    bytes), and each tenant's seeded monitor totals stay monotone."""
    import asyncio

    from mlops_tpu.schema import records_to_columns
    from mlops_tpu.serve.ipc import RingClient
    from mlops_tpu.serve.wire import RESP_OK, format_response

    emea, apac = registry.engines[0], registry.engines[1]
    expected_apac = apac.predict_records(sample_request)
    expected_emea = emea.predict_records(sample_request)
    assert expected_apac != expected_emea  # the tenant tag is decisive

    async def scenario():
        ring = RequestRing(
            workers=1, slots_small=2, slots_large=1, large_rows=8,
            tenant_names=("emea", "apac"),
        )
        try:
            client = RingClient(ring, 0)
            ds = emea.bundle.preprocessor.encode(
                records_to_columns(sample_request)
            )
            # The dead incarnation's per-tenant telemetry snapshot: the
            # respawn must seed EACH tenant's totals from its own row.
            snap_emea = dict(emea.monitor_snapshot())
            snap_apac = dict(apac.monitor_snapshot())
            ring.write_monitor(snap_emea, 0)
            ring.write_monitor(snap_apac, 1)
            slot = client.claim(len(sample_request), tenant=1)
            assert int(ring.slot_tenant[slot]) == 1
            future = client.submit(slot, ds.cat_ids, ds.numeric)
            popped = ring.pop_submissions()
            assert [s for s, _ in popped] == [slot]
            service = RingService(
                emea, ring, max_inflight=2, threads=2,
                engines=[emea, apac],
            )
            try:
                stats = service.reattach()
            finally:
                service.stop()
            assert stats["replayed_slots"] == 1
            client.on_doorbell()
            assert future.done() and int(future.result()) == RESP_OK
            pred, out, drift = client.response_arrays(slot)
            got = format_response(
                np.array(pred), np.array(out), np.array(drift)
            )
            client.release(slot)
            # Replay landed on APAC's bundle, bit-identically.
            assert got == json.loads(json.dumps(expected_apac))
            assert got != json.loads(json.dumps(expected_emea))
            # Per-tenant monitor totals are monotone across the respawn:
            # each engine's totals continue from its own seeded row (the
            # replayed request re-folded into apac's accumulator only).
            after_emea = emea.monitor_snapshot()
            after_apac = apac.monitor_snapshot()
            assert after_emea["rows"] == snap_emea["rows"]
            assert (
                after_apac["rows"]
                == snap_apac["rows"] + len(sample_request)
            )
        finally:
            ring.close()

    asyncio.run(scenario())


# ----------------------------------------------------------- lock sanitizer
@pytest.mark.parametrize("seed", [0, 1])
def test_multi_tenant_lock_discipline_under_perturbed_schedules(
    registry, fleet, prep_paths, sample_request, seed
):
    """The runtime lock sanitizer over the ring service + a SHARED-exec
    tenant pair with seeded schedule perturbation: zero order violations
    and per-tenant responses stay bit-identical under concurrency (the
    shared compile lock + per-tenant state refs hold up)."""
    from mlops_tpu.analysis.lockcheck import instrument_locks

    expected = {
        name: engine.predict_records(sample_request)
        for name, engine in zip(registry.names, registry.engines)
    }
    with multi_tenant_plane(
        registry.engines, prep_paths, fleet, workers=2, slots_small=16
    ) as (port, ring, _, service):
        with instrument_locks(service, perturb_seed=seed) as san_service, \
                instrument_locks(ring) as san_ring, \
                instrument_locks(
                    registry.engines[0], perturb_seed=seed
                ) as san_emea, \
                instrument_locks(registry.engines[2]) as san_latam:
            results = []
            lock = threading.Lock()

            def call(name):
                r = predict(port, sample_request, tenant=name)
                with lock:
                    results.append((name, r))

            threads = [
                threading.Thread(
                    target=call, args=(registry.names[i % 3],)
                )
                for i in range(12)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        for sanitizer in (san_service, san_ring, san_emea, san_latam):
            assert not sanitizer.violations, [
                str(v) for v in sanitizer.violations
            ]
        assert san_service.acquired, "service locks never exercised"
    assert len(results) == 12
    for name, (status, _, payload) in results:
        assert status == 200
        assert payload == json.loads(json.dumps(expected[name])), name


# ------------------------------------------------------ trace-report filter
def test_trace_report_tenant_filter(tmp_path, capsys):
    from mlops_tpu.commands import _trace_report
    from mlops_tpu.config import Config
    from mlops_tpu.trace import Span, TraceRecorder

    recorder = TraceRecorder(tmp_path / "spans.jsonl")
    for i in range(6):
        span = Span(f"r{i}", tenant="emea" if i % 3 else "apac")
        span.stamp("admission")
        span.stamp("respond")
        recorder.record(span.finish(200))
    recorder.close()
    config = Config()
    config.trace.dir = str(tmp_path)
    assert _trace_report(config) == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])[
        "spans"
    ] == 6
    config.trace.tenant = "apac"
    assert _trace_report(config) == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])[
        "spans"
    ] == 2
    # Tenant with no spans: the empty-report exit (2), still parseable.
    config.trace.tenant = "latam"
    assert _trace_report(config) == 2


# ----------------------------------------------------- bench key contract
@pytest.mark.slow
def test_bench_tenancy_stage_key_contract(registry, sample_request):
    """The CI contract for the tenancy bench keys: shared-exec count,
    per-tenant goodput under a 10x hot flood, and the starvation ratio
    — asserted against the real stage function over a warmed engine."""
    import bench

    engine = registry.engines[0]
    out = bench._tenancy_stage(engine, engine.bundle, sample_request[0])
    assert out["tenants_shared_exec_count"] == 1
    assert out["tenant_req_per_s_hot"] > 0
    assert out["tenant_req_per_s_cold"] > 0
    assert out["tenant_cold_solo_p99_ms"] > 0
    assert out["tenant_cold_contended_p99_ms"] > 0
    assert out["starvation_cold_p99_ratio"] > 0
    assert out["tenant_quota_shed_hot"] >= 0


def test_serve_cli_tenants_flag_maps_to_config():
    from mlops_tpu.cli import build_parser

    args = build_parser().parse_args(["serve", "--tenants", "t.toml"])
    assert args.tenants == "t.toml"
    args = build_parser().parse_args(
        ["trace-report", "--tenant", "emea"]
    )
    assert args.tenant == "emea"
