"""Sharded bulk scoring (BASELINE config 4) on the fake 8-device mesh."""

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.parallel import make_mesh
from mlops_tpu.parallel.bulk import score_dataset


@pytest.fixture(scope="module")
def flax_bundle(tiny_pipeline):
    _, result = tiny_pipeline
    return load_bundle(result.bundle_dir)


@pytest.fixture(scope="module")
def score_ds(flax_bundle):
    from mlops_tpu.data import generate_synthetic

    columns, _ = generate_synthetic(10_000, seed=99)
    return flax_bundle.preprocessor.encode(columns)


def test_sharded_matches_unsharded(flax_bundle, score_ds):
    """8-way data-parallel scoring must agree with the single-device path —
    the mesh changes layout, not math."""
    local = score_dataset(flax_bundle, score_ds, mesh=None, chunk_rows=4096)
    sharded = score_dataset(
        flax_bundle, score_ds, mesh=make_mesh(8), chunk_rows=4096
    )
    np.testing.assert_allclose(
        local.predictions, sharded.predictions, rtol=2e-2, atol=2e-3
    )
    np.testing.assert_array_equal(local.outliers, sharded.outliers)
    assert sharded.rows == 10_000
    assert sharded.rows_per_s > 0


def test_tail_chunk_padding_exact(flax_bundle, score_ds):
    """A chunk size that doesn't divide N exercises the padded tail; padded
    rows must not leak into outputs."""
    a = score_dataset(flax_bundle, score_ds, mesh=make_mesh(8), chunk_rows=4096)
    b = score_dataset(flax_bundle, score_ds, mesh=make_mesh(8), chunk_rows=2048)
    np.testing.assert_allclose(a.predictions, b.predictions, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(a.outliers, b.outliers)


def test_bulk_matches_serving_engine(flax_bundle, score_ds):
    """Bulk predictions agree with the serving engine's fused path on the
    same rows (one model, two execution surfaces)."""
    from mlops_tpu.serve import InferenceEngine

    take = 256
    engine = InferenceEngine(
        flax_bundle, buckets=(take,), enable_grouping=False
    )
    served = engine.predict_arrays(
        score_ds.cat_ids[:take], score_ds.numeric[:take]
    )
    bulk = score_dataset(
        flax_bundle, score_ds.slice(np.arange(take)), chunk_rows=take
    )
    np.testing.assert_allclose(
        np.asarray(served["predictions"], np.float32),
        bulk.predictions,
        rtol=1e-4,
        atol=1e-5,
    )


def test_bulk_empty_dataset(flax_bundle, score_ds):
    import json

    empty = score_dataset(flax_bundle, score_ds.slice(np.arange(0)))
    assert empty.rows == 0
    summary = empty.summary()
    json.dumps(summary)  # no NaN leaks into the JSON contract
    assert summary["default_rate"] == 0.0
    assert set(summary["feature_drift_batch"]) and all(
        v == 0.0 for v in summary["feature_drift_batch"].values()
    )


def test_bulk_sklearn_flavor(score_ds, encoded_small, tmp_path):
    from mlops_tpu.bundle import save_bundle
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.models.gbm import SklearnBaseline
    from mlops_tpu.monitor import fit_monitor

    config = Config()
    model_config = ModelConfig(family="gbm", n_estimators=20, max_tree_depth=3)
    _, ds = encoded_small
    baseline = SklearnBaseline.train(model_config, TrainConfig(), ds)
    monitor = fit_monitor(ds, config.monitor, seed=0)
    prep, _ = encoded_small
    save_bundle(tmp_path / "b", model_config, baseline, prep, monitor)
    bundle = load_bundle(tmp_path / "b")

    result = score_dataset(bundle, score_ds, chunk_rows=4096)
    assert result.predictions.shape == (10_000,)
    assert ((result.predictions >= 0) & (result.predictions <= 1)).all()
    direct = baseline.predict_proba(score_ds.cat_ids, score_ds.numeric)
    np.testing.assert_allclose(result.predictions, direct, rtol=1e-6)
