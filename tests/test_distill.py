"""Ensemble -> bulk-student distillation (train/distill.py) and the
CPU-backend bulk routing it enables (parallel/bulk.py use_distilled_bulk).

Addresses the measured gap: the 8-member flagship's bulk throughput loses
~9x to the reference's sklearn GBM floor on CPU (BASELINE.md config 1);
the distilled student buys it back while the fidelity record keeps the
substitution auditable.
"""

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.config import Config, ModelConfig, TrainConfig
from mlops_tpu.data import generate_synthetic
from mlops_tpu.parallel.bulk import score_dataset, use_distilled_bulk
from mlops_tpu.train.pipeline import run_training


@pytest.fixture(scope="module")
def ensemble_bundle_dir(tmp_path_factory):
    """A small 4-member ensemble trained through the real pipeline, which
    packages the distilled bulk student alongside."""
    root = tmp_path_factory.mktemp("distill")
    config = Config()
    config.data.rows = 4000
    config.model = ModelConfig(
        family="mlp", hidden_dims=(32, 32), embed_dim=4, ensemble_size=4
    )
    config.train = TrainConfig(steps=150, eval_every=150, batch_size=256)
    config.registry.root = str(root / "registry")
    config.registry.run_root = str(root / "runs")
    result = run_training(config, register=False)
    return result.bundle_dir


@pytest.fixture(scope="module")
def ensemble_bundle(ensemble_bundle_dir):
    return load_bundle(ensemble_bundle_dir)


def test_bundle_carries_bulk_student(ensemble_bundle):
    assert ensemble_bundle.has_bulk
    assert ensemble_bundle.bulk_variables is not None
    manifest = ensemble_bundle.manifest["bulk"]
    assert manifest["model_config"]["ensemble_size"] == 1
    fidelity = ensemble_bundle.bulk_fidelity
    assert 0.0 <= fidelity["mean_abs_prob_delta"] <= 0.2
    assert "roc_auc_delta" in fidelity


def test_student_tracks_teacher_probs(ensemble_bundle):
    """Distillation fidelity: student probabilities stay close to the
    ensemble's on fresh data (mean |delta| under a few points)."""
    columns, _ = generate_synthetic(2000, seed=41)
    ds = ensemble_bundle.preprocessor.encode(columns)
    exact = score_dataset(ensemble_bundle, ds, chunk_rows=2048, exact=True)
    distilled = score_dataset(ensemble_bundle, ds, chunk_rows=2048, exact=False)
    assert exact.path == "exact" and distilled.path == "distilled"
    assert np.mean(np.abs(exact.predictions - distilled.predictions)) < 0.05
    # Outlier flags don't depend on the classifier: identical either way.
    np.testing.assert_array_equal(exact.outliers, distilled.outliers)


def test_auto_routing_uses_student_on_cpu(ensemble_bundle):
    """Tests run on the CPU backend, so the auto route must pick the
    student — and exact=True must still force the ensemble."""
    assert use_distilled_bulk(ensemble_bundle) is True
    assert use_distilled_bulk(ensemble_bundle, exact=True) is False
    columns, _ = generate_synthetic(500, seed=42)
    ds = ensemble_bundle.preprocessor.encode(columns)
    auto = score_dataset(ensemble_bundle, ds, chunk_rows=512)
    assert auto.path == "distilled"
    assert auto.summary()["path"] == "distilled"


def test_single_model_bundle_has_no_student(tiny_pipeline):
    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    assert not bundle.has_bulk
    assert use_distilled_bulk(bundle) is False
    columns, _ = generate_synthetic(300, seed=43)
    ds = bundle.preprocessor.encode(columns)
    assert score_dataset(bundle, ds, chunk_rows=512).path == "exact"


def test_distill_opt_out(tmp_path):
    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(
        family="mlp", hidden_dims=(16,), embed_dim=4, ensemble_size=2
    )
    config.train = TrainConfig(
        steps=60, eval_every=60, batch_size=256, distill_bulk=False
    )
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    bundle = load_bundle(result.bundle_dir)
    assert not bundle.has_bulk


def test_serving_engine_never_uses_student(ensemble_bundle):
    """The serving engine is wired to the exact model: its predictions
    match the exact bulk path, not the student's."""
    from mlops_tpu.serve import InferenceEngine

    columns, _ = generate_synthetic(64, seed=44)
    ds = ensemble_bundle.preprocessor.encode(columns)
    engine = InferenceEngine(
        ensemble_bundle, buckets=(64,), enable_grouping=False
    )
    served = engine.predict_arrays(ds.cat_ids, ds.numeric)
    exact = score_dataset(ensemble_bundle, ds, chunk_rows=64, exact=True)
    np.testing.assert_allclose(
        served["predictions"], exact.predictions, rtol=1e-4, atol=1e-5
    )


def test_score_exact_flag_forces_ensemble(
    ensemble_bundle_dir, tmp_path, capsys
):
    """score-batch score.exact=true reports path=exact; default reports
    distilled (CPU backend) — the substitution is always visible and
    overridable from the CLI."""
    import json

    from mlops_tpu.commands import _score_batch
    from mlops_tpu.data import write_csv_columns

    columns, labels = generate_synthetic(400, seed=45)
    path = tmp_path / "in.csv"
    write_csv_columns(path, columns, labels)

    for exact, want in ((True, "exact"), (False, "distilled")):
        config = Config()
        config.data.train_path = str(path)
        config.serve.model_directory = str(ensemble_bundle_dir)
        config.score.exact = exact
        config.score.chunk_rows = 256
        assert _score_batch(config) == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["path"] == want


# Heaviest end-to-end path (~60s serial on CPU): excluded from the
# timed tier-1 gate; CI's parallel pytest job still runs it.
@pytest.mark.slow
def test_transformer_families_also_distill(tmp_path):
    """The FT-Transformer (best measured AUC) loses CPU bulk to the
    sklearn floor just like ensembles do — the distillation gate covers
    the transformer families too."""
    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(
        family="ft_transformer", token_dim=16, depth=1, heads=2
    )
    config.train = TrainConfig(steps=60, eval_every=60, batch_size=256)
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    bundle = load_bundle(result.bundle_dir)
    assert bundle.has_bulk
    assert bundle.manifest["bulk"]["model_config"]["family"] == "mlp"
    assert use_distilled_bulk(bundle) is True  # CPU test backend
    # Student tracks the transformer teacher on fresh rows.
    columns, _ = generate_synthetic(800, seed=46)
    ds = bundle.preprocessor.encode(columns)
    exact = score_dataset(bundle, ds, chunk_rows=512, exact=True)
    distilled = score_dataset(bundle, ds, chunk_rows=512, exact=False)
    assert np.mean(np.abs(exact.predictions - distilled.predictions)) < 0.06


def test_distilled_path_shards_over_mesh(ensemble_bundle):
    """Distilled routing composes with data-parallel scoring: the student
    sharded over the 8-device mesh matches its single-device output."""
    from mlops_tpu.parallel import make_mesh

    columns, _ = generate_synthetic(1000, seed=47)
    ds = ensemble_bundle.preprocessor.encode(columns)
    solo = score_dataset(ensemble_bundle, ds, chunk_rows=512, exact=False)
    sharded = score_dataset(
        ensemble_bundle, ds, mesh=make_mesh(8), chunk_rows=512, exact=False
    )
    assert solo.path == sharded.path == "distilled"
    np.testing.assert_allclose(
        solo.predictions, sharded.predictions, rtol=2e-2, atol=2e-3
    )
    np.testing.assert_array_equal(solo.outliers, sharded.outliers)
