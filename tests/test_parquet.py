"""Parquet ingest (data/parquet.py): CSV parity on the same rows, streamed
chunking with exact chunk shapes, format dispatch, and the degraded-value
contract (null categorical -> OOV, null numeric -> NaN, strict labels).

The reference's estate would get this from Spark reading Parquet through the
same external-table interface (`00-create-external-table.ipynb:92-95`); here
the contract is pinned by tests instead.
"""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from mlops_tpu.data import (  # noqa: E402
    generate_synthetic,
    iter_table_chunks,
    load_csv_columns,
    load_table_columns,
    write_csv_columns,
)
from mlops_tpu.data.parquet import (  # noqa: E402
    is_parquet,
    iter_parquet_chunks,
    load_parquet_columns,
    write_parquet_columns,
)
from mlops_tpu.schema import SCHEMA


@pytest.fixture(scope="module")
def twin_files(tmp_path_factory):
    """The same 6k rows written as CSV and as Parquet."""
    root = tmp_path_factory.mktemp("parquet")
    columns, labels = generate_synthetic(6_000, seed=23)
    csv_path = root / "data.csv"
    pq_path = root / "data.parquet"
    write_csv_columns(csv_path, columns, labels)
    write_parquet_columns(pq_path, columns, labels)
    return csv_path, pq_path


def _assert_columns_equal(got_cols, want_cols):
    for feat in SCHEMA.categorical:
        assert got_cols[feat.name] == want_cols[feat.name], feat.name
    for feat in SCHEMA.numeric:
        np.testing.assert_allclose(
            got_cols[feat.name], want_cols[feat.name], rtol=1e-12, err_msg=feat.name
        )


def test_parquet_matches_csv_batch_read(twin_files):
    csv_path, pq_path = twin_files
    csv_cols, csv_labels = load_csv_columns(csv_path, require_target=True)
    pq_cols, pq_labels = load_parquet_columns(pq_path, require_target=True)
    _assert_columns_equal(pq_cols, csv_cols)
    np.testing.assert_array_equal(pq_labels, csv_labels)


def test_dispatch_routes_on_extension(twin_files):
    csv_path, pq_path = twin_files
    assert is_parquet(pq_path) and is_parquet("gs://bucket/x.PQ")
    assert not is_parquet(csv_path)
    via_csv, _ = load_table_columns(csv_path)
    via_pq, _ = load_table_columns(pq_path)
    _assert_columns_equal(via_pq, via_csv)


def test_chunks_exact_shape_and_reassemble(twin_files):
    """Chunks must be EXACTLY chunk_rows (except the tail) even when Arrow
    record batches fragment at row-group boundaries, and must reassemble to
    the batch read."""
    _, pq_path = twin_files
    batch_cols, batch_labels = load_parquet_columns(pq_path, require_target=True)
    sizes, seen_labels = [], []
    seen = {name: [] for name in SCHEMA.feature_names}
    for columns, labels in iter_parquet_chunks(
        pq_path, chunk_rows=1700, require_target=True
    ):
        sizes.append(len(labels))
        seen_labels.append(labels)
        for name in SCHEMA.feature_names:
            seen[name].extend(columns[name])
    assert sizes[:-1] == [1700] * (len(sizes) - 1) and 0 < sizes[-1] <= 1700
    np.testing.assert_array_equal(np.concatenate(seen_labels), batch_labels)
    _assert_columns_equal(seen, batch_cols)


def test_chunks_rebuffer_across_row_groups(tmp_path):
    """Tiny row groups (97 rows) still yield exact 500-row chunks."""
    columns, labels = generate_synthetic(1_013, seed=5)
    path = tmp_path / "rg.parquet"
    write_parquet_columns(path, columns, labels)
    table = pq.read_table(path)
    pq.write_table(table, path, row_group_size=97)
    sizes = [
        len(c[SCHEMA.categorical[0].name])
        for c, _ in iter_parquet_chunks(path, chunk_rows=500)
    ]
    assert sizes == [500, 500, 13]


def test_streamed_fit_and_validate_accept_parquet(twin_files):
    from mlops_tpu.data import fit_streaming

    csv_path, pq_path = twin_files
    pre_csv = fit_streaming(csv_path, chunk_rows=1234)
    pre_pq = fit_streaming(pq_path, chunk_rows=1234)
    np.testing.assert_allclose(
        pre_pq.numeric_median, pre_csv.numeric_median, rtol=1e-6
    )
    np.testing.assert_allclose(pre_pq.numeric_mean, pre_csv.numeric_mean, rtol=1e-6)
    np.testing.assert_allclose(pre_pq.numeric_std, pre_csv.numeric_std, rtol=1e-6)


def test_null_handling_matches_degraded_contract(tmp_path):
    """Null categorical -> "" -> OOV; null numeric -> NaN -> imputable;
    both via the same contract the CSV reader pins for empty cells."""
    columns, labels = generate_synthetic(50, seed=1)
    cat = SCHEMA.categorical[0].name
    num = SCHEMA.numeric[0].name
    arrays, names = [], []
    for feat in SCHEMA.categorical:
        vals = [str(v) for v in columns[feat.name]]
        arr = pa.array(
            [None if (feat.name == cat and i == 3) else v for i, v in enumerate(vals)],
            pa.string(),
        )
        arrays.append(arr)
        names.append(feat.name)
    for feat in SCHEMA.numeric:
        vals = list(columns[feat.name])
        arr = pa.array(
            [None if (feat.name == num and i == 7) else v for i, v in enumerate(vals)],
            pa.float64(),
        )
        arrays.append(arr)
        names.append(feat.name)
    path = tmp_path / "nulls.parquet"
    pq.write_table(pa.Table.from_arrays(arrays, names=names), path)

    cols, got_labels = load_parquet_columns(path)
    assert got_labels is None  # no target column at all
    assert cols[cat][3] == ""
    assert np.isnan(cols[num][7])
    assert np.isfinite(np.asarray(cols[num])[:7]).all()


def test_strict_labels_fail_fast_with_row_number(tmp_path):
    columns, labels = generate_synthetic(40, seed=2)
    path = tmp_path / "bad.parquet"
    write_parquet_columns(path, columns, labels)
    table = pq.read_table(path)
    target = table.column(SCHEMA.target).to_pylist()
    target[17] = None
    table = table.set_column(
        table.schema.get_field_index(SCHEMA.target),
        SCHEMA.target,
        pa.array(target, pa.int8()),
    )
    pq.write_table(table, path)
    with pytest.raises(ValueError, match="data row 17"):
        load_parquet_columns(path, require_target=True)
    # Permissive read: one bad value unlabels the file (CSV contract).
    _, got = load_parquet_columns(path)
    assert got is None
    # Streamed strict read raises too (at the chunk containing row 17).
    with pytest.raises(ValueError, match=SCHEMA.target):
        list(iter_parquet_chunks(path, chunk_rows=10, require_target=True))


def test_missing_columns_error_parity(tmp_path):
    columns, _ = generate_synthetic(10, seed=3)
    drop = SCHEMA.numeric[2].name
    arrays, names = [], []
    for feat in SCHEMA.categorical:
        arrays.append(pa.array([str(v) for v in columns[feat.name]], pa.string()))
        names.append(feat.name)
    for feat in SCHEMA.numeric:
        if feat.name == drop:
            continue
        arrays.append(pa.array(columns[feat.name], pa.float64()))
        names.append(feat.name)
    path = tmp_path / "short.parquet"
    pq.write_table(pa.Table.from_arrays(arrays, names=names), path)
    with pytest.raises(ValueError, match="missing required columns"):
        load_parquet_columns(path)
    with pytest.raises(ValueError, match="missing required columns"):
        list(iter_parquet_chunks(path))


def test_train_pipeline_accepts_parquet(twin_files, tmp_path):
    """End-to-end: data.train_path=*.parquet flows through load_training_data."""
    from mlops_tpu.config import Config
    from mlops_tpu.train.pipeline import load_training_data

    _, pq_path = twin_files
    config = Config()
    config.data.train_path = str(pq_path)
    columns, labels = load_training_data(config)
    assert len(labels) == 6_000
    assert set(SCHEMA.feature_names) <= set(columns)


def test_score_stream_parquet_matches_csv(twin_files, tiny_pipeline):
    """Stream scoring a Parquet file produces the same aggregates as the
    CSV twin."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.data import score_csv_stream

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    csv_path, pq_path = twin_files
    a = score_csv_stream(bundle, csv_path, None, chunk_rows=2048)
    b = score_csv_stream(bundle, pq_path, None, chunk_rows=2048)
    assert a["rows"] == b["rows"] == 6_000
    np.testing.assert_allclose(a["mean_prediction"], b["mean_prediction"], rtol=1e-5)
    np.testing.assert_allclose(a["outlier_rate"], b["outlier_rate"], rtol=1e-6)
