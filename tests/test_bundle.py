"""Bundle + registry tests: round-trip, schema guard, staged promotion."""

import json

import numpy as np
import pytest

from mlops_tpu.bundle import (
    ModelRegistry,
    load_bundle,
    parse_model_uri,
    save_bundle,
)
from mlops_tpu.config import Config, ModelConfig, MonitorConfig, TrainConfig
from mlops_tpu.monitor import fit_monitor
from mlops_tpu.train.pipeline import run_training


@pytest.fixture(scope="module")
def trained(tiny_pipeline):
    return tiny_pipeline


def test_pipeline_produces_bundle_and_registers(trained):
    config, result = trained
    assert (result.bundle_dir / "manifest.json").exists()
    assert (result.bundle_dir / "params.msgpack").exists()
    assert result.model_uri == f"models:/{config.registry.model_name}/1"
    manifest = json.loads((result.bundle_dir / "manifest.json").read_text())
    assert manifest["metrics"]["validation_roc_auc_score"] > 0.5
    assert (result.run_dir / "metrics.jsonl").exists()


def test_bundle_round_trip_predictions_identical(trained):
    config, result = trained
    import jax.numpy as jnp

    bundle = load_bundle(result.bundle_dir)
    from mlops_tpu.ops.predict import make_predict_fn

    predict = make_predict_fn(bundle)
    from mlops_tpu.data import generate_synthetic

    columns, _ = generate_synthetic(50, seed=42)
    ds = bundle.preprocessor.encode(columns)
    out = predict(jnp.asarray(ds.cat_ids), jnp.asarray(ds.numeric))
    assert out["predictions"].shape == (50,)
    assert np.isfinite(np.asarray(out["predictions"])).all()
    assert ((np.asarray(out["predictions"]) >= 0) & (np.asarray(out["predictions"]) <= 1)).all()
    assert out["feature_drift_batch"].shape == (23,)
    # Load a second time: bit-identical outputs (deterministic packaging).
    bundle2 = load_bundle(result.bundle_dir)
    predict2 = make_predict_fn(bundle2)
    out2 = predict2(jnp.asarray(ds.cat_ids), jnp.asarray(ds.numeric))
    np.testing.assert_array_equal(
        np.asarray(out["predictions"]), np.asarray(out2["predictions"])
    )


def test_bundle_schema_guard(trained, tmp_path):
    _, result = trained
    import shutil

    broken = tmp_path / "broken"
    shutil.copytree(result.bundle_dir, broken)
    manifest = json.loads((broken / "manifest.json").read_text())
    manifest["schema_fingerprint"] = "deadbeefdeadbeef"
    (broken / "manifest.json").write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="schema"):
        load_bundle(broken)


def test_registry_versioning_and_stages(trained, tmp_path):
    config, result = trained
    registry = ModelRegistry(tmp_path / "reg")
    uri1 = registry.register("m", result.bundle_dir)
    uri2 = registry.register("m", result.bundle_dir)
    assert (uri1, uri2) == ("models:/m/1", "models:/m/2")
    assert registry.resolve("m", "latest").name == "2"
    registry.set_stage("m", 1, "production")
    assert registry.resolve("m", "production").name == "1"
    with pytest.raises(KeyError):
        registry.resolve("m", "staging")
    with pytest.raises(KeyError):
        registry.resolve("m", "7")
    assert registry.resolve_uri("models:/m/1").name == "1"


def test_registry_single_stage_holder(trained, tmp_path):
    _, result = trained
    registry = ModelRegistry(tmp_path / "reg")
    registry.register("m", result.bundle_dir)
    registry.register("m", result.bundle_dir)
    registry.set_stage("m", 1, "production")
    registry.set_stage("m", 2, "production")  # archives v1
    stages = {v["version"]: v["stage"] for v in registry.list_versions("m")}
    assert stages == {1: "none", 2: "production"}
    registry.set_stage("m", 2, "staging")  # demotion leaves NO production
    with pytest.raises(KeyError):
        registry.resolve("m", "production")


def test_registry_recovers_from_orphan_version_dir(trained, tmp_path):
    # A crash between bundle copy and index write leaves an orphan version
    # dir; the next register() must skip past it, not collide.
    _, result = trained
    registry = ModelRegistry(tmp_path / "reg")
    registry.register("m", result.bundle_dir)  # version 1
    orphan = tmp_path / "reg" / "m" / "versions" / "2"
    orphan.mkdir(parents=True)  # simulated torn registration
    uri = registry.register("m", result.bundle_dir)
    assert uri == "models:/m/3"
    assert registry.resolve("m", "latest").name == "3"


def test_parse_model_uri():
    assert parse_model_uri("models:/foo/3") == ("foo", "3")
    with pytest.raises(ValueError):
        parse_model_uri("model:/foo/3")
    with pytest.raises(ValueError):
        parse_model_uri("models:/foo")


def test_manifest_pins_environment(tiny_pipeline):
    """The manifest records every behavior-shaping package version (the
    reference's conda-env synthesis analogue, `02-register-model.ipynb`
    cell 11) so a serving env can be reconstructed from the artifact."""
    import json

    _, result = tiny_pipeline
    manifest = json.loads((result.bundle_dir / "manifest.json").read_text())
    pins = manifest["framework"]
    for key in ("mlops_tpu", "python", "jax", "flax", "optax", "numpy", "pydantic"):
        assert pins.get(key), f"missing environment pin: {key}"


# Heaviest end-to-end path (~60s serial on CPU): excluded from the
# timed tier-1 gate; CI's parallel pytest job still runs it.
@pytest.mark.slow
def test_ensemble_bundle_round_trip_through_engine(tmp_path):
    """Train a small deep ensemble end to end, reload its bundle, and serve
    it — the manifest must carry ensemble_size so load_bundle rebuilds the
    vmapped module, and the engine must stay family-agnostic."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.schema import LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_training

    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(
        family="mlp", ensemble_size=2, hidden_dims=(16, 16), embed_dim=4
    )
    config.train = TrainConfig(steps=40, eval_every=40, batch_size=256)
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    assert np.isfinite(result.train_result.metrics["validation_roc_auc_score"])

    bundle = load_bundle(result.bundle_dir)
    assert bundle.manifest["model_config"]["ensemble_size"] == 2
    engine = InferenceEngine(bundle, buckets=(1, 8), enable_grouping=False)
    engine.warmup()
    out = engine.predict_records([LoanApplicant().model_dump()])
    assert len(out["predictions"]) == 1
    assert 0.0 <= out["predictions"][0] <= 1.0
    assert out["outliers"][0] in (0.0, 1.0)


def _register_worker(args):
    """Process-pool worker for the concurrency stress (module-level for
    pickling): fresh registry object per process, one register call."""
    root, bundle_dir = args
    from mlops_tpu.bundle import ModelRegistry

    return ModelRegistry(root).register("stress", bundle_dir)


def test_concurrent_registration_is_serialized(trained, tmp_path):
    """Thread- and process-concurrent registers must produce unique,
    gapless versions and a consistent index (threading.Lock + flock in
    registry._locked — past the reference's CI-serializes assumption)."""
    import concurrent.futures

    _, result = trained
    root = tmp_path / "reg"

    with concurrent.futures.ThreadPoolExecutor(max_workers=6) as pool:
        thread_uris = list(
            pool.map(
                lambda _: ModelRegistry(root).register(
                    "stress", result.bundle_dir
                ),
                range(6),
            )
        )
    # spawn, not fork: the parent has initialized JAX (threads held), and
    # fork-under-threads can deadlock the children before they exec.
    import multiprocessing

    with concurrent.futures.ProcessPoolExecutor(
        max_workers=4, mp_context=multiprocessing.get_context("spawn")
    ) as pool:
        proc_uris = list(
            pool.map(
                _register_worker, [(str(root), str(result.bundle_dir))] * 4
            )
        )

    uris = thread_uris + proc_uris
    versions = sorted(int(u.rsplit("/", 1)[1]) for u in uris)
    assert versions == list(range(1, 11))  # unique and gapless
    registry = ModelRegistry(root)
    listed = sorted(v["version"] for v in registry.list_versions("stress"))
    assert listed == list(range(1, 11))
    for v in range(1, 11):
        assert (root / "stress" / "versions" / str(v) / "manifest.json").exists()


def test_gc_prunes_orphans_and_old_unstaged(trained, tmp_path):
    """gc removes crash orphans and (with keep_unstaged) old stage-'none'
    versions; staged versions and the newest unstaged survive."""
    _, result = trained
    registry = ModelRegistry(tmp_path / "reg")
    for _ in range(4):
        registry.register("m", result.bundle_dir)  # versions 1..4
    registry.set_stage("m", 1, "production")
    # crash orphan: dir on disk, absent from the index
    orphan = tmp_path / "reg" / "m" / "versions" / "9"
    orphan.mkdir(parents=True)

    removed = registry.gc("m", keep_unstaged=1)
    assert removed == {"orphans_removed": [9], "versions_removed": [2, 3]}
    left = sorted(v["version"] for v in registry.list_versions("m"))
    assert left == [1, 4]  # production v1 + newest unstaged v4
    assert registry.resolve("m", "production").name == "1"
    assert registry.resolve("m", "latest").name == "4"
    assert not orphan.exists()


def test_gc_prunes_abandoned_staging_dirs(trained, tmp_path):
    """A SIGKILLed register leaves a .incoming-* staging dir (the cleanup
    handler never ran); gc drops it."""
    _, result = trained
    registry = ModelRegistry(tmp_path / "reg")
    registry.register("m", result.bundle_dir)
    staging = tmp_path / "reg" / "m" / "versions" / ".incoming-deadbeef"
    staging.mkdir(parents=True)
    registry.gc("m")
    assert not staging.exists()
    assert registry.resolve("m", "latest").name == "1"
