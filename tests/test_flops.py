"""FLOP accounting / MFU helpers (utils/flops.py) — the bench's roofline
evidence must itself be trustworthy."""

import jax.numpy as jnp
import numpy as np

from mlops_tpu.utils.flops import (
    compile_with_flops,
    compiled_flops,
    measured_gemm_peak,
    mfu,
    peak_flops,
)


def test_compile_with_flops_counts_a_matmul():
    n = 128
    a = jnp.ones((n, n), jnp.float32)
    exe, flops = compile_with_flops(lambda a, b: a @ b, a, a)
    assert exe is not None
    # XLA counts 2*n^3 (multiply+add) for a dense matmul.
    assert flops == 2 * n**3
    np.testing.assert_allclose(np.asarray(exe(a, a)), np.full((n, n), n))
    assert compiled_flops(lambda a, b: a @ b, a, a) == flops


def test_compile_with_flops_survives_bad_fn():
    exe, flops = compile_with_flops(lambda x: undefined_name + x, 1.0)  # noqa: F821
    assert exe is None and flops is None


def test_measured_gemm_peak_is_sane():
    peak = measured_gemm_peak(n=256, reps=2)
    # Any host lands between 100 MFLOP/s and 100 TFLOP/s.
    assert 1e8 < peak < 1e14


def test_mfu_and_peak_lookup():
    assert mfu(None, 10.0, 1e12) is None
    assert mfu(1e9, 10.0, None) is None
    assert mfu(1e9, 100.0, 1e12) == 0.1

    class FakeDevice:
        device_kind = "TPU v5 lite"

    class UnknownDevice:
        device_kind = "mystery-asic"

    assert peak_flops(FakeDevice()) == 197e12
    assert peak_flops(UnknownDevice()) is None


def test_peak_env_override(monkeypatch):
    class UnknownDevice:
        device_kind = "mystery-asic"

    monkeypatch.setenv("MLOPS_TPU_PEAK_FLOPS", "5e12")
    assert peak_flops(UnknownDevice()) == 5e12
