"""Pipelined streaming executor (data/pipeline_exec.py) + its rewired
consumers: depth parity (bit-identical outputs serial vs overlapped),
bounded-queue backpressure, clean failure drain, and the satellite
vectorizations (reservoir scatter, vocab searchsorted encode)."""

import csv
import threading
import time

import numpy as np
import pytest

from mlops_tpu.data import generate_synthetic, write_csv_columns
from mlops_tpu.data.pipeline_exec import Stage, run_pipeline
from mlops_tpu.schema import SCHEMA


# --------------------------------------------------------------- executor
def test_executor_preserves_order_and_results_at_any_depth():
    expected = [-(x * x) for x in range(200)]
    for depth in (1, 2, 4, 8):
        out = []
        stats = run_pipeline(
            range(200),
            [Stage("sq", lambda x: x * x), Stage("neg", lambda x: -x)],
            out.append,
            depth=depth,
        )
        assert out == expected
        assert stats.items == 200
        assert stats.depth == max(1, depth)
        assert set(stats.stages) == {"read", "sq", "neg", "write"}


def test_executor_backpressure_bounds_in_flight_items():
    """A slow sink must throttle the source: in-flight items stay at the
    queue-bound ceiling regardless of source length."""
    lock = threading.Lock()
    state = {"produced": 0, "consumed": 0, "max_inflight": 0}

    def produce():
        for i in range(100):
            with lock:
                state["produced"] += 1
                state["max_inflight"] = max(
                    state["max_inflight"],
                    state["produced"] - state["consumed"],
                )
            yield i

    def slow_sink(_):
        time.sleep(0.002)
        with lock:
            state["consumed"] += 1

    depth = 2
    stages = [Stage("a", lambda x: x), Stage("b", lambda x: x)]
    run_pipeline(produce(), stages, slow_sink, depth=depth)
    # (stages + 1) bounded queues of `depth` plus one in-hand item per
    # worker (source, 2 stages, sink).
    ceiling = (len(stages) + 1) * depth + len(stages) + 2
    assert state["max_inflight"] <= ceiling


@pytest.mark.parametrize("where", ["source", "stage", "batch-stage", "sink"])
def test_executor_failure_propagates_and_drains(where):
    """The ORIGINAL exception must reach the caller from any position, with
    every worker thread joined (no hung threads, no blocked producers) —
    under SEEDED SCHEDULE PERTURBATION (analysis/lockcheck.py): each seed
    shifts which stages are mid-flight when the failure lands, so the
    drain path is exercised across genuinely different interleavings."""
    from mlops_tpu.analysis.lockcheck import SchedulePerturber

    for seed in (0, 1, 2):
        perturber = SchedulePerturber(seed, max_delay_s=0.0005)

        def src():
            for i in range(50):
                if where == "source" and i == 10:
                    raise ValueError("boom in source")
                yield i

        def mid(x):
            if where == "stage" and x == 10:
                raise ValueError("boom in stage")
            return x

        def batch(xs):
            if where == "batch-stage" and 10 in xs:
                raise ValueError("boom in batch-stage")
            return xs

        def sink(x):
            if where == "sink" and x == 10:
                raise ValueError("boom in sink")

        before = threading.active_count()
        with pytest.raises(ValueError, match="boom"):
            run_pipeline(
                src(),
                [
                    Stage("mid", perturber.wrap(mid)),
                    Stage("batch", perturber.wrap(batch), batch_max=4),
                ],
                perturber.wrap(sink),
                depth=3,
            )
        # run_pipeline joins its workers before re-raising.
        assert threading.active_count() == before, f"seed {seed} leaked"


def test_executor_perturbed_schedules_bit_identical_across_seeds():
    """Three seeded schedules, one answer: random per-stage delays shift
    thread interleavings (and batch-gather groupings) run to run, while
    FIFO ordering must keep the output BIT-IDENTICAL to the serial loop."""
    from mlops_tpu.analysis.lockcheck import SchedulePerturber

    expected = [-(x * x) for x in range(150)]
    for seed in (0, 1, 2):
        perturber = SchedulePerturber(seed, max_delay_s=0.0005)
        out = []
        stats = run_pipeline(
            range(150),
            [
                Stage("sq", perturber.wrap(lambda x: x * x)),
                Stage(
                    "neg",
                    perturber.wrap(lambda xs: [-x for x in xs]),
                    batch_max=4,
                ),
            ],
            perturber.wrap(out.append),
            depth=3,
        )
        assert out == expected, f"seed {seed} output diverged"
        assert stats.items == 150


def test_executor_batch_stage_is_grouping_invariant():
    """Batch gathers vary with timing; results must not."""
    expected = [x * 3 for x in range(100)]
    for depth in (1, 3, 8):
        out = []
        run_pipeline(
            range(100),
            [Stage("b", lambda xs: [x * 3 for x in xs], batch_max=5)],
            out.append,
            depth=depth,
        )
        assert out == expected


def test_executor_stage_timing_reports_occupancy():
    stats = run_pipeline(
        range(20),
        [Stage("work", lambda x: (time.sleep(0.001), x)[1])],
        lambda _: None,
        depth=2,
    )
    work = stats.stages["work"]
    assert work["items"] == 20
    assert work["busy_s"] >= 0.02
    assert 0.0 < work["occupancy"] <= 1.5
    assert stats.as_dict()["depth"] == 2


# ------------------------------------------------- satellite vectorizations
def test_reservoir_scatter_bit_identical_to_loop():
    """The vectorized last-write-wins scatter must replay the replaced
    per-value loop exactly, duplicate slots included."""
    from mlops_tpu.data.stream import StreamingStats

    def loop_fold(reservoir, values, seen, k, rng):
        if reservoir.size < k:
            taken = min(k - reservoir.size, values.size)
            reservoir = np.concatenate([reservoir, values[:taken]])
            values = values[taken:]
            seen += taken
        if values.size == 0:
            return reservoir
        idx = seen + 1 + np.arange(values.size, dtype=np.float64)
        accept = rng.random(values.size) < (k / idx)
        slots = rng.integers(0, k, size=values.size)
        for v, s in zip(values[accept], slots[accept]):
            reservoir[s] = v
        return reservoir

    rng_data = np.random.default_rng(3)
    k = 64  # tiny reservoir -> dense slot collisions
    stats = StreamingStats(reservoir_size=k, seed=9)
    reference = np.empty(0, np.float64)
    ref_rng = np.random.default_rng(9)
    reservoir = np.empty(0, np.float64)
    seen = 0
    for _ in range(6):
        values = rng_data.normal(size=500)
        reference = loop_fold(reference.copy(), values, seen, k, ref_rng)
        reservoir = stats._fold_reservoir(reservoir, values, seen)
        seen += values.size
        np.testing.assert_array_equal(reservoir, reference)


def test_vectorized_encode_matches_dict_lookup_reference():
    from mlops_tpu.data import Preprocessor

    columns, labels = generate_synthetic(2000, seed=12)
    feat = SCHEMA.categorical[1]
    vals = list(columns[feat.name])
    vals[0] = ""  # missing -> OOV
    vals[1] = "never_seen"  # unseen -> OOV
    vals[2] = feat.vocab[0] + "_suffix"  # longer than any vocab word -> OOV
    vals[3] = feat.vocab[-1]
    columns[feat.name] = vals
    prep = Preprocessor.fit(columns)
    ds = prep.encode(columns, labels)
    for j, f in enumerate(SCHEMA.categorical):
        lut = {v: i for i, v in enumerate(f.vocab)}
        expected = [lut.get(v, f.oov_id) for v in columns[f.name]]
        np.testing.assert_array_equal(ds.cat_ids[:, j], expected)


# ---------------------------------------------------------- raw byte reader
def test_raw_chunk_reader_reassembles_to_batch_read(tmp_path):
    from mlops_tpu.data import Preprocessor, load_csv_columns
    from mlops_tpu.data.stream import iter_raw_csv_chunks
    from mlops_tpu.native import encode_csv_bytes, native_available

    columns, labels = generate_synthetic(3000, seed=4)
    path = tmp_path / "plain.csv"
    write_csv_columns(path, columns, labels)
    prep = Preprocessor.fit(columns)
    batch = prep.encode(*load_csv_columns(path))

    chunks = list(iter_raw_csv_chunks(path, chunk_rows=700))
    assert [kind for kind, _ in chunks] == ["bytes"] * len(chunks)
    if not native_available():
        pytest.skip("native kernel unavailable")
    encoded = [encode_csv_bytes(payload, prep) for _, payload in chunks]
    assert [e.n for e in encoded[:-1]] == [700] * (len(encoded) - 1)
    np.testing.assert_array_equal(
        np.concatenate([e.cat_ids for e in encoded]), batch.cat_ids
    )
    np.testing.assert_array_equal(
        np.concatenate([e.numeric for e in encoded]), batch.numeric
    )


def test_raw_chunk_reader_degrades_on_quoted_fields(tmp_path):
    """A quote anywhere flips the reader to the csv-module tail — row
    content must survive, including a quoted embedded newline."""
    columns, labels = generate_synthetic(50, seed=6)
    path = tmp_path / "quoted.csv"
    write_csv_columns(path, columns, labels)
    text = path.read_text().splitlines()
    row = text[11].split(",")  # line 11 = data row 10 (line 0 is the header)
    row[1] = '"uni\nversity"'  # quoted field with embedded newline
    text[11] = ",".join(row)
    path.write_text("\n".join(text) + "\n")

    from mlops_tpu.data.stream import iter_raw_csv_chunks

    kinds, total = [], 0
    edu = []
    for kind, payload in iter_raw_csv_chunks(path, chunk_rows=20):
        kinds.append(kind)
        assert kind == "columns"
        total += len(payload[SCHEMA.categorical[0].name])
        edu.extend(payload["education"])
    assert total == 50
    assert edu[10] == "uni\nversity"


def test_raw_chunk_reader_handles_crlf(tmp_path):
    columns, labels = generate_synthetic(40, seed=7)
    path = tmp_path / "crlf.csv"
    write_csv_columns(path, columns, labels)
    path.write_bytes(path.read_bytes().replace(b"\r\n", b"\n").replace(b"\n", b"\r\n"))

    from mlops_tpu.data.stream import iter_raw_csv_chunks
    from mlops_tpu.data import Preprocessor
    from mlops_tpu.native import encode_csv_bytes, native_available

    if not native_available():
        pytest.skip("native kernel unavailable")
    prep = Preprocessor.fit(columns)
    chunks = list(iter_raw_csv_chunks(path, chunk_rows=16))
    encoded = [encode_csv_bytes(payload, prep) for _, payload in chunks]
    assert sum(e.n for e in encoded) == 40


# ----------------------------------------------------------- depth parity
@pytest.fixture(scope="module")
def stream_setup(tiny_pipeline, tmp_path_factory):
    from mlops_tpu.bundle import load_bundle

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    root = tmp_path_factory.mktemp("pipe")
    columns, labels = generate_synthetic(3000, seed=21)
    path = root / "in.csv"
    write_csv_columns(path, columns, labels)
    return bundle, path, root


def test_stream_scoring_depth_parity_bit_identical(stream_setup):
    """score_csv_stream at depth 1 vs 4 (and python vs native parse) must
    write byte-identical output files and equal aggregate stats."""
    from mlops_tpu.data.stream import score_csv_stream

    bundle, path, root = stream_setup
    runs = {}
    for name, kwargs in (
        ("serial-python", dict(pipeline_depth=1, native=False)),
        ("serial-auto", dict(pipeline_depth=1)),
        ("deep-auto", dict(pipeline_depth=4)),
    ):
        out = root / f"{name}.csv"
        stats = score_csv_stream(bundle, path, out, chunk_rows=512, **kwargs)
        runs[name] = (out.read_bytes(), stats)
    baseline_bytes, baseline_stats = runs["serial-python"]
    for name, (data, stats) in runs.items():
        assert data == baseline_bytes, f"{name} output diverged"
        assert stats["rows"] == 3000
        assert stats["mean_prediction"] == baseline_stats["mean_prediction"]
        assert stats["outlier_rate"] == baseline_stats["outlier_rate"]
        assert set(stats["stages"]) >= {"read", "encode", "compute", "write"}


def test_fit_streaming_depth_parity_bit_identical(stream_setup):
    from mlops_tpu.data import fit_streaming

    _, path, _ = stream_setup
    serial = fit_streaming(path, chunk_rows=700, pipeline_depth=1)
    deep = fit_streaming(path, chunk_rows=700, pipeline_depth=4)
    np.testing.assert_array_equal(serial.numeric_median, deep.numeric_median)
    np.testing.assert_array_equal(serial.numeric_mean, deep.numeric_mean)
    np.testing.assert_array_equal(serial.numeric_std, deep.numeric_std)


@pytest.mark.slow  # unique 1024-chunk compile; the serial 870s tier-1
# gate is at capacity (CI's parallel job still runs slow tests)
def test_score_dataset_depth_parity_bit_identical(stream_setup):
    from mlops_tpu.parallel.bulk import score_dataset

    bundle, _, _ = stream_setup
    columns, _ = generate_synthetic(5000, seed=31)
    ds = bundle.preprocessor.encode(columns)
    serial = score_dataset(bundle, ds, chunk_rows=1024, pipeline_depth=1)
    deep = score_dataset(bundle, ds, chunk_rows=1024, pipeline_depth=4)
    np.testing.assert_array_equal(serial.predictions, deep.predictions)
    np.testing.assert_array_equal(serial.outliers, deep.outliers)
    assert deep.pipeline is not None
    assert set(deep.pipeline["stages"]) >= {"slice", "compute", "fetch"}
    assert "pipeline" in deep.summary()


# ------------------------------------------------------------ fault drain
def _thread_names():
    return {t.name for t in threading.enumerate()}


def test_encode_fault_drains_pipeline_and_leaves_no_output(
    stream_setup, monkeypatch
):
    """A mid-stream encode exception must propagate (original type), join
    every pipeline thread, and leave NO output file behind — neither the
    final path nor the .tmp working file."""
    from mlops_tpu.data.encode import Preprocessor
    from mlops_tpu.data.stream import score_csv_stream

    bundle, path, root = stream_setup
    calls = {"n": 0}
    real_encode = Preprocessor.encode

    def flaky_encode(self, columns, labels=None, schema=SCHEMA):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("encode blew up mid-stream")
        return real_encode(self, columns, labels, schema)

    monkeypatch.setattr(Preprocessor, "encode", flaky_encode)
    out = root / "fault.csv"
    before = _thread_names()
    # chunk_rows=512 shares the parity tests' compiled chunk program
    # (persistent compile cache) — tier-1 wall budget is tight.
    with pytest.raises(RuntimeError, match="encode blew up"):
        score_csv_stream(
            bundle, path, out, chunk_rows=512, pipeline_depth=4, native=False
        )
    assert calls["n"] >= 3
    assert not out.exists()
    assert not list(root.glob("*.tmp"))
    assert _thread_names() == before


def test_device_fault_drains_pipeline_and_propagates(
    stream_setup, monkeypatch
):
    """Same contract when the DEVICE stage fails (compute raising mid-
    sweep): pipeline drains, original exception propagates, no output."""
    import mlops_tpu.parallel.bulk as bulk

    from mlops_tpu.data.stream import score_csv_stream

    bundle, path, root = stream_setup
    real_make = bulk.make_chunk_scorer

    def flaky_scorer_factory(*args, **kwargs):
        scorer = real_make(*args, **kwargs)
        calls = {"n": 0}

        def flaky(cat, num, mask):
            calls["n"] += 1
            if calls["n"] == 4:  # past warmup + first chunks
                raise RuntimeError("device fell over")
            return scorer(cat, num, mask)

        return flaky

    monkeypatch.setattr(bulk, "make_chunk_scorer", flaky_scorer_factory)
    out = root / "devfault.csv"
    before = _thread_names()
    with pytest.raises(RuntimeError, match="device fell over"):
        score_csv_stream(bundle, path, out, chunk_rows=512, pipeline_depth=4)
    assert not out.exists()
    assert not list(root.glob("*.tmp"))
    assert _thread_names() == before


# --------------------------------------------------------- throughput smoke
@pytest.mark.slow
def test_pipelined_throughput_beats_old_serial_path(tiny_pipeline, tmp_path):
    """The bench acceptance, in-suite: on a synthetic 200k-row dataset the
    pipelined path (native chunk encode, depth 2) must beat the
    pre-executor serial path (Python csv parse, depth 1) on rows/s —
    the bench records the same comparison as ``bulk_stream_speedup``."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.data.stream import score_csv_stream

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    columns, labels = generate_synthetic(200_000, seed=5)
    path = tmp_path / "big.csv"
    write_csv_columns(path, columns, labels)

    def best_rows_per_s(**kwargs):
        return max(
            score_csv_stream(
                bundle, path, None, chunk_rows=16_384, **kwargs
            )["rows_per_s"]
            for _ in range(2)
        )

    serial = best_rows_per_s(pipeline_depth=1, native=False)
    pipelined = best_rows_per_s(pipeline_depth=2)
    assert pipelined >= serial, (pipelined, serial)
