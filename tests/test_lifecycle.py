"""Lifecycle controller tests: triggers, reservoir, retrain, shadow,
gated promotion, bit-stable hot swap, rollback, and the gauge renders.

The acceptance contract (ISSUE 8): drift-inject -> auto-retrain ->
shadow-mirror -> gated hot swap with bit-stable serving during the swap
(every response attributable to exactly one bundle generation), a
candidate failing the AUC gate never swapping in, one-call rollback, and
a drift spike inside the cooldown window not re-triggering retrain.
"""

import dataclasses
import threading

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.config import Config
from mlops_tpu.lifecycle import (
    LifecycleController,
    SampleReservoir,
    TriggerPolicy,
    evaluate_gates,
    expected_calibration_error,
    roc_auc_np,
    run_retrain,
)
from mlops_tpu.lifecycle.shadow import ShadowEngine
from mlops_tpu.schema import SCHEMA
from mlops_tpu.serve.engine import InferenceEngine

# ----------------------------------------------------------------- fixtures


def _lifecycle_config(td, labeled_path="") -> Config:
    config = Config()
    config.lifecycle.enabled = True
    config.lifecycle.dir = str(td / "lifecycle")
    config.lifecycle.labeled_path = str(labeled_path)
    config.lifecycle.retrain_steps = 50
    config.lifecycle.min_labeled_rows = 500
    config.lifecycle.min_window_rows = 32
    config.lifecycle.hysteresis_windows = 2
    config.lifecycle.cooldown_s = 0.0
    config.lifecycle.mirror_fraction = 1.0
    config.lifecycle.shadow_min_mirrors = 4
    config.lifecycle.max_ece = 0.3  # tiny fixtures calibrate coarsely
    return config


@pytest.fixture(scope="module")
def lc(tiny_pipeline, tmp_path_factory):
    """Shared lifecycle scenery: the tiny incumbent bundle, a labeled
    DRIFTED window on disk (numerics x10 — K-S drift score ~1), encoded
    normal + drifted traffic, and one retrained candidate."""
    from mlops_tpu.data import generate_synthetic, write_csv_columns

    td = tmp_path_factory.mktemp("lifecycle")
    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)

    columns, labels = generate_synthetic(1500, seed=3)
    for feat in SCHEMA.numeric:
        columns[feat.name] = [v * 10.0 for v in columns[feat.name]]
    labeled = td / "labeled.csv"
    write_csv_columns(labeled, columns, labels)

    prep = bundle.preprocessor
    norm_cols, _ = generate_synthetic(64, seed=9)
    drift_cols = {k: list(v) for k, v in norm_cols.items()}
    for feat in SCHEMA.numeric:
        drift_cols[feat.name] = [v * 10.0 for v in drift_cols[feat.name]]

    config = _lifecycle_config(td, labeled)
    candidate = run_retrain(bundle, config, generation=2)
    return {
        "td": td,
        "bundle": bundle,
        "config": config,
        "normal": prep.encode(norm_cols),
        "drifted": prep.encode(drift_cols),
        "candidate": candidate,
    }


def _fresh_engine(lc) -> InferenceEngine:
    engine = InferenceEngine(
        lc["bundle"], buckets=(1, 8), enable_grouping=False
    )
    engine.warmup()
    return engine


def _feed(engine, ds, batch=8):
    for lo in range(0, ds.cat_ids.shape[0], batch):
        engine.predict_arrays(
            ds.cat_ids[lo : lo + batch], ds.numeric[lo : lo + batch]
        )


# ----------------------------------------------------------------- triggers


def _snap(rows, outliers, batches, drift):
    feats = {name: drift for name in SCHEMA.feature_names}
    return {
        "rows": rows,
        "outliers": outliers,
        "batches": batches,
        "drift_last": dict(feats),
        "drift_mean": dict(feats),
    }


def test_trigger_hysteresis_requires_consecutive_breaches():
    cfg = Config().lifecycle
    cfg.min_window_rows = 8
    cfg.hysteresis_windows = 2
    cfg.drift_threshold = 0.8
    cfg.cooldown_s = 100.0
    policy = TriggerPolicy(cfg)
    assert not policy.observe(_snap(10, 0, 1, 0.1), 0.0).fired  # baseline
    # First breached window: hysteresis holds fire.
    first = policy.observe(_snap(30, 0, 3, 0.95), 1.0)
    assert not first.fired and first.streak == 1
    # A CLEAN window resets the streak...
    calm = policy.observe(_snap(60, 1, 6, 0.55), 2.0)
    assert not calm.fired and calm.streak == 0
    # ...so one more breach still does not fire...
    assert not policy.observe(_snap(90, 1, 9, 0.95), 3.0).fired
    # ...but the second consecutive one does.
    fired = policy.observe(_snap(120, 1, 12, 0.95), 4.0)
    assert fired.fired and "drift" in fired.reason


def test_trigger_cooldown_blocks_respike():
    cfg = Config().lifecycle
    cfg.min_window_rows = 8
    cfg.hysteresis_windows = 1
    cfg.drift_threshold = 0.8
    cfg.cooldown_s = 100.0
    policy = TriggerPolicy(cfg)
    policy.observe(_snap(10, 0, 1, 0.1), 0.0)
    assert policy.observe(_snap(30, 0, 3, 0.95), 1.0).fired
    # Drift spike INSIDE the cooldown window: no re-trigger, and the
    # breach does not even accumulate hysteresis.
    spike = policy.observe(_snap(60, 0, 6, 0.99), 50.0)
    assert not spike.fired and spike.in_cooldown and spike.streak == 0
    # Past the cooldown the policy is armed again.
    assert policy.observe(_snap(90, 0, 9, 0.99), 101.0).fired


def test_trigger_thin_window_preserves_hysteresis_streak():
    """A window below the evidence floor is NO EVIDENCE, not a clean
    bill: alternating thin/full windows under sustained drift must still
    accumulate the streak (a reset here would mask real drift forever)."""
    cfg = Config().lifecycle
    cfg.min_window_rows = 100
    cfg.hysteresis_windows = 2
    cfg.drift_threshold = 0.8
    cfg.cooldown_s = 0.0
    policy = TriggerPolicy(cfg)
    policy.observe(_snap(10, 0, 1, 0.1), 0.0)
    first = policy.observe(_snap(210, 0, 3, 0.95), 1.0)  # full, breached
    assert not first.fired and first.streak == 1
    thin = policy.observe(_snap(220, 0, 4, 0.95), 2.0)  # 10 rows: thin
    assert not thin.fired and thin.streak == 1  # streak untouched
    fired = policy.observe(_snap(430, 0, 7, 0.95), 3.0)  # full, breached
    assert fired.fired


def test_trigger_needs_minimum_window_rows():
    cfg = Config().lifecycle
    cfg.min_window_rows = 1000
    cfg.hysteresis_windows = 1
    cfg.cooldown_s = 0.0
    policy = TriggerPolicy(cfg)
    policy.observe(_snap(10, 0, 1, 0.1), 0.0)
    assert not policy.observe(_snap(40, 0, 4, 0.99), 1.0).fired


def test_trigger_outlier_rate_path():
    cfg = Config().lifecycle
    cfg.min_window_rows = 8
    cfg.hysteresis_windows = 1
    cfg.outlier_threshold = 0.5
    cfg.cooldown_s = 0.0
    policy = TriggerPolicy(cfg)
    policy.observe(_snap(10, 0, 1, 0.1), 0.0)
    fired = policy.observe(_snap(30, 15, 3, 0.1), 1.0)
    assert fired.fired and "outlier" in fired.reason


# ---------------------------------------------------------------- reservoir


def test_reservoir_bounded_and_persistent(tmp_path):
    res = SampleReservoir(32, tmp_path, seed=1)
    rng = np.random.default_rng(0)
    cat = rng.integers(0, 2, (200, SCHEMA.num_categorical)).astype(np.int32)
    num = rng.normal(size=(200, SCHEMA.num_numeric)).astype(np.float32)
    res.add_batch(cat, num)
    assert res.rows == 32 and res.rows_seen == 200
    res.save()
    revived = SampleReservoir(32, tmp_path, seed=1)
    assert revived.load()
    assert revived.rows == 32 and revived.rows_seen == 200
    w_cat, w_num = revived.window()
    assert w_cat.shape == (32, SCHEMA.num_categorical)
    assert w_num.dtype == np.float32


# ------------------------------------------------------------------ retrain


def test_retrain_produces_candidate_bundle_and_checkpoint(lc):
    result = lc["candidate"]
    assert result.candidate_dir.is_dir()
    # The candidate loads as a real bundle with lifecycle provenance tags
    # and a monitor whose K-S reference width matches the incumbent's
    # compiled contract (the shared-exec-table invariant).
    bundle = load_bundle(result.candidate_dir)
    assert bundle.manifest["tags"]["lifecycle"] == "candidate"
    assert (
        bundle.monitor.num_ref_sorted.shape
        == lc["bundle"].monitor.num_ref_sorted.shape
    )
    ckpt_dir = (
        result.candidate_dir.parent.parent / "checkpoints" / "gen-2-t1"
    )
    assert any(ckpt_dir.iterdir()), "retrain must checkpoint"
    assert result.holdout.n > 0 and result.holdout.labels is not None


def test_retrain_attempts_never_resume_rejected_checkpoints(lc):
    """A second trigger (new attempt) must land in a FRESH checkpoint
    dir: resuming a rejected attempt's completed checkpoints would
    restore the final step and return the stale params untouched."""
    second = run_retrain(
        lc["bundle"], lc["config"], generation=2, attempt=2
    )
    assert second.candidate_dir.name == "gen-2-t2"
    assert second.candidate_dir != lc["candidate"].candidate_dir
    ckpts = second.candidate_dir.parent.parent / "checkpoints"
    assert (ckpts / "gen-2-t2").is_dir()


def test_retrain_same_tag_never_resumes_a_completed_attempt(lc):
    """Colliding attempt tags (process restart, offline CLI rerun) must
    WIPE a completed prior checkpoint and retrain fresh — a full resume
    would restore the final step and train zero new steps on however
    fresh a labeled window (partial checkpoints still resume)."""
    first = run_retrain(lc["bundle"], lc["config"], generation=2, attempt=4)
    ckpt_dir = (
        first.candidate_dir.parent.parent / "checkpoints" / "gen-2-t4"
    )
    latest = ckpt_dir / "latest.json"
    mtime = latest.stat().st_mtime_ns
    second = run_retrain(lc["bundle"], lc["config"], generation=2, attempt=4)
    # A completed-resume trains 0 steps and never re-checkpoints; the
    # wipe forces a fresh run that writes a new final checkpoint.
    assert latest.stat().st_mtime_ns > mtime
    assert second.metrics  # a real (re)trained candidate, graded


def test_retrain_refit_preprocessor_keeps_incumbent_encoded_holdout(lc):
    """Under refit_preprocessor the gates must grade each side in the
    encode configuration IT serves: the holdout ships in both encodings,
    same rows."""
    import copy

    config = copy.deepcopy(lc["config"])
    config.lifecycle.refit_preprocessor = True
    result = run_retrain(lc["bundle"], config, generation=2, attempt=5)
    assert result.holdout_incumbent is not result.holdout
    assert result.holdout_incumbent.n == result.holdout.n
    np.testing.assert_array_equal(
        result.holdout_incumbent.labels, result.holdout.labels
    )  # identical row selection
    assert not np.allclose(  # different normalization stats
        result.holdout_incumbent.numeric, result.holdout.numeric
    )
    # Without a refit the two references are the same object.
    assert lc["candidate"].holdout_incumbent is lc["candidate"].holdout


def test_retrain_monitor_refits_on_reservoir_window(lc):
    """The serve-path reservoir IS the monitor's refit source when it
    carries enough evidence: the candidate's drift reference must
    describe recent TRAFFIC, not the labeled file."""
    rng = np.random.default_rng(2)
    k = 1500
    window = (
        rng.integers(0, 2, (k, SCHEMA.num_categorical)).astype(np.int32),
        rng.normal(7.0, 0.1, (k, SCHEMA.num_numeric)).astype(np.float32),
    )
    result = run_retrain(
        lc["bundle"], lc["config"], generation=2, attempt=3,
        reservoir_window=window,
    )
    ref = np.asarray(result.bundle.monitor.num_ref_sorted)
    # Reference sample drawn from the N(7, 0.1) reservoir, not the
    # labeled window (whose numerics are nowhere near a tight 7.0 band).
    assert ref.shape == lc["bundle"].monitor.num_ref_sorted.shape
    assert abs(float(ref.mean()) - 7.0) < 0.5


# ----------------------------------------------------- shadow + gates + swap


def test_shadow_shares_exec_table_for_same_architecture(lc):
    engine = _fresh_engine(lc)
    shadow = ShadowEngine(engine, lc["candidate"].bundle)
    shadow.warm()
    assert shadow.warm_mode == "shared"
    assert set(shadow.engine._exec) == set(engine._exec)
    # Candidate warmup must only ever involve registered cache entries —
    # the tpulint Layer-2 / warmers lockstep extends to the lifecycle.
    from mlops_tpu.compilecache.registry import CACHE_ENTRY_IDS

    assert {"serve-predict-packed", "serve-predict-group-packed"} <= set(
        CACHE_ENTRY_IDS
    )


def test_candidate_failing_auc_gate_never_swaps(lc, monkeypatch):
    """A wrecked candidate (zeroed params -> AUC 0.5) must be REJECTED by
    the gates and the live engine must never change generation."""
    import jax

    import mlops_tpu.lifecycle.controller as controller_mod

    engine = _fresh_engine(lc)
    good = lc["candidate"]
    wrecked_bundle = dataclasses.replace(
        good.bundle,
        variables=jax.tree_util.tree_map(
            lambda a: np.zeros_like(np.asarray(a)), good.bundle.variables
        ),
    )
    wrecked = dataclasses.replace(good, bundle=wrecked_bundle)
    monkeypatch.setattr(
        controller_mod, "run_retrain", lambda *a, **k: wrecked
    )
    clock = {"t": 0.0}
    config = lc["config"]
    ctrl = LifecycleController(engine, config, clock=lambda: clock["t"])
    _feed(engine, lc["normal"])
    ctrl.run_once()
    for _ in range(4):
        _feed(engine, lc["drifted"])
        clock["t"] += 1.0
        status = ctrl.run_once()
        if status["promotions"]["rejected"]:
            break
    assert status["drift_triggers"] == 1
    assert status["promotions"] == {
        "promoted": 0, "rejected": 1, "rolled_back": 0,
    }
    assert engine.bundle_generation == 1  # never swapped in
    report = status["last_report"]
    assert report["outcome"] == "rejected"
    assert any("auc" in reason for reason in report["gates"]["reasons"])
    # A drift spike inside the post-rejection cooldown must not
    # re-trigger retrain.
    config.lifecycle.cooldown_s = 1000.0
    ctrl.policy.start_cooldown(clock["t"])
    _feed(engine, lc["drifted"])
    clock["t"] += 1.0
    assert ctrl.run_once()["drift_triggers"] == 1


def test_hot_swap_is_bit_stable_and_rolls_back(lc):
    """Concurrent traffic across a promotion: every response must equal
    the incumbent's or the candidate's reference response EXACTLY (one
    bundle generation end to end, never a mix), with the lock sanitizer
    asserting the declared order; rollback restores the incumbent's exact
    responses in one call."""
    from mlops_tpu.analysis.lockcheck import instrument_locks

    engine = _fresh_engine(lc)
    shadow = ShadowEngine(engine, lc["candidate"].bundle)
    shadow.warm()
    ds = lc["drifted"]
    cat, num = ds.cat_ids[:8], ds.numeric[:8]
    exp_incumbent = engine.predict_arrays(cat, num)
    exp_candidate = shadow.engine.predict_arrays(cat, num)
    assert exp_incumbent != exp_candidate  # the swap must be observable

    responses: list = []
    errors: list = []
    start = threading.Barrier(4)

    def hammer():
        try:
            start.wait()
            for _ in range(30):
                responses.append(engine.predict_arrays(cat, num))
        except Exception as err:  # pragma: no cover - surfaced below
            errors.append(err)

    with instrument_locks(engine, perturb_seed=7) as sanitizer:
        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        start.wait()
        generation = engine.swap_bundle(shadow.engine)
        for t in threads:
            t.join()
    assert not errors
    assert not sanitizer.violations, [str(v) for v in sanitizer.violations]
    assert generation == 2
    matched_inc = sum(r == exp_incumbent for r in responses)
    matched_cand = sum(r == exp_candidate for r in responses)
    assert matched_inc + matched_cand == len(responses), (
        "a response matched NEITHER bundle generation — the swap mixed "
        "params/programs across generations"
    )
    assert matched_cand > 0  # the swap actually took effect
    # Post-swap the engine serves the candidate verbatim...
    assert engine.predict_arrays(cat, num) == exp_candidate
    # ...and one rollback call restores the incumbent verbatim.
    assert engine.rollback() == 3
    assert engine.predict_arrays(cat, num) == exp_incumbent
    # Rollback is itself reversible (the states exchange).
    assert engine.rollback() == 4
    assert engine.predict_arrays(cat, num) == exp_candidate


def test_end_to_end_drift_retrain_shadow_promote(lc):
    """The acceptance loop: drift-inject -> trigger -> auto-retrain ->
    shadow-mirror -> gates -> hot promotion, all through the controller."""
    engine = _fresh_engine(lc)
    clock = {"t": 0.0}
    ctrl = LifecycleController(
        engine, lc["config"], clock=lambda: clock["t"]
    )
    _feed(engine, lc["normal"])
    assert ctrl.run_once()["state"] == "idle"  # baseline, no trigger
    status = None
    for _ in range(6):
        _feed(engine, lc["drifted"])
        clock["t"] += 1.0
        status = ctrl.run_once()
        if status["promotions"]["promoted"]:
            break
    assert status["drift_triggers"] == 1
    assert status["promotions"]["promoted"] == 1
    assert status["generation"] == 2
    report = status["last_report"]
    assert report["outcome"] == "promoted"
    assert report["gates"]["passed"]
    assert report["mirrors"] >= lc["config"].lifecycle.shadow_min_mirrors
    assert report["warm_mode"] == "shared"
    # The retrained candidate must actually fit the drifted window better
    # than the incumbent on the labeled holdout.
    assert report["auc_delta"] > 0
    snap = ctrl.metrics_snapshot()
    assert snap["reservoir_rows"] > 0
    assert snap["tee_drops"] == 0


# ------------------------------------------------------------------- gates


def test_gate_math_auc_ece():
    labels = np.array([0, 0, 1, 1, 0, 1], np.float64)
    good = np.array([0.1, 0.2, 0.9, 0.8, 0.3, 0.7])
    assert roc_auc_np(good, labels) == 1.0
    assert roc_auc_np(np.full(6, 0.5), labels) == 0.5
    assert expected_calibration_error(labels.astype(float), labels) == 0.0

    from mlops_tpu.lifecycle.shadow import ShadowReport

    cfg = Config().lifecycle
    base = dict(
        auc_candidate=0.8, auc_incumbent=0.8, auc_delta=0.0,
        ece_candidate=0.02, ece_incumbent=0.02,
        p99_candidate_ms=1.0, p99_incumbent_ms=1.0,
        p50_candidate_ms=0.5, p50_incumbent_ms=0.5,
        mirrors=10, mirror_drops=0, mean_abs_pred_delta=0.0,
        holdout_rows=100, warm_mode="shared", warm_s=0.0,
    )
    assert evaluate_gates(ShadowReport(**base), cfg).passed
    bad_auc = dict(base, auc_delta=-0.5, auc_candidate=0.3)
    decision = evaluate_gates(ShadowReport(**bad_auc), cfg)
    assert not decision.passed and "auc" in decision.reasons[0]
    bad_p99 = dict(base, p99_candidate_ms=100.0)
    decision = evaluate_gates(ShadowReport(**bad_p99), cfg)
    assert not decision.passed and "latency" in decision.reasons[0]
    bad_ece = dict(base, ece_candidate=0.9)
    decision = evaluate_gates(ShadowReport(**bad_ece), cfg)
    assert not decision.passed and "calibration" in decision.reasons[0]


# ----------------------------------------------------------------- metrics


def test_lifecycle_gauges_single_process_render():
    from mlops_tpu.serve.metrics import ServingMetrics

    metrics = ServingMetrics()
    assert "mlops_tpu_bundle_generation" not in metrics.render()
    metrics.set_lifecycle(
        {
            "generation": 3,
            "drift_triggers": 2,
            "shadow_auc_delta": 0.0123,
            "promotions": {"promoted": 1, "rejected": 1, "rolled_back": 0},
            "reservoir_rows": 77,
        }
    )
    text = metrics.render()
    assert 'mlops_tpu_bundle_generation{tenant="default"} 3' in text
    assert 'mlops_tpu_drift_trigger_total{tenant="default"} 2' in text
    assert 'mlops_tpu_shadow_auc_delta{tenant="default"} 0.012300' in text
    assert (
        'mlops_tpu_promotions_total{tenant="default",outcome="promoted"} 1'
        in text
    )
    assert (
        'mlops_tpu_promotions_total{tenant="default",outcome="rolled_back"}'
        " 0" in text
    )
    assert 'mlops_tpu_lifecycle_reservoir_rows{tenant="default"} 77' in text


def test_lifecycle_gauges_ring_render():
    from mlops_tpu.serve.ipc import RequestRing
    from mlops_tpu.serve.metrics import render_ring_metrics

    ring = RequestRing(workers=1, slots_small=2, slots_large=1, large_rows=8)
    try:
        assert "mlops_tpu_bundle_generation" not in render_ring_metrics(ring)
        ring.write_lifecycle(
            {
                "generation": 2,
                "drift_triggers": 1,
                "shadow_auc_delta": None,
                "promotions": {"promoted": 1, "rejected": 0,
                               "rolled_back": 1},
                "reservoir_rows": 5,
            }
        )
        text = render_ring_metrics(ring)
        assert 'mlops_tpu_bundle_generation{tenant="default"} 2' in text
        assert 'mlops_tpu_drift_trigger_total{tenant="default"} 1' in text
        # None delta: the series is withheld, not rendered as 0.
        assert "mlops_tpu_shadow_auc_delta" not in text
        assert (
            'mlops_tpu_promotions_total{tenant="default",'
            'outcome="rolled_back"} 1' in text
        )
        assert (
            'mlops_tpu_lifecycle_reservoir_rows{tenant="default"} 5' in text
        )
    finally:
        ring.close()


def test_rollback_without_swap_raises(lc):
    with pytest.raises(ValueError, match="no retired bundle"):
        InferenceEngine(lc["bundle"], buckets=(1,)).rollback()


def test_circuit_breaker_opens_on_repeated_retrain_failures(lc, tmp_path):
    """Repeated UNEXPECTED retrain failures (injected at the
    lifecycle.retrain fault point) open the circuit breaker: triggers
    stop firing for breaker_cooldown_s instead of hot-looping retrain
    attempts, the trips counter and gauges move, and the loop re-arms
    after the cooldown (ISSUE 9)."""
    from mlops_tpu import faults
    from mlops_tpu.serve.metrics import ServingMetrics

    engine = _fresh_engine(lc)
    config = Config()
    config.lifecycle.enabled = True
    config.lifecycle.dir = str(tmp_path / "state")
    config.lifecycle.labeled_path = str(lc["td"] / "labeled.csv")
    config.lifecycle.min_window_rows = 32
    config.lifecycle.hysteresis_windows = 1
    config.lifecycle.cooldown_s = 0.0
    config.lifecycle.breaker_failures = 2
    config.lifecycle.breaker_cooldown_s = 100.0
    clock = {"t": 0.0}
    ctrl = LifecycleController(engine, config, clock=lambda: clock["t"])
    faults.arm(faults.FaultPlan.from_rules(
        [{"point": "lifecycle.retrain", "mode": "raise",
          "message": "injected retrain failure"}]
    ))
    try:
        _feed(engine, lc["normal"])
        ctrl.run_once()  # baseline snapshot
        # Two failing triggers open the breaker.
        for expected_triggers in (1, 2):
            _feed(engine, lc["drifted"])
            clock["t"] += 1.0
            status = ctrl.run_once()
            assert status["drift_triggers"] == expected_triggers
            assert status["state"] == "idle"  # never stranded mid-retrain
            assert "injected retrain failure" in status["last_error"]
        assert status["breaker_open"] is True
        assert status["breaker_trips"] == 1
        # Open breaker: drift spikes neither fire nor retrain — and the
        # trigger machinery is never even EVALUATED (observe() would
        # accumulate hysteresis and arm hidden cooldowns that delay the
        # half-open probe; the controller must use the side-effect-free
        # consume() instead).
        real_observe, observed = ctrl.policy.observe, []
        ctrl.policy.observe = lambda *a, **k: (
            observed.append(1), real_observe(*a, **k)
        )[1]
        for _ in range(3):
            _feed(engine, lc["drifted"])
            clock["t"] += 1.0
            status = ctrl.run_once()
        ctrl.policy.observe = real_observe
        assert observed == []
        assert status["drift_triggers"] == 2  # unchanged while open
        assert status["breaker_open"] is True
        # The gauges render in both telemetry planes' shared formatter.
        lines = "\n".join(
            ServingMetrics.lifecycle_lines(ctrl.metrics_snapshot())
        )
        assert 'mlops_tpu_lifecycle_breaker_open{tenant="default"} 1' in lines
        assert (
            'mlops_tpu_lifecycle_breaker_trips_total{tenant="default"} 1'
            in lines
        )
        # Past the cooldown the loop re-arms (half-open): the next breach
        # triggers again, and one more failure does NOT instantly re-trip
        # (the streak restarted at zero when the breaker opened).
        clock["t"] += 101.0
        _feed(engine, lc["drifted"])
        clock["t"] += 1.0
        status = ctrl.run_once()
        assert status["breaker_open"] is False
        assert status["drift_triggers"] == 3
        assert status["breaker_trips"] == 1
        assert status["consecutive_failures"] == 1
    finally:
        faults.disarm()
        ctrl.engine.set_lifecycle_tee(None)


def test_trigger_policy_consume_has_no_side_effects():
    """`consume()` advances the differencing baseline only: no firing,
    no streak, no cooldown — the open-breaker window feed."""
    from mlops_tpu.config import LifecycleConfig

    policy = TriggerPolicy(LifecycleConfig(
        hysteresis_windows=1, min_window_rows=1, cooldown_s=300.0
    ))

    def snap(rows, drift):
        return {
            "rows": rows, "outliers": 0.0, "batches": rows,
            "drift_mean": {"f": drift}, "drift_sum": [drift * rows],
        }

    policy.consume(snap(100, 0.0))  # baseline
    policy.consume(snap(200, 0.95 * 2))  # a breach-sized window, consumed
    assert policy._streak == 0
    assert not policy.in_cooldown(0.0)
    # The next OBSERVED window differences against the consumed baseline
    # (continuous), and a breach there fires normally.
    decision = policy.observe(snap(300, 0.95 * 3 + 0.98 * 1), now=1.0)
    assert decision.fired, decision
