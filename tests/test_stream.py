"""Out-of-core streaming (data/stream.py): chunked ingest, one-pass stats,
stream scoring — the framework-native answer to the reference's Spark
external-table path (`00-create-external-table.ipynb:92-95`)."""

import csv

import numpy as np
import pytest

from mlops_tpu.data import (
    Preprocessor,
    fit_streaming,
    generate_synthetic,
    iter_csv_chunks,
    load_csv_columns,
    write_csv_columns,
)
from mlops_tpu.data.stream import StreamingStats
from mlops_tpu.schema import SCHEMA


@pytest.fixture(scope="module")
def csv_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("stream") / "data.csv"
    columns, labels = generate_synthetic(12_000, seed=11)
    write_csv_columns(path, columns, labels)
    return path, columns, labels


def test_chunks_reassemble_to_batch_read(csv_file):
    path, _, _ = csv_file
    batch_cols, batch_labels = load_csv_columns(path, require_target=True)
    seen_labels = []
    seen = {name: [] for name in SCHEMA.feature_names}
    sizes = []
    for columns, labels in iter_csv_chunks(path, chunk_rows=1700, require_target=True):
        sizes.append(len(labels))
        seen_labels.append(labels)
        for name in SCHEMA.feature_names:
            seen[name].extend(columns[name])
    assert all(s == 1700 for s in sizes[:-1]) and sizes[-1] <= 1700
    np.testing.assert_array_equal(np.concatenate(seen_labels), batch_labels)
    for feat in SCHEMA.categorical:
        assert seen[feat.name] == batch_cols[feat.name]
    for feat in SCHEMA.numeric:
        np.testing.assert_allclose(seen[feat.name], batch_cols[feat.name])


def test_streaming_fit_matches_batch_fit_exactly(csv_file):
    """With the full sample inside the reservoir, the one-pass fit must be
    BIT-equal to the batch fit (imputed moments close in closed form)."""
    path, _, _ = csv_file
    batch_cols, _ = load_csv_columns(path)
    pre_batch = Preprocessor.fit(batch_cols)
    pre_stream = fit_streaming(path, chunk_rows=1234)
    np.testing.assert_array_equal(pre_stream.numeric_median, pre_batch.numeric_median)
    np.testing.assert_array_equal(pre_stream.numeric_mean, pre_batch.numeric_mean)
    np.testing.assert_array_equal(pre_stream.numeric_std, pre_batch.numeric_std)


def test_streaming_fit_handles_missing_values():
    """NaNs impute with the (streaming) median in the closed-form moments."""
    columns, _ = generate_synthetic(4000, seed=3)
    name = SCHEMA.numeric[0].name
    vals = list(columns[name])
    for i in range(0, len(vals), 7):
        vals[i] = float("nan")
    columns[name] = vals
    pre_batch = Preprocessor.fit(columns)
    stats = StreamingStats()
    # two chunks
    half = {k: v[:2000] for k, v in columns.items()}
    rest = {k: v[2000:] for k, v in columns.items()}
    stats.update(half)
    stats.update(rest)
    pre_stream = stats.finalize()
    np.testing.assert_allclose(
        pre_stream.numeric_mean, pre_batch.numeric_mean, rtol=1e-6
    )
    np.testing.assert_allclose(
        pre_stream.numeric_std, pre_batch.numeric_std, rtol=1e-6
    )


def test_reservoir_bounds_memory_and_approximates_median():
    rng = np.random.default_rng(0)
    stats = StreamingStats(reservoir_size=500, seed=1)
    name = SCHEMA.numeric[0].name
    base, _ = generate_synthetic(100, seed=0)
    true_values = rng.normal(loc=5.0, scale=2.0, size=20_000)
    for start in range(0, 20_000, 4000):
        chunk = {k: (v * 40)[:4000] for k, v in base.items()}
        chunk[name] = true_values[start : start + 4000].tolist()
        stats.update(chunk)
    pre = stats.finalize()
    j = 0  # feature index of `name`
    assert stats._reservoirs[j].size == 500  # bounded
    assert abs(pre.numeric_median[j] - 5.0) < 0.3  # approximate median
    assert abs(pre.numeric_mean[j] - 5.0) < 0.05  # exact moments
    assert abs(pre.numeric_std[j] - 2.0) < 0.05


def test_stream_scoring_matches_batch(tiny_pipeline, tmp_path):
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.data.stream import score_csv_stream
    from mlops_tpu.parallel.bulk import make_chunk_scorer

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    columns, labels = generate_synthetic(3000, seed=21)
    path = tmp_path / "in.csv"
    out = tmp_path / "preds.csv"
    write_csv_columns(path, columns, labels)

    stats = score_csv_stream(bundle, path, out, chunk_rows=512)
    assert stats["rows"] == 3000
    assert 0.0 <= stats["mean_prediction"] <= 1.0

    ds = bundle.preprocessor.encode(columns)
    score = make_chunk_scorer(bundle, mesh=None)
    probs, outliers = score(ds.cat_ids, ds.numeric, np.ones(ds.n, bool))
    with out.open() as f:
        rows = list(csv.reader(f))[1:]
    got_p = np.array([float(r[0]) for r in rows])
    got_o = np.array([float(r[1]) for r in rows])
    np.testing.assert_allclose(got_p, np.asarray(probs), atol=1e-5)
    np.testing.assert_array_equal(got_o, np.asarray(outliers))


def test_corrupt_training_label_fails_fast_in_chunks(tmp_path):
    columns, labels = generate_synthetic(100, seed=2)
    path = tmp_path / "bad.csv"
    write_csv_columns(path, columns, labels)
    text = path.read_text().splitlines()
    parts = text[50].rsplit(",", 1)
    text[50] = parts[0] + ",not-a-label"
    path.write_text("\n".join(text) + "\n")
    with pytest.raises(ValueError, match="unparseable"):
        for _ in iter_csv_chunks(path, chunk_rows=40, require_target=True):
            pass


def test_labels_only_parsed_under_require_target(csv_file):
    """Feature-only consumers get labels=None every chunk; the permissive
    per-chunk label parse the batch reader's file-level contract forbids is
    simply not offered (module docstring)."""
    path, _, _ = csv_file
    for _, labels in iter_csv_chunks(path, chunk_rows=5000):
        assert labels is None


def test_streaming_moments_survive_large_magnitude_features():
    """Raw E[x^2]-E[x]^2 catastrophically cancels at mean ~1e8, std ~1
    (float64 ulp of sumsq exceeds the variance signal) — the shifted
    accumulation must keep the std exact."""
    rng = np.random.default_rng(4)
    base, _ = generate_synthetic(100, seed=0)
    name = SCHEMA.numeric[0].name
    stats = StreamingStats()
    all_vals = []
    for _ in range(5):
        vals = rng.normal(loc=1e8, scale=1.0, size=4000)
        all_vals.append(vals)
        chunk = {k: (v * 40)[:4000] for k, v in base.items()}
        chunk[name] = vals.tolist()
        stats.update(chunk)
    pre = stats.finalize()
    true_std = np.concatenate(all_vals).std()
    assert abs(pre.numeric_std[0] - true_std) / true_std < 1e-3
    assert abs(pre.numeric_mean[0] - 1e8) < 1.0


def test_stream_scoring_data_parallel_over_mesh(tiny_pipeline, tmp_path):
    """With a mesh, every chunk shards over 'data' (chunk size rounds up to
    divide the axis) and results match the single-device stream exactly."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.data.stream import score_csv_stream
    from mlops_tpu.parallel import make_mesh

    _, result = tiny_pipeline
    bundle = load_bundle(result.bundle_dir)
    columns, labels = generate_synthetic(2000, seed=33)
    path = tmp_path / "in.csv"
    write_csv_columns(path, columns, labels)

    solo = score_csv_stream(
        bundle, path, tmp_path / "solo.csv", chunk_rows=500
    )
    mesh = make_mesh(8)
    sharded = score_csv_stream(
        bundle, path, tmp_path / "mesh.csv", chunk_rows=500, mesh=mesh
    )
    assert sharded["rows"] == solo["rows"] == 2000
    solo_rows = (tmp_path / "solo.csv").read_text().splitlines()
    mesh_rows = (tmp_path / "mesh.csv").read_text().splitlines()
    solo_p = np.array([float(r.split(",")[0]) for r in solo_rows[1:]])
    mesh_p = np.array([float(r.split(",")[0]) for r in mesh_rows[1:]])
    np.testing.assert_allclose(mesh_p, solo_p, atol=1e-5)
