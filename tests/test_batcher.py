"""Micro-batching: grouped dispatch must be invisible to each request."""

import asyncio
import concurrent.futures

import numpy as np
import pytest

from mlops_tpu.bundle import load_bundle
from mlops_tpu.serve.batcher import MicroBatcher
from mlops_tpu.serve.engine import GROUP_ROW_BUCKET, InferenceEngine


@pytest.fixture(scope="module")
def engine(warm_engine):
    return warm_engine  # session-shared warmed engine (conftest)


def _requests(sample_request, k):
    reqs = []
    for i in range(k):
        rec = dict(sample_request[0])
        rec["age"] = 20.0 + i
        rec["credit_limit"] = 1000.0 * (i + 1)
        reqs.append([rec] * ((i % 3) + 1))  # sizes 1..3
    return reqs


def test_grouped_matches_solo(engine, sample_request):
    reqs = _requests(sample_request, 5)
    grouped = engine.predict_group(reqs)
    for req, got in zip(reqs, grouped):
        solo = engine.predict_records(req)
        assert len(got["predictions"]) == len(req)
        np.testing.assert_allclose(
            got["predictions"], solo["predictions"], atol=1e-5
        )
        np.testing.assert_allclose(got["outliers"], solo["outliers"], atol=1e-6)
        for k in solo["feature_drift_batch"]:
            assert (
                abs(got["feature_drift_batch"][k] - solo["feature_drift_batch"][k])
                < 1e-4
            ), k


def test_fetch_ring_sizing_bounds_executor_footprint(engine):
    """The dispatch bound and fetch ring occupy SEPARATE executor threads;
    the server sizes the ring so dispatch + fetch stays inside the pool
    with headroom for the solo fast path (2*max_inflight threads would
    saturate a max_workers == 2*max_inflight pool)."""
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)
    b = MicroBatcher(engine, executor, fetch_inflight=2)
    assert b._fetch_ring._value == 2
    b = MicroBatcher(engine, executor, max_inflight=3)  # default: mirror
    assert b._fetch_ring._value == 3
    b = MicroBatcher(engine, executor, fetch_inflight=0)  # floor: 1
    assert b._fetch_ring._value == 1
    executor.shutdown(wait=False)

    from mlops_tpu.config import ServeConfig
    from mlops_tpu.serve.server import HttpServer

    # Server wiring: defaults (workers=8, inflight=4) leave one thread of
    # headroom — 4 dispatch + 3 fetch < 8.
    server = HttpServer(engine, ServeConfig())
    workers = server._executor._max_workers
    dispatch = server.batcher._inflight._value
    fetch = server.batcher._fetch_ring._value
    assert dispatch + fetch < workers
    server._executor.shutdown(wait=False)

    # Inconsistent geometry is REJECTED at startup with a named error
    # (ServeConfig.validate), not silently clamped into server locals:
    # max_inflight == max_workers used to pass validation, leave zero
    # headroom (dispatch + fetch > pool), and serve with numbers the
    # config never said.
    import pytest

    from mlops_tpu.config import ServeConfigError

    cfg = ServeConfig()
    cfg.max_workers = 4
    cfg.max_inflight = 4
    with pytest.raises(ServeConfigError, match="max_inflight"):
        HttpServer(engine, cfg)
    assert (cfg.max_workers, cfg.max_inflight) == (4, 4)  # never mutated


def test_batcher_coalesces_concurrent_requests(engine, sample_request):
    # The batcher rides the two-phase grouped API (dispatch_group /
    # fetch_group) — count coalescing at the dispatch phase.
    calls = {"group": 0, "solo": 0}
    real_dispatch = engine.dispatch_group

    def counting_dispatch(reqs):
        calls["group"] += 1
        calls["last_size"] = len(reqs)
        return real_dispatch(reqs)

    engine_proxy = engine
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)

    async def drive():
        batcher = MicroBatcher(engine_proxy, executor, window_ms=20.0)
        batcher.engine.dispatch_group = counting_dispatch
        try:
            reqs = _requests(sample_request, 6)
            return await asyncio.gather(*(batcher.predict(r) for r in reqs))
        finally:
            del batcher.engine.dispatch_group

    responses = asyncio.run(drive())
    assert len(responses) == 6
    assert calls["group"] >= 1
    assert calls["last_size"] > 1, "concurrent requests should coalesce"
    for req, got in zip(_requests(sample_request, 6), responses):
        assert len(got["predictions"]) == len(req)


def test_large_requests_bypass_batcher(engine, sample_request):
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)

    async def drive():
        batcher = MicroBatcher(engine, executor, window_ms=5.0)
        big = [dict(sample_request[0])] * (GROUP_ROW_BUCKET + 5)
        return await batcher.predict(big)

    response = asyncio.run(drive())
    assert len(response["predictions"]) == GROUP_ROW_BUCKET + 5


def test_disabled_window_runs_solo(engine, sample_request):
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)

    async def drive():
        batcher = MicroBatcher(engine, executor, window_ms=0.0)
        assert not batcher.enabled
        return await batcher.predict(sample_request)

    response = asyncio.run(drive())
    assert len(response["predictions"]) == 1


def test_sklearn_flavor_groups_through_the_tensorized_path(tmp_path):
    """The gbm family serves through the packed group path (ISSUE 19 —
    the Hummingbird-style tensorization lowered it into the same packed
    contract as flax), and grouped answers stay bit-identical to solo."""
    from mlops_tpu.config import Config, ModelConfig, TrainConfig
    from mlops_tpu.train.pipeline import run_training

    config = Config()
    config.data.rows = 1200
    config.model = ModelConfig(family="gbm", n_estimators=10, max_tree_depth=3)
    config.train = TrainConfig(steps=1)
    config.registry.root = str(tmp_path / "reg")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    eng = InferenceEngine(load_bundle(result.bundle_dir), buckets=(1, 8))
    assert eng.supports_grouping
    reqs = [[{"age": 30.0}], [{"age": 40.0}]]
    out = eng.predict_group(reqs)
    assert len(out) == 2
    for grouped, req in zip(out, reqs):
        solo = eng.predict_records(req)
        assert grouped["predictions"] == solo["predictions"]


def test_overlapped_dispatch_stress_matches_solo(engine, sample_request):
    """100 concurrent mixed-size requests through the batcher (overlapped
    dispatches, group-batched encode) return exactly what each request
    would get alone — ordering, per-request drift, everything."""
    rng = np.random.default_rng(9)
    requests = []
    for i in range(100):
        rec = dict(sample_request[0])
        rec["age"] = float(20 + (i % 50))
        rec["bill_amount_1"] = float(rng.integers(100, 5000))
        requests.append([rec] * int(rng.integers(1, GROUP_ROW_BUCKET + 1)))

    expected = [engine.predict_records(r) for r in requests]

    async def run():
        executor = concurrent.futures.ThreadPoolExecutor(max_workers=8)
        batcher = MicroBatcher(engine, executor, window_ms=1.0)
        return await asyncio.gather(
            *[batcher.predict(r) for r in requests]
        )

    got = asyncio.run(run())
    for g, e in zip(got, expected):
        assert g["predictions"] == pytest.approx(e["predictions"], abs=1e-6)
        assert g["outliers"] == e["outliers"]
        for name, score in e["feature_drift_batch"].items():
            assert g["feature_drift_batch"][name] == pytest.approx(
                score, abs=1e-5
            )


def test_burst_stress_perturbed_schedules_bit_identical(engine, sample_request):
    """The seeded schedule-perturbation harness (tpulint Layer 3 runtime
    half, analysis/lockcheck.py): 3 seeds shift the thread interleaving of
    overlapped dispatch/fetch while the engine's real locks are swapped
    for instrumented wrappers asserting the declared TPULINT_LOCK_ORDER.
    Responses must be BIT-IDENTICAL across seeds — group composition is
    deterministic (all co-travelers enqueue inside the window), so only
    the schedule varies, and the schedule must not leak into results."""
    from mlops_tpu.analysis.lockcheck import (
        SchedulePerturber,
        instrument_engine,
    )

    requests = []
    for i in range(20):
        rec = dict(sample_request[0])
        rec["age"] = float(21 + i)
        rec["credit_limit"] = 500.0 * (i + 1)
        requests.append([rec] * ((i % 3) + 1))

    def drive(seed):
        perturber = SchedulePerturber(seed, max_delay_s=0.001)
        engine.dispatch_group = perturber.wrap(engine.dispatch_group)
        engine.fetch_group = perturber.wrap(engine.fetch_group)
        try:
            with instrument_engine(
                engine, perturb_seed=seed, max_perturb_s=0.001
            ) as san:

                async def run():
                    executor = concurrent.futures.ThreadPoolExecutor(
                        max_workers=8
                    )
                    batcher = MicroBatcher(
                        engine, executor, window_ms=20.0, max_inflight=4
                    )
                    try:
                        return await asyncio.gather(
                            *[batcher.predict(r) for r in requests]
                        )
                    finally:
                        executor.shutdown(wait=True)

                responses = asyncio.run(run())
            return responses, list(san.violations), dict(san.acquired)
        finally:
            del engine.dispatch_group  # restore the bound class methods
            del engine.fetch_group

    baseline = None
    for seed in (0, 1, 2):
        responses, violations, acquired = drive(seed)
        assert violations == [], [str(v) for v in violations]
        assert acquired.get("_acc_lock", 0) >= 1  # the hot path ran locked
        assert len(responses) == len(requests)
        if baseline is None:
            baseline = responses
            # sanity vs the solo path (approx — grouped kernels):
            solo = engine.predict_records(requests[3])
            assert responses[3]["predictions"] == pytest.approx(
                solo["predictions"], abs=1e-5
            )
        else:
            assert responses == baseline, f"seed {seed} output diverged"


def test_warmup_exec_table_writes_hold_compile_lock(tiny_pipeline):
    """Regression for the tpulint TPU402 true positive this layer found:
    engine warmup fills the AOT dispatch table while the server is already
    accepting traffic (bind-first-warm-concurrently), so a live
    novel-shape request (`_compile_novel`) can race those writes — every
    table write must hold `_compile_lock`."""
    from mlops_tpu.analysis.lockcheck import instrument_locks
    from mlops_tpu.bundle import load_bundle

    _, result = tiny_pipeline
    eng = InferenceEngine(
        load_bundle(result.bundle_dir), buckets=(1,), enable_grouping=False
    )
    with instrument_locks(eng) as san:
        eng.warmup()
    assert eng.warmup_stats["programs"] == 1
    assert san.acquired.get("_compile_lock", 0) == 1
    assert san.violations == []
    out = eng.predict_records([{"age": 30.0}])
    assert len(out["predictions"]) == 1


def test_abandoned_requests_are_purged_at_claim_time(engine, sample_request):
    """Entries whose caller gave up (request deadline 503 during a device
    stall) must be dropped when a group is claimed — a recovering device
    must serve live traffic, not a dead backlog, and a long stall must not
    grow the queue without bound."""

    async def run():
        executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)
        batcher = MicroBatcher(engine, executor, window_ms=30.0)
        loop = asyncio.get_running_loop()

        # Seed abandoned entries directly (what wait_for cancellation
        # leaves behind), then one live request.
        for _ in range(5):
            dead = loop.create_future()
            dead.cancel()
            batcher._pending.append(
                ([sample_request[0]], dead, None, None, None)
            )
        live = asyncio.create_task(batcher.predict([sample_request[0]]))
        response = await asyncio.wait_for(live, timeout=30)
        assert 0.0 <= response["predictions"][0] <= 1.0
        # the dead entries did not survive the claim
        assert all(not f.cancelled() for _, f, _, _, _ in batcher._pending)
        executor.shutdown(wait=False)

    asyncio.run(run())


def test_idle_fast_path_skips_window(engine):
    """A lone request on an idle batcher must not pay the coalescing
    window: it runs solo immediately (measured: the 1 ms default window
    tripled sequential-client latency for zero coalescing)."""
    import concurrent.futures
    import time

    executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)
    batcher = MicroBatcher(engine, executor, window_ms=200.0)  # huge window
    rec = {"age": 30.0}

    async def drive():
        t0 = time.perf_counter()
        out = await batcher.predict([rec])
        return out, time.perf_counter() - t0

    out, dt = asyncio.run(drive())
    assert 0.0 <= out["predictions"][0] <= 1.0
    # Far below the 200 ms window: the idle fast-path skipped it.
    assert dt < 0.15, f"idle request waited {dt*1e3:.0f} ms"
    # And the batcher queue stayed untouched.
    assert not batcher._pending and not batcher._dispatch_tasks


def test_continuous_and_windowed_bit_identical_under_load(
    engine, sample_request
):
    """ISSUE 17 acceptance: the continuous batcher's responses are
    BIT-IDENTICAL to the windowed batcher's (and to the solo path) at any
    load — admission policy changes WHEN groups form, never the
    per-request math (each slot's drift is over its own rows)."""
    rng = np.random.default_rng(17)
    requests = []
    for i in range(60):
        rec = dict(sample_request[0])
        rec["age"] = float(20 + (i % 45))
        rec["bill_amount_2"] = float(rng.integers(50, 9000))
        requests.append([rec] * int(rng.integers(1, GROUP_ROW_BUCKET + 1)))

    expected = [engine.predict_records(r) for r in requests]

    def drive(mode):
        async def run():
            executor = concurrent.futures.ThreadPoolExecutor(max_workers=8)
            batcher = MicroBatcher(
                engine, executor, window_ms=1.0, batch_mode=mode
            )
            try:
                return await asyncio.gather(
                    *[batcher.predict(r) for r in requests]
                )
            finally:
                executor.shutdown(wait=True)

        return asyncio.run(run())

    continuous = drive("continuous")
    windowed = drive("windowed")
    assert continuous == windowed == expected


def test_continuous_mode_still_coalesces(engine, sample_request):
    """Continuous admission must keep the batcher's reason to exist:
    concurrent arrivals ride shared dispatches (in-flight round trips are
    the coalescing window), not 1 dispatch per request."""
    calls = {"group": 0, "requests": 0}
    real_dispatch = engine.dispatch_group

    def counting_dispatch(reqs):
        calls["group"] += 1
        calls["requests"] += len(reqs)
        return real_dispatch(reqs)

    async def drive():
        executor = concurrent.futures.ThreadPoolExecutor(max_workers=8)
        batcher = MicroBatcher(
            engine, executor, window_ms=1.0, batch_mode="continuous"
        )
        engine.dispatch_group = counting_dispatch
        try:
            reqs = _requests(sample_request, 24)
            return await asyncio.gather(*(batcher.predict(r) for r in reqs))
        finally:
            del engine.dispatch_group
            executor.shutdown(wait=True)

    responses = asyncio.run(drive())
    assert len(responses) == 24
    # The idle fast-path may take the first arrival solo; the rest must
    # share dispatches.
    assert calls["group"] < calls["requests"], "nothing coalesced"


def test_continuous_admit_deadline_policy(engine):
    """The empty-pipe admit deadline: full window on cold start (no
    measurement yet), ZERO while dispatches are in flight (their round
    trips already coalesced arrivals for free), admit_fraction x the
    dispatch EWMA once measured — always capped by window_ms."""
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=2)
    b = MicroBatcher(
        engine, executor, window_ms=10.0, batch_mode="continuous",
        admit_fraction=0.5,
    )
    executor.shutdown(wait=False)
    assert b._admit_deadline_s() == b.window_s  # cold start: full cap
    b._observe_dispatch_s(0.004)
    assert b._dispatch_ewma_s == pytest.approx(0.004)  # first sample sets
    assert b._admit_deadline_s() == pytest.approx(0.002)  # fraction of it
    b._observe_dispatch_s(0.008)  # EWMA folds 0.8 old + 0.2 new
    assert b._dispatch_ewma_s == pytest.approx(0.8 * 0.004 + 0.2 * 0.008)
    b._dispatch_ewma_s = 1.0  # a slow dispatch never exceeds the cap
    assert b._admit_deadline_s() == b.window_s
    b._dispatch_tasks.add(object())  # in flight: admission is free
    assert b._admit_deadline_s() == 0.0
    b._dispatch_tasks.clear()

    with pytest.raises(ValueError, match="batch_mode"):
        MicroBatcher(engine, None, batch_mode="adaptive")


def test_server_wires_batch_mode_from_config(engine):
    """ServeConfig.batch_mode / batch_admit_fraction reach the batcher
    (TPU503 liveness: a knob that never reaches its consumer is dead)."""
    from mlops_tpu.config import ServeConfig
    from mlops_tpu.serve.server import HttpServer

    server = HttpServer(
        engine, ServeConfig(batch_mode="windowed", batch_admit_fraction=0.25)
    )
    assert server.batcher.batch_mode == "windowed"
    assert server.batcher.admit_fraction == 0.25
    server._executor.shutdown(wait=False)
    server = HttpServer(engine, ServeConfig())
    assert server.batcher.batch_mode == "continuous"  # shipped default
    server._executor.shutdown(wait=False)


def test_stalled_solo_pushes_arrivals_back_to_batcher():
    """A hung fast-path call must not let later arrivals bypass the
    batcher's backpressure: while a solo dispatch is in flight, new
    requests enqueue (where the claim-time purge and max_inflight bound
    the backlog) instead of piling un-cancellable work into the executor."""
    import concurrent.futures
    import threading

    class StallingEngine:
        supports_grouping = True

        def __init__(self):
            self.release = threading.Event()
            self.solo_calls = 0

        def _respond(self):
            self.release.wait(timeout=10)
            return {"predictions": [0.5], "outliers": [0.0],
                    "feature_drift_batch": {}}

        def predict_records(self, records):
            self.solo_calls += 1  # fast-path entry point only
            return self._respond()

        def predict_group(self, requests):
            return [self._respond() for _ in requests]

    eng = StallingEngine()
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=4)
    batcher = MicroBatcher(eng, executor, window_ms=1.0)

    async def drive():
        first = asyncio.create_task(batcher.predict([{"age": 1.0}]))
        await asyncio.sleep(0.05)  # > window: first went solo and stalled
        assert batcher._solo_inflight == 1
        # Deadline fires: the CALLER is cancelled, but the engine call
        # still occupies its executor thread — the counter must NOT drop
        # (an early decrement would re-open the fast path for the next
        # victim, rebuilding the unbounded dead backlog).
        first.cancel()
        await asyncio.sleep(0.05)
        assert batcher._solo_inflight == 1
        second = asyncio.create_task(batcher.predict([{"age": 2.0}]))
        await asyncio.sleep(0.05)
        # Second arrival did NOT take the fast path: it either sits in
        # _pending or rides a grouped dispatch task.
        assert eng.solo_calls == 1
        eng.release.set()
        await second
        await asyncio.sleep(0.05)  # executor completion drains the counter

    asyncio.run(drive())
    assert batcher._solo_inflight == 0
