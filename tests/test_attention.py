"""Flash-attention kernel numerics vs the dense XLA reference.

The Pallas kernel runs in interpret mode on the CPU test backend
(`ops/attention.py:_use_interpret`), so these tests exercise the exact
kernel code paths (tiling, online softmax, padding mask) without a TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.ops.attention import (
    attend,
    flash_attention,
    reference_attention,
)


def _qkv(b, s, h, d, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize(
    "b,s,h,d",
    [
        (2, 128, 4, 32),  # exact block multiple
        (1, 200, 2, 16),  # ragged: seq padded inside the kernel
        (2, 24, 2, 8),  # FT-Transformer shape, below one block
    ],
)
def test_flash_matches_reference(b, s, h, d):
    q, k, v = _qkv(b, s, h, d)
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_bf16_close_to_f32_reference():
    q, k, v = _qkv(2, 128, 4, 32, dtype=jnp.bfloat16, seed=1)
    out = flash_attention(q, k, v)
    ref = reference_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_flash_gradients_match_reference():
    q, k, v = _qkv(1, 96, 2, 16, seed=2)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, block_q=32, block_k=32).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=2e-5)


def test_flash_gradients_match_reference_ragged_and_cross():
    """The Pallas backward under padding: a seq that is NOT a block
    multiple (mask path in all three kernels) and distinct q/kv lengths
    (cross-attention) must still match dense gradients."""
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(2, 45, 2, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 70, 2, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 70, 2, 16)).astype(np.float32))

    def loss_flash(q, k, v):
        # A non-uniform cotangent (sum of squares) exercises delta != 1.
        return (flash_attention(q, k, v, block_q=32, block_k=32) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=1e-4
        )


def test_flash_backward_is_pallas_not_dense_remat():
    """The VJP must lower to Pallas kernels (VERDICT r4 #5): the backward
    jaxpr carries the dq and dkv pallas_calls and — unlike the round-4
    dense-remat VJP — no [S, S] softmax materialization."""
    q, k, v = _qkv(1, 64, 2, 16, seed=8)
    jaxpr = jax.make_jaxpr(
        jax.grad(lambda q: flash_attention(q, k, v, block_q=32, block_k=32).sum())
    )(q)
    text = str(jaxpr)
    # forward + dq + dkv kernels
    assert text.count("pallas_call") >= 3, text.count("pallas_call")
    assert "softmax" not in text


def test_flash_bf16_gradients_finite_and_close():
    q, k, v = _qkv(2, 128, 4, 32, dtype=jnp.bfloat16, seed=9)

    def loss(q, k, v):
        return flash_attention(q, k, v).astype(jnp.float32).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: reference_attention(q, k, v).sum(), argnums=(0, 1, 2)
    )(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    for a, b in zip(g, gr):
        assert np.isfinite(np.asarray(a, np.float32)).all()
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b), atol=6e-2
        )


def test_flash_under_jit_and_vmap():
    q, k, v = _qkv(2, 64, 2, 16, seed=3)
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v, block_q=32, block_k=32))
    np.testing.assert_allclose(
        np.asarray(jitted(q, k, v)),
        np.asarray(reference_attention(q, k, v)),
        atol=2e-5,
    )


def test_attend_auto_dispatch_is_xla_off_tpu():
    """Off-TPU the Pallas kernels run interpreted, so the None-dispatch
    must stay on XLA dense even at flash-length sequences (product
    CPU-fallback paths: doc training/scoring); use_flash=True still
    forces the kernel for the equivalence tests."""
    q, k, v = _qkv(1, 256, 2, 16, seed=10)
    jaxpr = str(jax.make_jaxpr(lambda q: attend(q, k, v))(q))
    assert jax.default_backend() != "tpu"  # conftest pins cpu
    assert "pallas_call" not in jaxpr
    forced = str(
        jax.make_jaxpr(lambda q: attend(q, k, v, use_flash=True))(q)
    )
    assert "pallas_call" in forced


def test_attend_dispatch():
    # Short sequence routes to the dense path; the forced-kernel long
    # case pins flash against dense (off-TPU the auto-dispatch stays
    # dense, so use_flash=True keeps the kernel covered here).
    q, k, v = _qkv(1, 24, 2, 8, seed=4)
    np.testing.assert_allclose(
        np.asarray(attend(q, k, v)),
        np.asarray(reference_attention(q, k, v)),
        atol=2e-5,
    )
    q, k, v = _qkv(1, 160, 2, 8, seed=5)
    np.testing.assert_allclose(
        np.asarray(attend(q, k, v, use_flash=True)),
        np.asarray(reference_attention(q, k, v)),
        atol=2e-5,
    )
