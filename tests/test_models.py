"""Model zoo tests: shapes, dtypes, determinism across families."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig
from mlops_tpu.models import FAMILIES, build_model, init_params
from mlops_tpu.schema import NUM_CATEGORICAL, NUM_NUMERIC


def _dummy_batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, 2, size=(n, NUM_CATEGORICAL)).astype(np.int32)
    num = rng.normal(size=(n, NUM_NUMERIC)).astype(np.float32)
    return jnp.asarray(cat), jnp.asarray(num)


@pytest.mark.parametrize("family", FAMILIES)
def test_forward_shapes(family):
    config = ModelConfig(
        family=family, hidden_dims=(32, 32), token_dim=32, depth=2, heads=4
    )
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    cat, num = _dummy_batch()
    logits = model.apply(variables, cat, num, train=False)
    assert logits.shape == (16,)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.parametrize("family", FAMILIES)
def test_forward_deterministic_eval(family):
    config = ModelConfig(
        family=family, hidden_dims=(32,), token_dim=32, depth=1, heads=4
    )
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(1))
    cat, num = _dummy_batch()
    a = model.apply(variables, cat, num, train=False)
    b = model.apply(variables, cat, num, train=False)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_params_are_float32():
    model = build_model(ModelConfig(family="mlp", hidden_dims=(32,)))
    variables = init_params(model, jax.random.PRNGKey(0))
    for leaf in jax.tree_util.tree_leaves(variables["params"]):
        assert leaf.dtype == jnp.float32


def test_dropout_needs_rng_only_in_train():
    model = build_model(ModelConfig(family="mlp", hidden_dims=(32, 32), dropout=0.5))
    variables = init_params(model, jax.random.PRNGKey(0))
    cat, num = _dummy_batch()
    out1 = model.apply(
        variables, cat, num, train=True, rngs={"dropout": jax.random.PRNGKey(2)}
    )
    out2 = model.apply(
        variables, cat, num, train=True, rngs={"dropout": jax.random.PRNGKey(3)}
    )
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


class TestDeepEnsemble:
    """Vmapped deep ensemble (models/ensemble.py) — the MXU-native answer
    to the reference's RandomForest variance reduction
    (`01-train-model.ipynb:195-227`)."""

    def _build(self, k=4):
        config = ModelConfig(family="mlp", ensemble_size=k, hidden_dims=(32, 32))
        model = build_model(config)
        variables = init_params(model, jax.random.PRNGKey(0))
        return model, variables

    def test_train_mode_exposes_member_axis(self):
        model, variables = self._build(k=4)
        cat, num = _dummy_batch()
        logits = model.apply(
            variables, cat, num, train=True, rngs={"dropout": jax.random.PRNGKey(1)}
        )
        assert logits.shape == (4, 16)

    def test_eval_mode_keeps_zoo_contract(self):
        model, variables = self._build(k=4)
        cat, num = _dummy_batch()
        logits = model.apply(variables, cat, num, train=False)
        assert logits.shape == (16,)
        assert logits.dtype == jnp.float32

    def test_members_are_independently_initialized(self):
        model, variables = self._build(k=4)
        leaf = jax.tree_util.tree_leaves(variables["params"])[0]
        assert leaf.shape[0] == 4
        # split params rngs: members must not be clones of one another
        flat = np.asarray(leaf).reshape(4, -1)
        assert not np.allclose(flat[0], flat[1])

    def test_eval_is_logit_of_mean_member_probability(self):
        model, variables = self._build(k=4)
        cat, num = _dummy_batch()
        agg = model.apply(variables, cat, num, train=False)
        # dropout off in train=False; reconstruct member logits by slicing
        # each member's params out and running the bare member module
        member_cfg = ModelConfig(family="mlp", ensemble_size=1, hidden_dims=(32, 32))
        member = build_model(member_cfg)
        probs = []
        for i in range(4):
            member_params = jax.tree.map(lambda x: x[i], variables["params"]["member"])
            lg = member.apply({"params": member_params}, cat, num, train=False)
            probs.append(jax.nn.sigmoid(lg))
        mean_prob = jnp.stack(probs).mean(0)
        np.testing.assert_allclose(
            np.asarray(jax.nn.sigmoid(agg)), np.asarray(mean_prob), atol=1e-5
        )

    def test_ensemble_size_one_is_not_wrapped(self):
        config = ModelConfig(family="mlp", ensemble_size=1, hidden_dims=(32,))
        from mlops_tpu.models import MLP

        assert isinstance(build_model(config), MLP)
