"""Mixture-of-experts family (models/moe.py): routing, aux loss, expert
parallelism. The reference has no parallelism at all (SURVEY.md SS2.7);
EP completes the DP/TP/SP set this framework provides beyond parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mlops_tpu.config import ModelConfig, TrainConfig
from mlops_tpu.models import build_model, init_params
from mlops_tpu.models.moe import MoEFeedForward
from mlops_tpu.parallel import make_mesh, make_sharded_train_step
from mlops_tpu.parallel.sharding import param_shardings
from mlops_tpu.train.loop import TrainState, make_optimizer, training_loss


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.integers(0, 2, (n, 9)).astype(np.int32)),
        jnp.asarray(rng.normal(size=(n, 14)).astype(np.float32)),
        jnp.asarray((rng.random(n) < 0.2).astype(np.float32)),
    )


def test_moe_ffn_routes_top_k_and_normalizes():
    """The combine weights must select exactly top_k experts per token and
    sum to 1 — checked through the router's own gate computation."""
    ffn = MoEFeedForward(num_experts=4, token_dim=8, top_k=2, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 8)), jnp.float32)
    variables = ffn.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    # Reconstruct the gate path exactly as the module computes it.
    kernel = variables["params"]["router"]["kernel"]
    bias = variables["params"]["router"]["bias"]
    gates = jax.nn.softmax(x @ kernel + bias, axis=-1)
    _, top_idx = jax.lax.top_k(gates, 2)
    mask = jax.nn.one_hot(top_idx, 4).sum(-2)
    weights = gates * mask
    weights = weights / (weights.sum(-1, keepdims=True) + 1e-9)
    assert np.allclose(np.asarray(weights.sum(-1)), 1.0, atol=1e-5)
    assert int((np.asarray(weights) > 0).sum(-1).max()) <= 2
    # And the module's forward is finite with those weights in play.
    out = ffn.apply(variables, x, train=False)
    assert out.shape == x.shape
    assert bool(jnp.isfinite(out).all())


def test_aux_loss_sown_only_in_train_mode():
    config = ModelConfig(family="moe", token_dim=32, depth=2, heads=4, num_experts=4)
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    cat, num, _ = _batch()
    _, aux = model.apply(
        variables,
        cat,
        num,
        train=True,
        rngs={"dropout": jax.random.PRNGKey(1)},
        mutable=["aux_losses"],
    )
    leaves = jax.tree_util.tree_leaves(aux)
    assert len(leaves) == 2  # one load-balance term per block
    # Switch LB loss is ~aux_weight for near-uniform routing, >= that bound
    # in general (Cauchy-Schwarz: E * sum(imp*load) >= 1 when imp == load).
    assert all(float(jnp.mean(leaf)) > 0 for leaf in leaves)
    _, aux_eval = model.apply(variables, cat, num, train=False, mutable=["aux_losses"])
    assert not jax.tree_util.tree_leaves(aux_eval)


def test_training_loss_includes_aux():
    config = ModelConfig(
        family="moe", token_dim=32, depth=1, heads=4, num_experts=4, dropout=0.0
    )
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    cat, num, lab = _batch()
    from mlops_tpu.train.loop import sigmoid_bce

    logits, aux = model.apply(
        variables,
        cat,
        num,
        train=True,
        rngs={"dropout": jax.random.PRNGKey(1)},
        mutable=["aux_losses"],
    )
    expect = float(sigmoid_bce(logits, lab)) + sum(
        float(jnp.mean(leaf)) for leaf in jax.tree_util.tree_leaves(aux)
    )
    got = float(
        training_loss(model, variables["params"], cat, num, lab, jax.random.PRNGKey(1))
    )
    assert abs(got - expect) < 1e-5


def test_expert_axis_shards_over_model():
    config = ModelConfig(family="moe", token_dim=32, depth=1, heads=4, num_experts=8)
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    mesh = make_mesh(8, model_parallel=2)
    shardings = param_shardings(mesh, variables["params"])
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }
    w_in = [s.spec for name, s in flat.items() if name.endswith("experts_in")]
    assert w_in and all(spec[0] == "model" for spec in w_in)
    b_in = [s.spec for name, s in flat.items() if name.endswith("experts_in_bias")]
    assert b_in and all(spec[0] == "model" for spec in b_in)


def test_sharded_train_step_runs_with_moe():
    """EP composes with the DP/TP step: experts sharded over 'model',
    batch over 'data', one step yields a finite loss."""
    config = ModelConfig(
        family="moe",
        token_dim=32,
        depth=1,
        heads=4,
        num_experts=4,
        dropout=0.0,
        precision="f32",
    )
    tconfig = TrainConfig(batch_size=32, steps=1, learning_rate=1e-3)
    model = build_model(config)
    variables = init_params(model, jax.random.PRNGKey(0))
    optimizer = make_optimizer(tconfig)
    mesh = make_mesh(8, model_parallel=2)
    step_fn, _ = make_sharded_train_step(
        model, optimizer, tconfig, mesh, variables["params"]
    )
    state = TrainState(
        params=variables["params"],
        opt_state=optimizer.init(variables["params"]),
        step=jnp.asarray(0, jnp.int32),
        rng=jax.random.PRNGKey(1),
    )
    cat, num, lab = _batch(32)
    new_state, loss = step_fn(state, cat, num, lab, jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 1


# Heaviest end-to-end path (~60s serial on CPU): excluded from the
# timed tier-1 gate; CI's parallel pytest job still runs it.
@pytest.mark.slow
def test_moe_trains_end_to_end_and_serves(tmp_path):
    """Tiny MoE through the full pipeline: train -> bundle -> engine."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.config import Config
    from mlops_tpu.schema import LoanApplicant
    from mlops_tpu.serve.engine import InferenceEngine
    from mlops_tpu.train.pipeline import run_training

    config = Config()
    config.data.rows = 2000
    config.model = ModelConfig(
        family="moe", token_dim=16, depth=1, heads=2, num_experts=2
    )
    config.train = TrainConfig(steps=30, eval_every=30, batch_size=256)
    config.registry.root = str(tmp_path / "registry")
    config.registry.run_root = str(tmp_path / "runs")
    result = run_training(config, register=False)
    assert np.isfinite(result.train_result.metrics["validation_roc_auc_score"])
    bundle = load_bundle(result.bundle_dir)
    engine = InferenceEngine(bundle, buckets=(1, 8), enable_grouping=False)
    engine.warmup()
    out = engine.predict_records([LoanApplicant().model_dump()])
    assert 0.0 <= out["predictions"][0] <= 1.0
