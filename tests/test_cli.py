"""CLI surface: parser/handler sync, help smoke, small util units."""

import numpy as np

from mlops_tpu.cli import build_parser, main
from mlops_tpu.utils.timing import percentile


def test_every_subcommand_has_a_handler_and_vice_versa():
    """cli.py's subparser list and commands._HANDLERS are edited in two
    places; they must never drift (a listed command without a handler
    exits 'not implemented', a handler without a listing is unreachable)."""
    from mlops_tpu.commands import _HANDLERS

    parser = build_parser()
    sub = next(
        a for a in parser._actions if a.__class__.__name__ == "_SubParsersAction"
    )
    listed = set(sub.choices)
    assert listed == set(_HANDLERS), (
        f"parser-only: {listed - set(_HANDLERS)}; "
        f"handler-only: {set(_HANDLERS) - listed}"
    )


def test_no_args_prints_help_and_exits_nonzero(capsys):
    assert main([]) == 1
    assert "mlops-tpu" in capsys.readouterr().out


def test_percentile_matches_numpy_nearest_rank():
    """percentile() is nearest-rank by contract — compare against numpy's
    inverted_cdf method (its nearest-rank), not the interpolating default."""
    rng = np.random.default_rng(0)
    for n in (1, 7, 500, 501):
        values = sorted(rng.normal(size=n).tolist())
        for q in (0, 25, 50, 75, 90, 99, 100):
            ours = percentile(values, q)
            ref = float(np.percentile(values, q, method="inverted_cdf"))
            assert ours == ref, (n, q)
    assert percentile([42.0], 50) == 42.0
