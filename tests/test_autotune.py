"""gridtuner (mlops_tpu/autotune/): cost model, grid search, hot regrid.

Three layers, cheapest first: jax-free units over the cost model and the
exact DP search (including the plan-coverage PROPERTY — every plan warms
a bucket for 100% of the observed shape histogram, so a regrid can never
introduce a hot-path compile), controller tick semantics on a stub
engine, then the real-engine hot-regrid path (warm -> twin -> swap ->
rollback) on the shared tiny pipeline bundle.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from mlops_tpu.autotune import (
    AutotuneController,
    CostModel,
    GridPlan,
    apply_plan,
    demand_from_shapes,
    fit_cost_model,
    ledger_rows_from_snapshot,
    search_plan,
    warm_plan,
)
from mlops_tpu.autotune.costmodel import (
    MEASURED_OVERHEAD_FRACTION,
    demand_from_spans,
)
from mlops_tpu.autotune.search import score_grid
from mlops_tpu.config import AutotuneConfig, AutotuneConfigError
from mlops_tpu.trace.shapes import ShapeStats


def _rows(points):
    """(size, mean_dispatch_s, dispatches) -> ledger report rows."""
    return [
        {
            "entry": f"bucket_{size}",
            "device_s": cost * weight,
            "dispatches": weight,
            "rows": size * weight,
            "padded_rows": size * weight,
        }
        for size, cost, weight in points
    ]


# ------------------------------------------------------------ cost model
def test_fit_recovers_affine_coefficients():
    # Exact affine data: a=2ms overhead, b=10us/padded-row.
    a, b = 2e-3, 1e-5
    model = fit_cost_model(
        _rows([(s, a + b * s, 100.0) for s in (1, 8, 64, 256)])
    )
    assert model is not None and model.mode == "affine-fit"
    assert model.a_s == pytest.approx(a, rel=1e-9)
    assert model.b_s == pytest.approx(b, rel=1e-9)
    assert model.dispatch_s(128) == pytest.approx(a + b * 128)


def test_fit_single_point_measured_affine_split():
    model = fit_cost_model(_rows([(64, 4e-3, 50.0)]))
    assert model is not None and model.mode == "measured-affine"
    assert model.points == 1
    assert model.a_s == pytest.approx(4e-3 * MEASURED_OVERHEAD_FRACTION)
    # The split preserves the measured absolute cost at the observed size.
    assert model.dispatch_s(64) == pytest.approx(4e-3)


def test_fit_nonphysical_slope_degrades_to_measured_affine():
    # Bigger buckets measured CHEAPER (noise): optimizing that slope
    # would reward maximal padding — the fit must refuse.
    model = fit_cost_model(_rows([(1, 5e-3, 10.0), (256, 1e-3, 10.0)]))
    assert model is not None and model.mode == "measured-affine"
    assert model.b_s > 0 and model.a_s >= 0


def test_fit_holds_without_solo_observations():
    assert fit_cost_model([]) is None
    assert fit_cost_model(
        [{"entry": "group_8x8", "device_s": 1.0, "dispatches": 10.0,
          "rows": 100.0, "padded_rows": 640.0}]
    ) is None


def test_ledger_snapshot_folds_model_tags():
    rows = ledger_rows_from_snapshot(
        {
            "bucket_8@abc123": [1.0, 10.0, 60.0, 80.0],
            "bucket_8@def456": [3.0, 30.0, 180.0, 240.0],
            "group_8x8": [1.0, 1.0, 8.0, 64.0],
        }
    )
    by_entry = {r["entry"]: r for r in rows}
    assert by_entry["bucket_8"]["dispatches"] == 40.0
    assert by_entry["bucket_8"]["device_s"] == 4.0
    assert by_entry["group_8x8"]["rows"] == 8.0


# ---------------------------------------------------------------- demand
def test_demand_from_shapes_mass_matches_requested_counters():
    stats = ShapeStats()
    rng = np.random.default_rng(3)
    total_requested = total_dispatches = 0
    for _ in range(500):
        n = int(rng.integers(1, 65))
        padded = 8 if n <= 8 else 64
        stats.observe(f"bucket_{padded}", n, padded)
        total_requested += n
        total_dispatches += 1
    demand = demand_from_shapes(stats.snapshot())
    assert sum(w for _, w in demand) == pytest.approx(total_dispatches)
    # The histogram bounds granularity; the rescale pins the mass to the
    # exact requested counter (per-point integer rounding is the only
    # slack left).
    mass = sum(r * w for r, w in demand)
    assert mass == pytest.approx(total_requested, rel=0.02)
    # Group entries never contribute (fixed geometry).
    stats.observe("group_8x8", 5, 64)
    assert demand_from_shapes(stats.snapshot()) == demand


def test_demand_from_spans_exact_rows():
    spans = [
        {"entry": "bucket_8", "rows": 3},
        {"entry": "bucket_8", "rows": 3},
        {"entry": "bucket_64", "rows": 40},
        {"entry": "group_8x8", "rows": 5},  # grouped: excluded
        {"entry": "bucket_8", "rows": 0},  # malformed: excluded
    ]
    assert demand_from_spans(spans) == [(3, 2.0), (40, 1.0)]


# ---------------------------------------------------------------- search
MODEL = CostModel(a_s=2e-3, b_s=1e-5, points=4, mode="affine-fit")


def test_search_beats_hand_picked_grid_on_skewed_trace():
    # The acceptance trace: heavily skewed small-batch demand on a
    # hand-picked (1, 8, 64, 256) grid — almost everything dispatches at
    # 8 or 64 rows while asking for 3 or 12.
    demand = [(3, 900.0), (12, 80.0), (200, 15.0), (256, 5.0)]
    # Padding-dominated economics (per-row cost well above overhead at
    # the observed sizes) — the regime where grid choice actually pays.
    model = CostModel(a_s=1e-3, b_s=1e-4, points=4, mode="affine-fit")
    plan = search_plan(demand, model, (1, 8, 64, 256), max_entries=16)
    assert plan.predicted_rows_per_s > plan.baseline_rows_per_s
    assert plan.predicted_gain_pct > 5.0
    assert plan.predicted_waste_pct < plan.baseline_waste_pct
    # The searched buckets sit ON the demand sizes (the DP's optimality
    # argument) and keep the live ceiling.
    assert set(plan.buckets) <= {3, 12, 200, 256}
    assert plan.buckets[-1] == 256


@pytest.mark.parametrize("seed", range(8))
def test_plan_covers_every_observed_shape(seed):
    """THE coverage property: every demand size (clamped to the live
    ceiling, which the plan must keep) has a bucket >= it — so warming
    exactly the plan's entries leaves NO observed shape to compile on
    the hot path after the swap."""
    rng = np.random.default_rng(seed)
    stats = ShapeStats()
    ceiling = int(rng.choice([64, 256, 1024]))
    for _ in range(int(rng.integers(50, 400))):
        n = int(
            min(np.exp(rng.uniform(0, np.log(ceiling))), ceiling)
        )
        padded = min(
            next(b for b in (1, 8, 64, 256, 1024) if b >= n), ceiling
        )
        stats.observe(f"bucket_{padded}", n, padded)
    demand = demand_from_shapes(stats.snapshot())
    max_entries = int(rng.integers(2, 17))
    plan = search_plan(demand, MODEL, (1, 8, ceiling), max_entries)
    assert len(plan.buckets) <= max_entries
    assert plan.buckets[-1] == ceiling  # the ceiling never shrinks
    for rows, _ in demand:
        clamped = min(rows, ceiling)
        assert any(b >= clamped for b in plan.buckets), (
            f"demand size {clamped} uncovered by {plan.buckets}"
        )
    # The live grid is inside the searched space, so the optimum never
    # loses to it.
    assert plan.predicted_gain_pct >= -1e-9


def test_score_grid_accounting():
    rate, waste = score_grid((8,), [(2, 10.0)], MODEL)
    # 10 dispatches of 2 useful rows padded to 8.
    assert rate == pytest.approx(20.0 / (10 * MODEL.dispatch_s(8)))
    assert waste == pytest.approx(100.0 * (80 - 20) / 80)


def test_plan_dict_round_trip():
    plan = search_plan([(3, 10.0)], MODEL, (1, 8), 4)
    doc = json.loads(json.dumps(plan.as_dict()))
    assert GridPlan.from_dict(doc) == plan
    assert doc["format"] == 1


# ---------------------------------------------------------------- config
def test_autotune_config_validates():
    AutotuneConfig().validate()
    with pytest.raises(AutotuneConfigError, match="interval_s"):
        AutotuneConfig(interval_s=0).validate()
    with pytest.raises(AutotuneConfigError, match="max_entries"):
        AutotuneConfig(max_entries=1).validate()
    with pytest.raises(AutotuneConfigError, match="plan_dir"):
        AutotuneConfig(enabled=True, plan_dir="").validate()


# ------------------------------------------------------------ controller
class _StubLedger:
    def __init__(self):
        self.entries = {}

    def snapshot(self):
        return {k: list(v) for k, v in self.entries.items()}


class _StubEngine:
    monitor_accumulating = True

    def __init__(self, buckets=(1, 8, 64, 256)):
        self.buckets = tuple(buckets)
        self.grid_generation = 0
        self.bundle_generation = 0
        self.shape_stats = ShapeStats()
        self.cost_ledger = _StubLedger()
        self.rolled_back = 0

    def rollback(self):
        self.rolled_back += 1
        self.grid_generation += 1

    def feed(self, demand, model=MODEL, ledger=True):
        for rows, weight in demand:
            padded = next(
                (b for b in self.buckets if b >= rows), self.buckets[-1]
            )
            for _ in range(int(weight)):
                self.shape_stats.observe(f"bucket_{padded}", rows, padded)
        if ledger:
            self.seed_ledger(model)

    def seed_ledger(self, model=MODEL):
        for b in self.buckets:
            self.cost_ledger.entries.setdefault(
                f"bucket_{b}",
                [model.dispatch_s(b) * 100, 100.0, b * 100.0, b * 100.0],
            )


def _config(tmp_path, **kw):
    kw.setdefault("plan_dir", str(tmp_path / "autotune"))
    kw.setdefault("min_dispatches", 10)
    return AutotuneConfig(enabled=True, **kw).validate()


def test_controller_holds_then_plans_dry_run(tmp_path):
    engine = _StubEngine()
    controller = AutotuneController(
        engine, _config(tmp_path, apply=False, min_gain_pct=1.0)
    )
    assert controller.run_once(now=0.0) == "held: 0 dispatches < min"
    engine.feed([(3, 900.0), (200, 20.0)])
    status = controller.run_once(now=1.0)
    assert status.startswith("planned (dry-run)")
    doc = json.loads((tmp_path / "autotune" / "plan.json").read_text())
    assert doc["applied"] is False and doc["buckets"][-1] == 256
    snap = controller.metrics_snapshot()
    assert snap["plans"]["planned"] == 1
    assert snap["predicted_gain_pct"] > 1.0
    assert snap["grid_generation"] == 0


def test_controller_disarmed_without_telemetry(tmp_path):
    engine = _StubEngine()
    engine.shape_stats = None
    controller = AutotuneController(engine, _config(tmp_path))
    assert controller.run_once(now=0.0) == "disarmed"


def test_controller_rejects_subthreshold_gains(tmp_path):
    engine = _StubEngine()
    engine.feed([(3, 900.0), (200, 20.0)])
    controller = AutotuneController(
        engine, _config(tmp_path, min_gain_pct=1e6)
    )
    status = controller.run_once(now=0.0)
    assert status.startswith("rejected: gain")
    assert controller.metrics_snapshot()["plans"]["rejected"] == 1


def test_controller_applies_then_cools_down(tmp_path, monkeypatch):
    engine = _StubEngine()
    engine.feed([(3, 900.0), (200, 20.0)])
    applied = []

    def fake_apply(eng, buckets, workers=0):
        applied.append(tuple(buckets))
        eng.buckets = tuple(buckets)
        eng.grid_generation += 1
        return eng.grid_generation

    monkeypatch.setattr("mlops_tpu.autotune.apply.apply_plan", fake_apply)
    controller = AutotuneController(
        engine, _config(tmp_path, min_gain_pct=1.0, cooldown_s=100.0)
    )
    status = controller.run_once(now=0.0)
    assert status == "applied: grid_generation=1"
    assert applied and applied[0][-1] == 256
    # Cooldown: the audit window must observe the new grid first.
    assert controller.run_once(now=50.0) == "cooling"
    assert controller.run_once(now=200.0) != "cooling"
    doc = json.loads((tmp_path / "autotune" / "plan.json").read_text())
    assert doc["applied"] is True and doc["grid_generation"] == 1


def test_sibling_adopts_leads_applied_plan(tmp_path, monkeypatch):
    lead_engine = _StubEngine()
    lead_engine.feed([(3, 900.0), (200, 20.0)])

    def fake_apply(eng, buckets, workers=0):
        eng.buckets = tuple(buckets)
        eng.grid_generation += 1
        return eng.grid_generation

    monkeypatch.setattr("mlops_tpu.autotune.apply.apply_plan", fake_apply)
    config = _config(tmp_path, min_gain_pct=1.0)
    lead = AutotuneController(lead_engine, config)
    assert lead.run_once(now=0.0).startswith("applied")

    sibling_engine = _StubEngine()
    sibling = AutotuneController(
        sibling_engine, config, adopt=True, replica=1
    )
    status = sibling.run_once(now=0.0)
    assert status == "adopted: grid_generation=1"
    assert sibling_engine.buckets == lead_engine.buckets
    # Idempotent: the same plan generation never re-applies.
    assert sibling.run_once(now=1.0) == "adopt: current"


def test_adopt_without_plan_is_a_noop(tmp_path):
    sibling = AutotuneController(
        _StubEngine(), _config(tmp_path), adopt=True, replica=1
    )
    assert sibling.run_once(now=0.0) == "adopt: no plan"


def test_controller_rollback_counts_and_restores(tmp_path):
    engine = _StubEngine()
    controller = AutotuneController(engine, _config(tmp_path))
    status = controller.rollback()
    assert status == "rolled_back: grid_generation=1"
    assert engine.rolled_back == 1
    assert controller.metrics_snapshot()["plans"]["rolled_back"] == 1


def test_measured_gain_audit_from_ledger_deltas(tmp_path, monkeypatch):
    engine = _StubEngine()

    def fake_apply(eng, buckets, workers=0):
        eng.buckets = tuple(buckets)
        eng.grid_generation += 1
        return eng.grid_generation

    monkeypatch.setattr("mlops_tpu.autotune.apply.apply_plan", fake_apply)
    controller = AutotuneController(
        engine, _config(tmp_path, min_gain_pct=1.0, cooldown_s=0.0)
    )
    # Tick 0 (held: no demand yet) captures the ledger totals; the next
    # window's delta is then exactly the rows/seconds added below.
    engine.seed_ledger()
    controller.run_once(now=0.0)
    engine.feed([(3, 900.0), (200, 20.0)], ledger=False)
    ledger = engine.cost_ledger.entries
    ledger["bucket_8"][0] += 1.0  # +1 device-second
    ledger["bucket_8"][2] += 500.0  # +500 useful rows
    assert controller.run_once(now=1.0).startswith("applied")
    # Post-apply window at double the rate; tick 3 is rejected (already
    # on the plan grid) so it measures WITHOUT resetting the audit.
    ledger["bucket_8"][0] += 1.0
    ledger["bucket_8"][2] += 1000.0
    assert controller.run_once(now=2.0).startswith("rejected")
    snap = controller.metrics_snapshot()
    assert snap["measured_gain_pct"] == pytest.approx(100.0, rel=0.01)


def test_warm_plan_refuses_non_accumulating_engine():
    class _Sklearn:
        monitor_accumulating = False

    with pytest.raises(ValueError, match="flax"):
        warm_plan(_Sklearn(), (1, 8))


# ------------------------------------------------------- real-engine path
@pytest.fixture(scope="module")
def regrid_engine(tiny_pipeline):
    """A private engine the regrid tests MAY mutate (warm_engine is the
    shared read-only one)."""
    from mlops_tpu.bundle import load_bundle
    from mlops_tpu.serve.engine import InferenceEngine

    _, result = tiny_pipeline
    engine = InferenceEngine(
        load_bundle(result.bundle_dir), buckets=(1, 8), enable_grouping=False
    )
    engine.warmup()
    return engine


def test_hot_regrid_swap_and_rollback(regrid_engine, sample_request):
    engine = regrid_engine
    request = sample_request * 2  # 2 rows: pads to 8 now, to 2 after
    before = engine.predict_records(request)
    gen0 = engine.grid_generation
    new_gen = apply_plan(engine, (1, 2, 8))
    assert new_gen == gen0 + 1
    assert tuple(engine.buckets) == (1, 2, 8)
    with engine._compile_lock:
        assert ("bucket", 2) in engine._exec
    # Bit-stable across the regrid: same request, same floats, even
    # though it now dispatches through the new bucket_2 entry.
    after = engine.predict_records(request)
    assert after["predictions"] == pytest.approx(
        before["predictions"], abs=1e-6
    )
    engine.rollback()
    assert tuple(engine.buckets) == (1, 8)
    assert engine.grid_generation == gen0 + 2
    restored = engine.predict_records(request)
    assert restored["predictions"] == pytest.approx(
        before["predictions"], abs=1e-6
    )


def test_regrid_never_shrinks_the_ceiling(regrid_engine):
    with pytest.raises(ValueError, match="max_bucket"):
        apply_plan(regrid_engine, (1, 4))


def test_regrid_aborts_when_promotion_races_warm(
    regrid_engine, monkeypatch
):
    from mlops_tpu.autotune.apply import RegridAborted

    def racing_warm(engine, buckets, workers=0):
        engine.bundle_generation += 1  # a promotion landed mid-warm
        return 0

    monkeypatch.setattr("mlops_tpu.autotune.apply.warm_plan", racing_warm)
    generation = regrid_engine.grid_generation
    with pytest.raises(RegridAborted):
        apply_plan(regrid_engine, (1, 2, 8))
    assert regrid_engine.grid_generation == generation  # no swap happened


# ----------------------------------------------------- bench key contract
def test_bench_autotune_stage_key_contract(tiny_pipeline, sample_request):
    """BENCH_r10+ rounds carry the gridtuner keys: the measured goodput
    gain of the autotuned grid over the hand grid on the skewed trace,
    the hammer-observed swap downtime, and the plan's own prediction
    (so every committed round carries the predicted-vs-measured audit).
    Runs the REAL stage — its engine is private, so the shared fixtures
    are untouched."""
    import bench
    from mlops_tpu.bundle import load_bundle

    _, result = tiny_pipeline
    out = bench._autotune_stage(
        load_bundle(result.bundle_dir), sample_request[0]
    )
    assert set(out) >= {
        "autotune_goodput_gain_pct",
        "regrid_downtime_ms",
        "autotune_predicted_gain_pct",
        "autotune_buckets",
        "autotune_baseline_waste_pct",
        "autotune_waste_pct",
    }
    assert out["regrid_downtime_ms"] >= 0.0
    # The incumbent grid is inside the searched space, so the plan's
    # own claim is non-negative by construction.
    assert out["autotune_predicted_gain_pct"] >= 0.0
    assert out["autotune_buckets"][-1] == 4096  # ceiling never shrinks
